"""mxnet_tpu — a TPU-native deep-learning framework with MXNet's capabilities.

From-scratch rebuild of Apache MXNet 0.11.1's API surface and semantics
(reference at /root/reference) on a JAX/XLA/Pallas execution model: eager
NDArray ops dispatch through cached jit closures, Symbol.bind compiles whole
graphs into single XLA computations, KVStore lowers to mesh collectives.
See SURVEY.md for the layer map this follows.
"""
from .libinfo import __version__  # noqa: F401  (single version source)

from . import base
from . import libinfo
from . import log
from . import name
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, num_gpus
from . import ndarray
from . import ndarray as nd
from . import random
from .random import seed  # noqa: F401
from . import autograd
from . import engine
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from . import attribute
from .attribute import AttrScope
from . import executor
from . import initializer
from . import initializer as init  # mx.init.Xavier() etc. (reference alias)
from . import optimizer
from . import optimizer as opt
from . import lr_scheduler
from . import metric
from . import callback
from . import io
from . import image
from . import recordio
from . import kvstore
from . import kvstore as kv
from . import model
from .model import save_checkpoint, load_checkpoint
from . import module
from . import module as mod
from . import rnn
from . import gluon
from . import monitor
from . import monitor as mon  # reference __init__.py:62 alias
from .monitor import Monitor
from . import profiler
from . import visualization
from . import visualization as viz
from . import test_utils
from . import registry
from .executor_manager import DataParallelExecutorManager  # noqa: F401
from . import operator
from .operator import CustomOp, CustomOpProp
from . import rtc
from . import contrib
from . import plugin
from . import parallel
from . import telemetry

# Decide telemetry at import so the jax.monitoring compile listener is
# installed before the process's FIRST compile (a fit run must log its
# warmup compiles too). With MXTPU_TELEMETRY unset this is one cached
# flag read and nothing else.
telemetry.enabled()

# Persistent XLA compilation cache (MXTPU_COMPILE_CACHE): wired at
# import, before the first compile, so warm starts skip the 20-40s
# XLA compiles entirely. Off (empty) by default — one flag read.
from .config import enable_compile_cache as _enable_compile_cache
_enable_compile_cache()
del _enable_compile_cache

# Server/scheduler processes block in their role loop here and exit with the
# job (reference python/mxnet/kvstore_server.py:75).
from .kvstore_server import init_server_module_if_needed as _init_kv_server
_init_kv_server()
del _init_kv_server
