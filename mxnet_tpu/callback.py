"""Training callbacks.

Reference: python/mxnet/callback.py (214 LoC): module_checkpoint,
do_checkpoint, log_train_metric, Speedometer, ProgressBar,
LogValidationMetricsCallback.
"""
import logging
import math
import sys
import time

from . import telemetry as _tele

__all__ = ['Speedometer', 'do_checkpoint', 'module_checkpoint',
           'log_train_metric', 'ProgressBar', 'LogValidationMetricsCallback']


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Reference callback.py:55."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info('Iter[%d] Batch[%d] Train-%s=%f',
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """Logs samples/sec every `frequent` batches (the role of reference
    callback.py's Speedometer; the `Speed:` line format is pinned —
    downstream scripts and the compat tests parse it).

    Implemented as a rolling measurement window: the window opens on
    the first batch of an epoch (a rewinding batch counter re-opens
    it), and every time the batch counter lands on a multiple of
    `frequent` the window's throughput is reported and a fresh window
    opens.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._window_open = None   # wall-clock when the window opened
        self._prev_batch = None

    def __call__(self, param):
        now = time.time()
        rewound = (self._prev_batch is not None
                   and param.nbatch < self._prev_batch)
        self._prev_batch = param.nbatch
        if self._window_open is None or rewound:
            self._window_open = now
            return
        if param.nbatch % self.frequent:
            return
        speed = self.frequent * self.batch_size / (now - self._window_open)
        # telemetry mirror of the measurement (no-op when telemetry is
        # off); the pinned `Speed:` log-line format below is unchanged
        _tele.gauge('speedometer.samples_per_sec').set(round(speed, 2))
        metric = param.eval_metric
        if metric is None:
            logging.info('Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec',
                         param.epoch, param.nbatch, speed)
        else:
            pairs = metric.get_name_value()
            if self.auto_reset:
                metric.reset()
            fields = [param.epoch, param.nbatch, speed]
            for name, value in pairs:
                fields.extend((name, value))
            logging.info('Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec'
                         + '\t%s=%f' * len(pairs), *fields)
        self._window_open = time.time()


class ProgressBar:
    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = '=' * filled_len + '-' * (self.bar_len - filled_len)
        sys.stdout.write('[%s] %s%s\r' % (prog_bar, percents, '%'))


class LogValidationMetricsCallback:
    """Epoch-end eval logger; the `Validation-` line format is pinned
    (parsed by downstream scripts, so only the internals differ from
    the reference's)."""

    def __call__(self, param):
        metric = param.eval_metric
        if metric is None:
            return
        for name, value in metric.get_name_value():
            logging.info('Epoch[%d] Validation-%s=%f',
                         param.epoch, name, value)
