"""RecordIO — packed record format + image pack/unpack helpers.

Reference: python/mxnet/recordio.py (456 LoC: MXRecordIO, MXIndexedRecordIO,
IRHeader pack/unpack/pack_img/unpack_img) and dmlc-core's recordio stream
(magic-delimited records) used by src/io/iter_image_recordio*.cc.

Binary layout per record (dmlc recordio): uint32 magic 0xced7230a,
uint32 lrecord (upper 3 bits cflag, lower 29 bits length), payload,
padded to 4-byte boundary. Image records carry an IRHeader
(uint32 flag, float32 label, uint64 id, uint64 id2) before the payload.
"""
import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from . import _native

__all__ = ['MXRecordIO', 'MXIndexedRecordIO', 'IRHeader', 'pack', 'unpack',
           'pack_img', 'unpack_img']

_kMagic = 0xced7230a

IRHeader = namedtuple('HeaderType', ['flag', 'label', 'id', 'id2'])
_IR_FORMAT = 'IfQQ'
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential record reader/writer (reference recordio.py:28)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.is_open = False
        self.open()

    def open(self):
        lib = _native.get_lib()
        if self.flag == 'w':
            self.writable = True
        elif self.flag == 'r':
            self.writable = False
        else:
            raise ValueError('Invalid flag %s' % self.flag)
        if lib is not None:
            # native reader/writer (src/recordio.cc)
            self._lib = lib
            self._nh = ctypes.c_void_p()
            create = (lib.MXTRecordIOWriterCreate if self.writable
                      else lib.MXTRecordIOReaderCreate)
            _native.check_call(create(self.uri.encode(),
                                      ctypes.byref(self._nh)))
            self.handle = None
        else:
            self._lib = None
            self._nh = None
            self.handle = open(self.uri, 'wb' if self.writable else 'rb')
        self.pid = os.getpid()
        self.is_open = True

    def close(self):
        if self.is_open:
            if self._nh is not None:
                free = (self._lib.MXTRecordIOWriterFree if self.writable
                        else self._lib.MXTRecordIOReaderFree)
                _native.check_call(free(self._nh))
                self._nh = None
            else:
                self.handle.close()
            self.is_open = False
            self.pid = None

    def reset(self):
        self.close()
        self.open()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d['handle'] = None
        d['_lib'] = None
        d['_nh'] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        if self.is_open:
            self.is_open = False
            self.open()

    def write(self, buf):
        assert self.writable
        if self._nh is not None:
            _native.check_call(self._lib.MXTRecordIOWriterWrite(
                self._nh, bytes(buf), len(buf)))
            return
        length = len(buf)
        if length >= 1 << 29:
            raise ValueError('RecordIO only accepts records < 2^29 bytes')
        buf = bytes(buf)
        # dmlc magic-escape: the payload is split at 4-aligned occurrences
        # of the magic word (dropped on write, re-inserted on read) so a
        # reader can always resync on magic. cflag: 0=whole, 1=begin,
        # 2=middle, 3=end (upper 3 bits of lrecord).
        lower = (length >> 2) << 2
        hits = np.flatnonzero(
            np.frombuffer(buf[:lower], dtype='<u4') == _kMagic) * 4
        if len(hits) == 0:
            self._write_chunk(0, buf)
            return
        dptr = 0
        for j, i in enumerate(hits):
            self._write_chunk(1 if j == 0 else 2, buf[dptr:i])
            dptr = int(i) + 4
        self._write_chunk(3, buf[dptr:])

    def _write_chunk(self, cflag, data):
        self.handle.write(struct.pack(
            '<II', _kMagic, (cflag << 29) | (len(data) & 0x1fffffff)))
        self.handle.write(data)
        pad = (4 - len(data) % 4) % 4
        if pad:
            self.handle.write(b'\x00' * pad)

    def read(self):
        assert not self.writable
        if self._nh is not None:
            out = ctypes.c_void_p()
            ln = ctypes.c_size_t()
            _native.check_call(self._lib.MXTRecordIOReaderNext(
                self._nh, ctypes.byref(out), ctypes.byref(ln)))
            if ln.value == ctypes.c_size_t(-1).value:
                return None
            return ctypes.string_at(out, ln.value) if ln.value else b''
        got = self._read_chunk()
        if got is None:
            return None
        cflag, buf = got
        if cflag == 0:
            return buf
        if cflag != 1:
            raise IOError('RecordIO stream begins mid multi-part record')
        out = bytearray(buf)
        magic_bytes = struct.pack('<I', _kMagic)
        while True:
            got = self._read_chunk()
            if got is None:
                raise IOError('truncated multi-part RecordIO record')
            cflag, buf = got
            if cflag not in (2, 3):
                raise IOError('bad continuation flag %d' % cflag)
            out += magic_bytes + buf
            if cflag == 3:
                return bytes(out)

    def _read_chunk(self):
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack('<II', head)
        if magic != _kMagic:
            raise IOError('Invalid RecordIO magic number')
        length = lrec & 0x1fffffff
        buf = self.handle.read(length)
        if len(buf) < length:
            # full header but short payload (writer killed mid-record):
            # corrupt, not clean EOF — match the native reader's error
            raise IOError('truncated RecordIO record')
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return lrec >> 29, buf

    def tell(self):
        if self._nh is not None:
            out = ctypes.c_size_t()
            fn = (self._lib.MXTRecordIOWriterTell if self.writable
                  else self._lib.MXTRecordIOReaderTell)
            _native.check_call(fn(self._nh, ctypes.byref(out)))
            return out.value
        return self.handle.tell()

    def seek_pos(self, pos):
        assert not self.writable
        if self._nh is not None:
            _native.check_call(self._lib.MXTRecordIOReaderSeek(self._nh, pos))
        else:
            self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Keyed random access via a .idx sidecar (reference recordio.py:142)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == 'r' and os.path.exists(self.idx_path):
            with open(self.idx_path) as fidx:
                for line in fidx:
                    parts = line.strip().split('\t')
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.writable and self.idx:
            with open(self.idx_path, 'w') as fidx:
                for key in self.keys:
                    fidx.write('%s\t%d\n' % (str(key), self.idx[key]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.seek_pos(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack a header + byte payload (reference recordio.py:297)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, 0, float(header.label), header.id, header.id2)
        return hdr + s
    label = np.asarray(header.label, dtype=np.float32)
    hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id, header.id2)
    return hdr + label.tobytes() + s


def unpack(s):
    """Reference recordio.py:322."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt='.raw'):
    """Pack an image array. '.raw' stores uint8 CHW pixels + shape prefix
    (hermetic, no codec dependency); '.jpg'/'.png' require pillow."""
    img = np.asarray(img)
    if img_fmt == '.raw':
        shape = np.asarray(img.shape, dtype=np.int32)
        payload = b'RAW0' + struct.pack('<I', len(shape)) + shape.tobytes() + \
            img.astype(np.uint8).tobytes()
        return pack(header, payload)
    try:
        from PIL import Image
        import io as _io
    except ImportError:
        raise ImportError('pack_img with %s requires pillow; use .raw' % img_fmt)
    buf = _io.BytesIO()
    fmt = img_fmt.lstrip('.').upper()
    if fmt == 'JPG':
        fmt = 'JPEG'  # PIL registers only the long name
    Image.fromarray(img).save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1, data_shape=None):
    header, payload = unpack(s)
    if payload[:4] == b'RAW0':
        ndim = struct.unpack('<I', payload[4:8])[0]
        shape = np.frombuffer(payload[8:8 + 4 * ndim], dtype=np.int32)
        img = np.frombuffer(payload[8 + 4 * ndim:], dtype=np.uint8)
        img = img.reshape(tuple(shape))
    else:
        try:
            from PIL import Image
            import io as _io
            img = np.asarray(Image.open(_io.BytesIO(payload)))
            if img.ndim == 3:
                img = img.transpose(2, 0, 1)
        except ImportError:
            raise ImportError('JPEG/PNG decode requires pillow; '
                              'use .raw packed records')
    if data_shape is not None and tuple(img.shape) != tuple(data_shape):
        if img.ndim == 2 and len(data_shape) == 3 and data_shape[0] == 1:
            img = img[None]
        elif img.size == int(np.prod(data_shape)):
            img = img.reshape(data_shape)
    return header, img
