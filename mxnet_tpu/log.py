"""Logging helper (reference python/mxnet/log.py get_logger: a logger
with the reference's level-letter/timestamp format, to stderr or file).
"""
import logging
import sys

__all__ = ['get_logger']

_FORMAT = '%(asctime)s [%(levelname).1s] %(name)s: %(message)s'
_DATEFMT = '%m%d %H:%M:%S'


class _Formatter(logging.Formatter):
    def __init__(self):
        super().__init__(_FORMAT, _DATEFMT)


def get_logger(name=None, filename=None, filemode=None, level=logging.WARNING):
    """Get a configured logger (reference log.py:48). ``filename``
    routes to a file (mode ``filemode``, default 'a'); otherwise
    stderr. Repeated calls reconfigure the level only."""
    logger = logging.getLogger(name)
    if getattr(logger, '_mxtpu_init', False):
        logger.setLevel(level)
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or 'a')
    else:
        handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(_Formatter())
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxtpu_init = True
    return logger


# reference log.py exports the camelCase name as well
getLogger = get_logger
