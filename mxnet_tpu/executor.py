"""Executor — a bound, XLA-compiled symbol graph.

Reference: include/mxnet/executor.h:52-153 + src/executor/graph_executor.cc.
The reference's Init pipeline (InitFullGraph → gradient pass → AssignContext
→ PlanMemory → AttachOpExecs → InitCachedOps → bulk segments,
graph_executor.cc:917-1336) collapses here into tracing ONE pure function
over the argument arrays and letting jax.jit/XLA do gradient (via vjp),
scheduling, fusion, and memory planning (SURVEY.md §3.2 "TPU mapping").

Two execution modes:
- compiled (default): forward and forward+backward are each one jitted XLA
  computation. When is_train=True the forward is LAZY — Module's
  forward→backward sequence runs a single fused fwd+bwd computation.
- staged: used when group2ctx (manual model parallelism) or a monitor
  callback is active — per-node eager interpretation with device_put at
  ctx_group boundaries (reference AssignContext + _CrossDeviceCopy,
  graph_executor.cc:309-423) and per-op observability (ExecuteMonCallback,
  graph_executor.cc:1398).
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError, np_dtype
from .context import Context
from . import faults as _faults
from . import random as _random
from .ndarray.ndarray import NDArray, zeros as nd_zeros, from_jax
from .ops import registry as _reg

__all__ = ['Executor', 'simple_bind']


def mirror_wrap(f):
    """Gradient-memory tradeoff ≙ XLA rematerialization.

    Reference: MXNET_BACKWARD_DO_MIRROR (graph_executor.cc:273-287) marks
    cheap forward nodes for recompute in backward. Here the same knob is a
    jax.checkpoint policy applied to the whole traced forward:
      MXTPU_BACKWARD_DO_MIRROR=1     full remat (max memory saving)
      MXTPU_BACKWARD_DO_MIRROR=dots  keep matmul results, recompute the rest
                                     (closest to the reference's heuristic
                                     of mirroring everything but convolution
                                     and dot outputs)
    The legacy MXNET_ spelling is honored too. Loss and gradients are
    bit-identical either way — only the memory/time tradeoff changes.
    """
    from .config import flags as _flags
    _flags.reload('MXTPU_BACKWARD_DO_MIRROR')  # tests toggle it per-case
    val = _flags.get('MXTPU_BACKWARD_DO_MIRROR')
    if val in ('', '0', 'false', 'False'):
        return f
    if val == 'dots':
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        return jax.checkpoint(f, policy=policy)
    return jax.checkpoint(f)


def _align_head(g, sharding):
    """Move a head-gradient (cotangent) onto the primal's sharding if it
    arrived committed elsewhere — SequentialModule hands gradients
    across module device groups; the reference engine does this copy
    implicitly via cross-context dependency edges."""
    if getattr(g, 'sharding', None) == sharding:
        return g
    return jax.device_put(g, sharding)


def _entry_key(node, idx):
    return (id(node), idx)


class _GraphProgram:
    """Compiled form of a symbol: canonical input orders + a pure runner."""

    def __init__(self, symbol):
        self.symbol = symbol
        self.topo = symbol._topo()
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.outputs = list(symbol._outputs)
        self._aux_set = set(self.aux_names)
        # host ops (image codecs, legacy callback bridges) cannot be
        # traced; their presence forces the staged per-op path
        self.has_host_ops = any(not n.is_variable() and n.opdef().host
                                for n in self.topo)
        self.op_nodes = [n for n in self.topo if not n.is_variable()]
        self.topo_index = {n: i for i, n in enumerate(self.topo)}
        # per-node jax.named_scope names: device traces, HLO dumps and
        # profiler output attribute ops to the SYMBOL's layer names
        # instead of anonymous fusion.123 clusters
        from .telemetry.programs import scope_name
        self.scope_names = [scope_name(n.name) for n in self.topo]

    def make_runner(self):
        """Build run(arg_arrays, aux_arrays, key, is_train) ->
        (outputs, new_aux). Pure; jit-compiled by the executor."""
        topo = self.topo
        arg_index = {n: i for i, n in enumerate(self.arg_names)}
        aux_index = {n: i for i, n in enumerate(self.aux_names)}
        outputs = self.outputs

        scope_names = self.scope_names

        def run(arg_arrays, aux_arrays, key, is_train):
            env = {}
            new_aux = dict()
            for ni, node in enumerate(topo):
                if node.is_variable():
                    if node.name in aux_index:
                        env[_entry_key(node, 0)] = aux_arrays[aux_index[node.name]]
                    else:
                        env[_entry_key(node, 0)] = arg_arrays[arg_index[node.name]]
                    continue
                op = node.opdef()
                _reg.record(op)
                attrs = dict(node.attrs)
                if op.train_aware:
                    attrs['__is_train__'] = is_train
                ins = [env[_entry_key(p, i)] for p, i in node.inputs]
                if op.needs_rng:
                    ins.append(jax.random.fold_in(key, ni))
                # named_scope threads the symbol's layer name into the
                # HLO metadata of everything this node lowers to —
                # trace-time only, zero cost in the compiled program
                with jax.named_scope(scope_names[ni]):
                    if op.host:
                        # pure_callback bridge: host python at execution
                        # time, traceable (and differentiable via legacy
                        # backward)
                        outs = _reg.host_bridge(op, attrs)(*ins)
                    else:
                        outs = op.fn(attrs, *ins)
                if not isinstance(outs, (tuple, list)):
                    outs = (outs,)
                for i, o in enumerate(outs):
                    env[_entry_key(node, i)] = o
                # collect aux updates (BatchNorm moving stats)
                for in_idx, out_idx in op.mutate_inputs.items():
                    if in_idx < len(node.inputs):
                        src, _ = node.inputs[in_idx]
                        if src.is_variable() and src.name in aux_index:
                            new_aux[aux_index[src.name]] = outs[out_idx]
            out_arrays = tuple(env[_entry_key(n, i)] for n, i in outputs)
            aux_out = tuple(new_aux.get(i, aux_arrays[i])
                            for i in range(len(self.aux_names)))
            return out_arrays, aux_out

        return run


class Executor:
    """Reference executor.py:45 wrapper + graph_executor.cc in one."""

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req='write',
                 aux_states=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self._prog = _GraphProgram(symbol)
        self._group2ctx = group2ctx
        self._monitor = None
        self._monitor_all = False

        self.arg_arrays = self._canon_args(args, self._prog.arg_names, 'args')
        self.aux_arrays = self._canon_args(aux_states or [],
                                           self._prog.aux_names, 'aux_states')
        self.arg_dict = dict(zip(self._prog.arg_names, self.arg_arrays))
        self.aux_dict = dict(zip(self._prog.aux_names, self.aux_arrays))

        # grad bookkeeping
        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in self._prog.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(self._prog.arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, 'null')
                              for n in self._prog.arg_names}
        if args_grad is None:
            self.grad_arrays = [None] * len(self.arg_arrays)
        else:
            self.grad_arrays = self._canon_args(args_grad, self._prog.arg_names,
                                                'args_grad', allow_missing=True)
        self.grad_dict = {n: g for n, g in zip(self._prog.arg_names,
                                               self.grad_arrays)}
        self._grad_names = [n for n in self._prog.arg_names
                            if self._grad_req.get(n, 'null') != 'null'
                            and self.grad_dict.get(n) is not None]

        run = self._prog.make_runner()
        self._fwd = jax.jit(functools.partial(run), static_argnums=(3,))
        grad_idx = tuple(self._prog.arg_names.index(n) for n in self._grad_names)

        # training-health sentinels (telemetry/health): with
        # MXTPU_HEALTH=1 (and telemetry on) the fused fwd+bwd program
        # ALSO returns one packed stats vector — grad/param norms,
        # update ratio, per-output finite flags — computed on device
        # inside the same compiled step. Off: the trace is byte-
        # identical to the plain form (asserted by test_health.py).
        from .telemetry import health as _health
        from .telemetry import dynamics as _dynamics
        self._health_on = _health.enabled() and bool(self._grad_names)
        health_on = self._health_on
        # per-layer training dynamics (telemetry/dynamics): with
        # MXTPU_DYNAMICS=1 the fused fwd+bwd ALSO returns the packed
        # per-layer stats vector — it rides the same per-batch host
        # sync the health sentinel already pays. Off: byte-identical
        # trace (asserted by test_dynamics.py).
        self._dyn_on = _dynamics.enabled() and bool(self._grad_names)
        dyn_on = self._dyn_on
        self._out_names = list(symbol.list_outputs())

        def fwd_bwd(arg_arrays, aux_arrays, key, head_grads):
            def f(wrt):
                full = list(arg_arrays)
                for i, gi in enumerate(grad_idx):
                    full[gi] = wrt[i]
                outs, new_aux = run(tuple(full), aux_arrays, key, True)
                return outs, new_aux

            wrt = tuple(arg_arrays[gi] for gi in grad_idx)
            (outs, new_aux), vjp = jax.vjp(mirror_wrap(f), wrt)
            zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
            (grads,) = vjp((head_grads, zero_aux))
            rets = (outs, new_aux, grads)
            if health_on:
                rets += (_health.step_stats(outs, grads=grads,
                                            params=wrt),)
            if dyn_on:
                rets += (_dynamics.step_stats(outs, grads=grads,
                                              params=wrt),)
            return rets

        self._fwd_bwd = jax.jit(fwd_bwd)
        self._run_eager = run

        self.outputs_cached = None
        self._pending = None  # (arg jax arrays, aux jax arrays, key) for lazy train fwd
        self._partial = None  # partial_forward stepping state

        from . import telemetry as _tele
        if _tele.enabled():
            # cost attribution: route both compiles through the program
            # registrar — an explicit lower().compile() whose executable
            # yields XLA's cost/memory analysis (program.* gauges, the
            # per-program summary table). fwd_bwd is THE train step of
            # the per-batch loop, so its FLOPs feed the MFU estimate.
            gname = _tele.programs.scope_name(
                getattr(symbol, 'name', None) or 'graph')
            self._fwd = _tele.programs.register(
                'executor.fwd[%s]' % gname, self._fwd, static_argnums=(3,))
            self._fwd_bwd = _tele.programs.register(
                'executor.fwd_bwd[%s]' % gname, self._fwd_bwd,
                step_flops=True)
            # retrace-storm detector: binding the same graph signature
            # repeatedly (rebind-per-batch, reshape loops) recompiles
            # the same XLA program each time
            _tele.xla.note_retrace(
                ('executor', tuple(self._prog.arg_names),
                 tuple(symbol.list_outputs()),
                 tuple((tuple(a.shape), str(a._data.dtype))
                       for a in self.arg_arrays)))

    def _canon_args(self, args, names, what, allow_missing=False):
        if isinstance(args, dict):
            out = []
            for n in names:
                if n in args:
                    out.append(args[n])
                elif allow_missing:
                    out.append(None)
                else:
                    raise MXNetError('missing %s: %s' % (what, n))
            return out
        args = list(args)
        if len(args) != len(names):
            raise MXNetError('length of %s (%d) != expected (%d: %s)'
                             % (what, len(args), len(names), names))
        return args

    # -- forward ----------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Reference executor.py:89 / GraphExecutor::Forward."""
        from . import telemetry as _tele
        with _tele.span('executor.forward', 'executor'):
            try:
                return self._forward_impl(is_train, **kwargs)
            except Exception as e:
                # RESOURCE_EXHAUSTED: dump the per-program memory
                # breakdown before the crash surfaces (no-op otherwise)
                _tele.programs.maybe_oom_report(e)
                raise

    def _forward_impl(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k in self.arg_dict:
                if isinstance(v, NDArray):
                    self.arg_dict[k]._data = v._data
                else:
                    self.arg_dict[k]._data = jnp.asarray(np.asarray(v))
        self._partial = None  # a full forward invalidates any stepping pass
        if self._use_staged():
            return self._forward_staged(is_train)

        arg_data = tuple(a._data for a in self.arg_arrays)
        aux_data = tuple(a._data for a in self.aux_arrays)
        key = _random.next_key()
        if is_train and self._grad_names:
            # defer: backward will run the fused fwd+bwd computation
            self._pending = (arg_data, aux_data, key)
            self.outputs_cached = None
            return self._lazy_outputs()
        self._pending = None
        outs, new_aux = self._fwd(arg_data, aux_data, key, bool(is_train))
        if is_train:
            self._write_aux(new_aux)
        self.outputs_cached = [from_jax(o, self._ctx) for o in outs]
        return self.outputs_cached

    def partial_forward(self, is_train, step):
        """Interactive stepping forward: execute exactly one operator
        node per call (the GraphExecutor::PartialForward role; the
        stepping loop contract is documented at reference
        include/mxnet/c_predict_api.h:160-169 — call from step=0,
        increment until the return value hits 0).

        Unlike :meth:`forward`, which dispatches one fused XLA program,
        each step here eagerly dispatches a single operator so callers
        can report progress on slow models; intermediate buffers
        persist in an env dict between calls.  Variable values are
        snapshotted into the env when a pass starts — inputs written
        mid-pass take effect on the next pass (restart at step 0), not
        on the remaining steps of the current one.  Restarting at step
        0 (or jumping to an arbitrary step) rebuilds the env and
        replays up to that node.  An abandoned pass keeps its env (and
        the device buffers it holds) until the next full forward,
        param copy, or restart releases it.  Returns the number of
        steps left.
        """
        prog = self._prog
        n_steps = len(prog.op_nodes)
        step = int(step)
        if n_steps == 0:
            # variable-only graph: outputs are just current variables
            env = self._snapshot_env()
            self.outputs_cached = [from_jax(env[_entry_key(n, i)], self._ctx)
                                   for n, i in prog.outputs]
            return 0
        if step < 0 or step >= n_steps:
            return 0
        st = self._partial
        if st is None or st['next'] != step:
            st = self._partial = {'env': self._snapshot_env(), 'next': 0,
                                  'key': _random.next_key(), 'new_aux': {}}
            lo = 0
        else:
            lo = step
        for k in range(lo, step + 1):
            node = prog.op_nodes[k]
            # deterministic per-node stream: fold the stepping pass's
            # base key by topo position, like the jitted runner does
            rng_key = functools.partial(jax.random.fold_in, st['key'],
                                        prog.topo_index[node])
            self._exec_node(node, st['env'], is_train, rng_key,
                            new_aux=st['new_aux'])
        st['next'] = step + 1
        left = n_steps - step - 1
        if left == 0:
            self._pending = None
            self.outputs_cached = [from_jax(st['env'][_entry_key(n, i)],
                                            self._ctx)
                                   for n, i in prog.outputs]
            if is_train:
                for name, v in st['new_aux'].items():
                    self.aux_dict[name]._data = v
            self._partial = None
        return left

    def _lazy_outputs(self):
        self._out_handles = [from_jax(None, self._ctx)
                             for _ in self._prog.outputs]
        self._materialized = False
        return _LazyOutputs(self)

    def _materialize(self):
        if self._pending is None:
            return
        arg_data, aux_data, key = self._pending
        outs, new_aux = self._fwd(arg_data, aux_data, key, True)
        self._write_aux(new_aux)
        for h, o in zip(self._out_handles, outs):
            h._data = o
        self.outputs_cached = self._out_handles
        self._pending = None

    def _write_aux(self, new_aux):
        for a, v in zip(self.aux_arrays, new_aux):
            a._data = v

    @property
    def outputs(self):
        if self._pending is not None:
            self._materialize()
        if self.outputs_cached is None:
            self.forward(False)
        return self.outputs_cached

    # -- backward ---------------------------------------------------------
    def backward(self, out_grads=None, is_train=True):
        """Reference GraphExecutor::Backward (graph_executor.cc:93)."""
        from . import telemetry as _tele
        with _tele.span('executor.backward', 'executor'):
            try:
                return self._backward_impl(out_grads, is_train)
            except Exception as e:
                _tele.programs.maybe_oom_report(e)
                raise

    def _backward_impl(self, out_grads=None, is_train=True):
        if self._use_staged():
            return self._backward_staged(out_grads)
        if self._pending is not None:
            arg_data, aux_data, key = self._pending
        else:
            arg_data = tuple(a._data for a in self.arg_arrays)
            aux_data = tuple(a._data for a in self.aux_arrays)
            key = _random.next_key()
        heads = self._head_grads(out_grads, arg_data, aux_data)
        if _faults.enabled():
            # dispatch-exception seam: the per-batch loop's fused
            # fwd+bwd is about to train one step
            _faults.maybe_raise('executor')
        hv = dv = None
        rets = list(self._fwd_bwd(arg_data, aux_data, key, heads))
        outs, new_aux, grads = rets[0], rets[1], rets[2]
        extra = rets[3:]
        if self._health_on:
            hv = extra.pop(0)
        if self._dyn_on:
            dv = extra.pop(0)
        self._write_aux(new_aux)
        if self._pending is not None:
            for h, o in zip(self._out_handles, outs):
                h._data = o
            self.outputs_cached = self._out_handles
            self._pending = None
        else:
            self.outputs_cached = [from_jax(o, self._ctx) for o in outs]
        self._assign_grads(grads)
        if hv is not None:
            # the sentinel check fetches the small stats vector — the
            # per-batch loop's one added sync (it already synchronizes
            # per batch for its metric). On a non-finite flag the
            # offending batch is STILL loaded in arg_dict, so the
            # first-bad-layer bisect replays it directly.
            from .telemetry import health as _health
            _health.note_step(hv, source='executor',
                              bisect=self.first_nonfinite_node)
        if dv is not None:
            # per-layer dynamics row: rides the same per-batch sync
            from .telemetry import dynamics as _dynamics
            _dynamics.note_step(dv, self._grad_names, self._out_names)

    def _head_grads(self, out_grads, arg_data, aux_data):
        if out_grads is None:
            return tuple(jnp.ones(s, d)
                         for s, d in self._out_shapes(arg_data, aux_data))
        if isinstance(out_grads, NDArray):
            out_grads = [out_grads]
        heads = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads)
        # head grads handed over from ANOTHER module's executor live on
        # that module's devices (SequentialModule chains modules across
        # device groups); pull them onto this computation's output
        # sharding — the reference's engine does this copy implicitly
        # via cross-context dependency edges. Target shardings: the
        # materialized outputs when available (callers that build head
        # grads have read get_outputs()); else, for a single-device
        # computation, the args' device.
        outs = self.outputs_cached
        if outs and len(outs) == len(heads):
            return tuple(_align_head(g, o._data.sharding)
                         for g, o in zip(heads, outs))
        arg_shardings = {a.sharding for a in arg_data
                         if hasattr(a, 'sharding')}
        if len(arg_shardings) == 1:
            (sh,) = arg_shardings
            if len(sh.device_set) == 1:
                heads = tuple(_align_head(g, sh) for g in heads)
        return heads

    def _out_shapes(self, arg_data, aux_data):
        key = tuple((a.shape, str(a.dtype)) for a in arg_data)
        cached = getattr(self, '_out_shapes_memo', None)
        if cached is not None and cached[0] == key:
            return cached[1]
        outs = jax.eval_shape(lambda a, x: self._run_eager(a, x, jnp.zeros((2,), jnp.uint32), True)[0],
                              arg_data, aux_data)
        res = [(o.shape, o.dtype) for o in outs]
        self._out_shapes_memo = (key, res)
        return res

    def _assign_grads(self, grads):
        for name, g in zip(self._grad_names, grads):
            dst = self.grad_dict[name]
            req = self._grad_req[name]
            if req == 'add':
                dst._data = dst._data + g.astype(dst._data.dtype)
            else:
                dst._data = g.astype(dst._data.dtype)

    # -- staged (group2ctx / monitor) mode --------------------------------
    def _use_staged(self):
        return (self._group2ctx is not None or self._monitor is not None
                or self._prog.has_host_ops)

    def _node_device(self, node):
        if self._group2ctx:
            grp = node.attr_dict.get('ctx_group')
            if grp and grp in self._group2ctx:
                return self._group2ctx[grp].jax_device()
        return self._ctx.jax_device()

    def _env_put_variable(self, node, env):
        """Load a variable node's current value into an eager env."""
        src = (self.aux_dict[node.name] if node.name in self.aux_dict
               else self.arg_dict[node.name])
        env[_entry_key(node, 0)] = jax.device_put(src._data,
                                                  self._node_device(node))

    def _snapshot_env(self):
        """Fresh eager env with all variable values snapshotted."""
        env = {}
        for node in self._prog.topo:
            if node.is_variable():
                self._env_put_variable(node, env)
        return env

    def _exec_node(self, node, env, is_train, rng_key, new_aux=None):
        """Eagerly execute one non-variable node into ``env``.

        Shared per-node dispatch for the staged forward and the
        partial_forward stepping path: group2ctx device placement,
        host-op direct call, monitor callbacks, and mutate_inputs aux
        collection (into ``new_aux`` keyed by aux name, if given) all
        live here so the two eager paths cannot drift.
        """
        dev = self._node_device(node)
        op = node.opdef()
        _reg.record(op)
        attrs = dict(node.attrs)
        if op.train_aware:
            attrs['__is_train__'] = bool(is_train)
        ins = [jax.device_put(env[_entry_key(p, i)], dev)
               for p, i in node.inputs]
        if op.needs_rng:
            ins.append(rng_key())
        # same layer-name attribution as the jitted runner: profiler
        # spans and any per-op jit cache entries carry the node name
        with jax.named_scope(self._prog.scope_names[
                self._prog.topo_index[node]]):
            outs = op.fn(attrs, *ins)
        if not isinstance(outs, (tuple, list)):
            outs = (outs,)
        for i, o in enumerate(outs):
            env[_entry_key(node, i)] = o
        if self._monitor is not None:
            # reference entry naming: <node>_output / <node>_output<i>
            # (what Monitor patterns like '.*output.*' match against)
            nvis = op.n_visible_outputs(node.attrs)
            for i in range(nvis):
                self._monitor('%s_output' % node.name if nvis == 1 else
                              '%s_output%d' % (node.name, i),
                              from_jax(outs[i], self._ctx))
        if new_aux is not None:
            for in_idx, out_idx in op.mutate_inputs.items():
                if in_idx < len(node.inputs):
                    src, _ = node.inputs[in_idx]
                    if src.is_variable() and src.name in self.aux_dict:
                        new_aux[src.name] = outs[out_idx]
        return outs

    def _forward_staged(self, is_train):
        env = {}
        prog = self._prog
        new_aux = {} if is_train else None
        for node in prog.topo:
            if node.is_variable():
                self._env_put_variable(node, env)
            else:
                self._exec_node(node, env, is_train, _random.next_key,
                                new_aux=new_aux)
        if new_aux:
            for name, v in new_aux.items():
                self.aux_dict[name]._data = v
        self.outputs_cached = [from_jax(env[_entry_key(n, i)], self._ctx)
                               for n, i in prog.outputs]
        self._staged_env_inputs = None
        return self.outputs_cached

    def _backward_staged(self, out_grads):
        # eager vjp over the pure runner (device movement handled by jax)
        arg_data = tuple(a._data for a in self.arg_arrays)
        aux_data = tuple(a._data for a in self.aux_arrays)
        key = _random.next_key()
        grad_idx = tuple(self._prog.arg_names.index(n) for n in self._grad_names)

        def f(wrt):
            full = list(arg_data)
            for i, gi in enumerate(grad_idx):
                full[gi] = wrt[i]
            outs, _ = self._run_eager(tuple(full), aux_data, key, True)
            return outs

        wrt = tuple(arg_data[gi] for gi in grad_idx)
        outs, vjp = jax.vjp(f, wrt)
        if out_grads is None:
            heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = tuple(g._data if isinstance(g, NDArray) else jnp.asarray(g)
                          for g in out_grads)
            if len(heads) != len(outs):
                raise ValueError(
                    'backward got %d head gradients for %d outputs'
                    % (len(heads), len(outs)))
            # cross-device handoff (see _head_grads): cotangents must
            # live where the primals do
            heads = tuple(_align_head(g, o.sharding)
                          for g, o in zip(heads, outs))
        (grads,) = vjp(heads)
        self.outputs_cached = [from_jax(o, self._ctx) for o in outs]
        self._assign_grads(grads)

    def first_nonfinite_node(self, overrides=None, is_train=True):
        """First-bad-layer bisect (telemetry/health): replay the graph
        through the staged per-node path and return the first symbol
        whose VALUE is non-finite, as ``(name, output_index)`` — or
        None when everything is finite. Variables are checked too, so a
        poisoned weight (or a NaN input batch) is named directly rather
        than through the first op that touches it.

        ``overrides`` maps variable names to jax arrays replacing the
        executor's current values (the fused window loops pass the
        offending batch's draw-time snapshot). Parameters are whatever
        the executor holds NOW — for a window incident that is the
        post-window state, which a mid-window NaN has usually poisoned;
        the poisoned weight then IS the attribution. Once-per-incident
        cost: one eager dispatch + host check per node."""
        from .telemetry.health import has_nonfinite
        prog = self._prog
        env = {}
        key = _random.next_key()
        mon, self._monitor = self._monitor, None   # no monitor callbacks
        try:                                       # during the replay
            for node in prog.topo:
                if node.is_variable():
                    if overrides and node.name in overrides:
                        env[_entry_key(node, 0)] = jax.device_put(
                            overrides[node.name], self._node_device(node))
                    else:
                        self._env_put_variable(node, env)
                    vals = (env[_entry_key(node, 0)],)
                else:
                    rng_key = functools.partial(jax.random.fold_in, key,
                                                prog.topo_index[node])
                    vals = self._exec_node(node, env, is_train, rng_key)
                for i, v in enumerate(vals):
                    if has_nonfinite(np.asarray(v)):
                        return node.name, i
        finally:
            self._monitor = mon
        return None

    # -- misc API ---------------------------------------------------------
    def set_monitor_callback(self, callback, monitor_all=False):
        """Reference executor.h:148 SetMonitorCallback; forces staged mode."""
        self._monitor = callback
        self._monitor_all = monitor_all

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        self._partial = None  # param writes invalidate a stepping pass
        dev = self._ctx.jax_device()
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                dst = self.arg_dict[name]
                dst._data = jax.device_put(
                    arr._data.astype(dst._data.dtype), dev)
            elif not allow_extra_params:
                raise ValueError('Found name "%s" that is not in the arguments' % name)
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    dst = self.aux_dict[name]
                    dst._data = jax.device_put(
                        arr._data.astype(dst._data.dtype), dev)
                elif not allow_extra_params:
                    raise ValueError('Found name "%s" that is not in the auxiliary states' % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Rebind with new input shapes; XLA recompiles (cached per shape)."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = {}
        for name, sh in zip(self._prog.arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if tuple(cur.shape) == tuple(sh):
                new_args[name] = cur
            else:
                new_args[name] = nd_zeros(sh, ctx=self._ctx,
                                          dtype=str(cur._data.dtype))
        new_aux = {}
        for name, sh in zip(self._prog.aux_names, aux_shapes):
            cur = self.aux_dict[name]
            new_aux[name] = cur if tuple(cur.shape) == tuple(sh) else \
                nd_zeros(sh, ctx=self._ctx, dtype=str(cur._data.dtype))
        grads = None
        if any(g is not None for g in self.grad_arrays):
            grads = {n: nd_zeros(new_args[n].shape, ctx=self._ctx,
                                 dtype=str(new_args[n]._data.dtype))
                     for n in self._grad_names}
        return Executor(self._symbol, self._ctx, new_args, grads,
                        self._grad_req, new_aux, group2ctx=self._group2ctx)

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))


class _LazyOutputs(list):
    """List proxy that materializes the deferred training forward on access."""

    def __init__(self, executor):
        super().__init__(executor._out_handles)
        self._exec = executor

    def __getitem__(self, i):
        self._exec._materialize()
        return super().__getitem__(i)

    def __iter__(self):
        self._exec._materialize()
        return super().__iter__()


def simple_bind(symbol, ctx, grad_req='write', type_dict=None, group2ctx=None,
                shared_exec=None, **kwargs):
    """Reference symbol.py:1250 Symbol.simple_bind: infer shapes, allocate."""
    arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**kwargs)
    if arg_shapes is None:
        raise MXNetError('cannot infer shapes')
    type_dict = type_dict or {}
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    # dtypes via type inference (reference simple_bind runs InferType):
    # a Cast to bf16 makes downstream parameters bf16 automatically
    arg_types, _, aux_types = symbol.infer_type(**type_dict)
    args = {}
    for name, sh, it in zip(arg_names, arg_shapes, arg_types):
        # keep the dtype OBJECT: str() of the bf16 scalar class is not a
        # parseable dtype name (np_dtype is idempotent)
        args[name] = nd_zeros(sh, ctx=ctx,
                              dtype=np_dtype(type_dict.get(name, it)))
    aux = {}
    for name, sh, it in zip(aux_names, aux_shapes, aux_types):
        aux[name] = nd_zeros(sh, ctx=ctx, dtype=np_dtype(it))
    grads = None
    req_of = (lambda n: grad_req) if isinstance(grad_req, str) else \
        (lambda n: grad_req[arg_names.index(n)] if isinstance(grad_req, (list, tuple))
         else grad_req.get(n, 'null'))
    if grad_req != 'null':
        grads = {}
        for name, sh in zip(arg_names, arg_shapes):
            if req_of(name) != 'null':
                grads[name] = nd_zeros(sh, ctx=ctx,
                                       dtype=str(args[name]._data.dtype))
    return Executor(symbol, ctx, args, grads, grad_req, aux,
                    group2ctx=group2ctx)
