"""Typed, validated configuration: env-flag catalog + parameter structs.

Reference: dmlc-core's parameter.h (`DMLC_DECLARE_FIELD` with defaults,
ranges and enums, `Init(kwargs)` validation with readable errors) and
docs/how_to/env_var.md (the catalog of `MXNET_*` environment variables).

Two pieces:

- ``Flag`` / ``flags``: every environment variable the framework reads,
  declared centrally with type, default, and doc. ``flags.get(name)``
  parses + validates once and caches; ``flags.describe()`` prints the
  catalog (the env_var.md equivalent). Reference ``MXNET_*`` spellings
  are accepted as aliases for the ``MXTPU_*`` names.
- ``Parameter``/``field``: a small dmlc-Parameter analog for validated
  option structs (ranges, enums, required fields) used by iterators and
  tools.
"""
import os
import threading

__all__ = ['Flag', 'FlagRegistry', 'flags', 'Parameter', 'field']


class Flag:
    def __init__(self, name, type_, default, doc, aliases=(), choices=None,
                 min_value=None, max_value=None):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc
        self.aliases = tuple(aliases)
        self.choices = choices
        self.min_value = min_value
        self.max_value = max_value

    def parse(self, raw):
        if raw is None:
            return self.default
        try:
            if self.type is bool:
                val = raw.strip().lower() not in ('', '0', 'false', 'no')
            else:
                val = self.type(raw)
        except (TypeError, ValueError):
            raise ValueError(
                'env %s=%r: expected %s' % (self.name, raw,
                                            self.type.__name__))
        if self.choices is not None and val not in self.choices:
            raise ValueError('env %s=%r: must be one of %s'
                             % (self.name, raw, sorted(self.choices)))
        if self.min_value is not None and val < self.min_value:
            raise ValueError('env %s=%r: must be >= %s'
                             % (self.name, raw, self.min_value))
        if self.max_value is not None and val > self.max_value:
            raise ValueError('env %s=%r: must be <= %s'
                             % (self.name, raw, self.max_value))
        return val


class FlagRegistry:
    def __init__(self):
        self._flags = {}
        self._cache = {}
        self._lock = threading.Lock()

    def declare(self, name, type_, default, doc, **kwargs):
        flag = Flag(name, type_, default, doc, **kwargs)
        self._flags[name] = flag
        return flag

    def get(self, name):
        """Parsed + validated value of a declared flag (cached; reference
        dmlc::GetEnv but with the declaration enforced)."""
        with self._lock:
            if name in self._cache:
                return self._cache[name]
            flag = self._flags[name]  # KeyError = undeclared flag: a bug
            raw = os.environ.get(flag.name)
            if raw is None:
                for alias in flag.aliases:
                    raw = os.environ.get(alias)
                    if raw is not None:
                        break
            val = flag.parse(raw)
            self._cache[name] = val
            return val

    def reload(self, name=None):
        """Drop cached values (tests mutate os.environ)."""
        with self._lock:
            if name is None:
                self._cache.clear()
            else:
                self._cache.pop(name, None)

    def describe(self):
        """The env_var.md catalog as text."""
        lines = []
        for name in sorted(self._flags):
            f = self._flags[name]
            alias = (' (alias: %s)' % ', '.join(f.aliases)) if f.aliases else ''
            lines.append('%s [%s, default %r]%s\n    %s'
                         % (name, f.type.__name__, f.default, alias, f.doc))
        return '\n'.join(lines)

    def __iter__(self):
        return iter(self._flags.values())


flags = FlagRegistry()

# ---- the catalog (reference: docs/how_to/env_var.md) ----------------------
flags.declare('MXTPU_ENGINE_WORKERS', int, 4,
              'Worker threads in the native dependency engine',
              aliases=('MXNET_CPU_WORKER_NTHREADS',), min_value=1,
              max_value=512)
flags.declare('MXTPU_ENGINE_TYPE', str, 'ThreadedEngine',
              'Engine scheduling mode; NaiveEngine = synchronous debugging '
              'mode (race detection off the table by construction)',
              aliases=('MXNET_ENGINE_TYPE',),
              choices={'NaiveEngine', 'ThreadedEngine',
                       'ThreadedEnginePerDevice'})
flags.declare('MXTPU_NO_NATIVE', bool, False,
              'Skip loading/building the native runtime library '
              '(pure-python fallbacks for engine/recordio/profiler)')
flags.declare('MXTPU_BACKWARD_DO_MIRROR', str, '0',
              "Gradient-memory tradeoff: '1' (or any truthy value) = full "
              "rematerialization of the forward under jax.checkpoint, "
              "'dots' = keep matmul results (checkpoint_dots policy), "
              "'0'/''/'false' = off (legacy spellings honored)",
              aliases=('MXNET_BACKWARD_DO_MIRROR',))
flags.declare('MXTPU_CONV_BWD_PATCHES', bool, False,
              'compute conv2d weight gradients as an explicit im2col '
              'patches-matmul instead of conv_backprop_filter '
              '(groups=1 2D convs only; see docs/perf.md)')
flags.declare('MXTPU_CONV_STEM_S2D', bool, False,
              'rewrite thin-input strided convs (cin<=4, stride>1: the '
              'image-network stem) into space-to-depth + stride-1 convs; '
              'exact reparametrization that the MXU tiles far better than '
              'a cin=3 strided conv (see docs/perf.md)')
flags.declare('MXTPU_TELEMETRY', bool, False,
              'Runtime telemetry (mxnet_tpu/telemetry): span/counter/'
              'gauge registry over the train hot path, XLA compile and '
              'memory gauges, JSONL metrics log + end-of-run summary '
              'table. Off = zero-overhead no-op path')
flags.declare('MXTPU_TELEMETRY_PATH', str, 'telemetry.jsonl',
              'Append-only JSONL metrics log written while '
              'MXTPU_TELEMETRY=1 (one JSON record per line: spans, '
              'compile events, end-of-run summary)')
flags.declare('MXTPU_TELEMETRY_RETRACE_WARN', int, 5,
              'Warn (once, loudly) when the same graph is compiled more '
              'than this many times — the retrace-storm detector',
              min_value=1)
flags.declare('MXTPU_TELEMETRY_PORT', int, -1,
              'Live telemetry endpoint (telemetry/serve.py, requires '
              'MXTPU_TELEMETRY=1): serve /metrics (Prometheus text), '
              '/healthz (200/503 from the health incident state) and '
              '/summary (registry snapshot as JSON) from a stdlib HTTP '
              'server on a daemon thread. 0 binds an OS-assigned '
              'ephemeral port; -1 (default) = off: no thread, no socket',
              min_value=-1, max_value=65535)
flags.declare('MXTPU_TELEMETRY_SYNC_EVERY', int, 0,
              'Cluster telemetry sync cadence (telemetry/cluster.py, '
              'requires MXTPU_TELEMETRY=1): every N training steps run '
              'one small off-graph allgather carrying each host\'s key '
              'gauges (step-time p50, io-wait share, dispatch span, '
              'live bytes); process 0 publishes cluster.* per-host '
              'gauges, spread, slowest-host id and the straggler '
              'classification. 0 (default) = off: the fit loops never '
              'touch the hook', min_value=0)
flags.declare('MXTPU_TELEMETRY_MAX_MB', float, 0.0,
              'Size cap (MB) for the JSONL telemetry log: once the file '
              'would exceed it, records are dropped (counted under '
              'telemetry.dropped_records, warned once) instead of '
              'filling the disk on week-long runs. 0 = unlimited',
              min_value=0.0)
flags.declare('MXTPU_GOODPUT', bool, True,
              'Goodput accounting plane (telemetry/goodput.py, requires '
              'MXTPU_TELEMETRY=1 — telemetry off means true no-op): '
              'classify every second of measured wall-clock into named '
              'buckets (productive step compute, XLA compile, input '
              'wait, checkpoint, eval, collective comm, restart rework, '
              'unattributed overhead) from the existing span/mark '
              'sites; buckets + overhead sum to wall-clock exactly. '
              'goodput.* gauges, a goodput JSONL record, the "Where the '
              'time went" summary block, /metrics + /summary, fleet '
              'aggregation through the cluster sync vector. 0 = off')
flags.declare('MXTPU_GOODPUT_LOST_S', float, 0.0,
              'Cumulative lost-work seconds of PRIOR supervised '
              'attempts, stamped into a relaunched child\'s environment '
              'by tools/train_supervisor.py / tools/gang_supervisor.py '
              '(dead-attempt wall since the last_good checkpoint '
              'pointer). The goodput record reports it as prior_lost_s '
              'with the derived job_wall_s / job_goodput_pct; per-'
              'process buckets still sum to per-process wall. Not for '
              'humans to set', min_value=0.0)
flags.declare('MXTPU_TIMELINE', bool, False,
              'Pod-level step timeline (telemetry/timeline.py, requires '
              'MXTPU_TELEMETRY=1): cross-host clock alignment piggy-'
              'backed on the cluster sync allgather (no new collective; '
              'cluster.h<i>.clock_offset_ms), a step-phase ledger from '
              'the existing spans, and per-sync-round critical-path '
              'attribution — the gang step decomposed into compute / '
              'collective-wait / io / host-side with the gating host '
              'AND phase named (timeline.critical_host/critical_phase/'
              'skew_ms gauges, timeline JSONL records, the "step '
              'timeline" summary block; tools/trace_merge.py stitches '
              'the per-host logs into one offset-corrected Perfetto '
              'trace). Off (default) = true no-op: one cached-bool per '
              'seam, lowered programs byte-identical, the sync vector '
              'slots ride as NaN')
flags.declare('MXTPU_TELEMETRY_BIND', str, '127.0.0.1',
              'Bind address for the live telemetry endpoint '
              '(telemetry/serve.py). Default 127.0.0.1 = loopback only; '
              "set to '0.0.0.0' (or empty) to expose /metrics /healthz "
              '/summary on all interfaces — do that only behind scrape-'
              'infra access control (docs/observability.md)')
flags.declare('MXTPU_CKPT_DIR', str, '',
              'Root directory for periodic sharded training checkpoints '
              '(module/checkpointing.py over parallel/checkpoint.py\'s '
              'orbax tier): each host writes only its own shards, so '
              'save/restore cost scales with per-host bytes, not model '
              'size. Must be a path every host of a multi-host job can '
              'reach. Empty (default) = checkpointing off')
flags.declare('MXTPU_CKPT_EVERY', int, 0,
              'Save a training checkpoint every N trained steps '
              '(quantized to window boundaries on the fused-fit path). '
              'Captures params, optimizer state, RNG streams, epoch/'
              'step cursor and eval-metric state; saves are '
              'asynchronous — the step loop is never blocked on the '
              'write. 0 (default) = off (MXTPU_CKPT_DIR must also be '
              'set)', min_value=0)
flags.declare('MXTPU_CKPT_KEEP', int, 3,
              'How many checkpoint steps to retain (orbax max_to_keep '
              'pruning); older steps are deleted as new ones commit',
              min_value=1)
flags.declare('MXTPU_CKPT_ASYNC', bool, True,
              'Write checkpoints on a background thread (the step loop '
              'only captures array references and moves on). If the '
              'async writer dies, checkpointing falls back to '
              'synchronous saves — and if those fail too, training '
              'continues without checkpoints (warn, never crash). 0 '
              'forces synchronous saves from the start')
flags.declare('MXTPU_CKPT_RESUME', bool, True,
              'At fit() start, restore from the newest health-certified '
              'checkpoint (the last-good pointer) when MXTPU_CKPT_DIR '
              'holds one: parameters, optimizer state, RNG streams and '
              'the epoch/step cursor come back bit-exactly and the '
              'data iterator is skipped to the restored step. 0 always '
              'starts fresh (existing checkpoints are left alone)')
flags.declare('MXTPU_RESTART_MAX', int, 3,
              'Restart budget for the supervised training driver '
              '(module/resilient_fit.py, tools/train_supervisor.py): '
              'how many times a failed run is restored from last-good '
              'and resumed before the failure is re-raised', min_value=0)
flags.declare('MXTPU_RESTART_BACKOFF', float, 2.0,
              'Base backoff (seconds) between supervised restarts; '
              'attempt k waits backoff * 2^(k-1), capped at 60s',
              min_value=0.0)
flags.declare('MXTPU_FAULT_INJECT', str, '',
              'Deterministic fault injection (mxnet_tpu/faults.py): '
              "'<kind>:<step>[:<arg>]' with kind one of nan-grad, "
              'checkpoint-corrupt, dispatch-exception, '
              'backend-probe-timeout, slow-host, hang, host-loss, '
              'mem-hog, clock-skew — fires one real fault '
              'at a deterministic training step so every recovery path '
              '(health raise, restore-from-last-good, restart backoff, '
              'bench reprobe) is exercised by real tests, not mocks. '
              'Empty (default) = off: every seam is one cached-bool '
              'check and the compiled programs are untouched')
flags.declare('MXTPU_HEALTH', bool, False,
              'Training-health sentinels (telemetry/health, requires '
              'MXTPU_TELEMETRY=1): in-graph NaN/Inf detection with '
              'exact-step attribution through the fused windows, a '
              'first-bad-layer bisect, rolling-baseline spike detectors '
              'over step time / loss / grad-norm, and a "Run health" '
              'block in the telemetry summary. Off (or telemetry off) = '
              'true no-op: the compiled programs are byte-identical')
flags.declare('MXTPU_HEALTH_ACTION', str, 'warn',
              "What a non-finite incident does: 'warn' logs it (rate-"
              "limited), 'record' only appends the health JSONL record, "
              "'raise' raises telemetry.health.TrainingHealthError with "
              'the diagnostic (step, window step, first bad layer) '
              'attached. Spike anomalies never raise',
              choices={'warn', 'record', 'raise'})
flags.declare('MXTPU_HEALTH_K', float, 8.0,
              'Spike threshold for the health anomaly detectors: an '
              'observation more than K robust deviations (MAD) from '
              'the rolling median is an anomaly', min_value=1.0)
flags.declare('MXTPU_HEALTH_WINDOW', int, 64,
              'Trailing-window length (observations) backing the health '
              "anomaly detectors' rolling median/MAD baseline",
              min_value=4)
flags.declare('MXTPU_TFEVENTS_DIR', str, '',
              'Directory for native TensorBoard event files '
              '(telemetry/ledger.py): every ledger scalar '
              '(MXTPU_SCALARS_EVERY) is also encoded as a tfevents '
              'record through the dependency-free TFRecord/Event '
              'writer — `tensorboard --logdir <dir>` works on any run '
              'without tensorboardX or torch installed. Empty '
              '(default) = no event file is written')
flags.declare('MXTPU_WATCHDOG_SECS', float, 0.0,
              'Hang watchdog (telemetry/watchdog.py): once the training '
              'loop has made its first progress mark, a daemon thread '
              'checks that marks (per-batch/per-window dispatch, eval '
              'windows, cluster sync rounds, kvstore push/pull, '
              'checkpoint commits) keep arriving at least this often. '
              'On a stall it dumps all-thread stacks + the last '
              'telemetry state as a hang JSONL incident, flips /healthz '
              'to 503 with a hung digest, and applies '
              'MXTPU_WATCHDOG_ACTION. Set it above the worst legitimate '
              'gap (an XLA recompile can take 20-40s). 0 (default) = '
              'off: no thread is ever created', min_value=0.0)
flags.declare('MXTPU_WATCHDOG_ACTION', str, 'warn',
              "What the hang watchdog does on a stall: 'warn' records "
              "the incident and keeps waiting (clears when progress "
              "resumes), 'abort' additionally exits the process with "
              'the distinct code 85 so tools/train_supervisor.py '
              'relaunches from the last-good checkpoint',
              choices={'warn', 'abort'})
flags.declare('MXTPU_SUPERVISOR_LIVENESS', float, 0.0,
              'Supervisor-side liveness tier (tools/train_supervisor.py, '
              'read from the environment — the supervisor never imports '
              'the framework): if the child process appends no new '
              'bytes to its MXTPU_TELEMETRY_PATH JSONL for this many '
              'seconds, the supervisor SIGTERMs (then SIGKILLs) and '
              'relaunches it against the same restart budget — the '
              'tier for a child too wedged to run its own in-process '
              'watchdog. Needs the child run with MXTPU_TELEMETRY=1; '
              'set it well above MXTPU_WATCHDOG_SECS so the in-process '
              'watchdog acts first. 0 (default) = off', min_value=0.0)
flags.declare('MXTPU_ELASTIC_INPUT', bool, False,
              'Straggler-aware input re-balancing (telemetry/cluster.py, '
              'requires MXTPU_TELEMETRY=1 and '
              'MXTPU_TELEMETRY_SYNC_EVERY>0): when a cluster sync round '
              'classifies the slowest host as input-bound, every host '
              'deterministically computes the same shifted shard '
              'assignment from the same gathered round and applies it '
              'at the next epoch boundary via the iterator '
              'shard_info()/set_shard() protocol (ImageRecordIter, '
              'MNISTIter). Off (default) = the fit loops never touch '
              'the hook')
flags.declare('MXTPU_KVSTORE_TIMEOUT', float, 0.0,
              'Bound (seconds) on each kvstore_dist push/pull server '
              'reply. A shard request that exceeds it counts as a '
              'transient connection error and enters the '
              'MXTPU_KVSTORE_RETRIES reconnect-and-retry path instead '
              'of hanging into the watchdog. 0 (default) = unbounded '
              '(the pre-retry behavior)', min_value=0.0)
flags.declare('MXTPU_KVSTORE_RETRIES', int, 2,
              'How many times a kvstore_dist push/pull shard request is '
              'retried after a transient connection error (socket '
              'error, or an MXTPU_KVSTORE_TIMEOUT expiry): each retry '
              'reconnects to the server and backs off exponentially '
              '(0.05s * 2^k, capped at 2s). Past the budget the error '
              're-raises as ConnectionError — retryable by '
              'resilient_fit/the supervisor. 0 = a single attempt',
              min_value=0)
flags.declare('MXTPU_XPROF', str, '',
              "One-shot step-windowed device-trace capture: 'start:stop' "
              "(training-step counts) arms jax.profiler to start once "
              "`start` steps have completed and stop at `stop`, writing "
              'a TensorBoard/Perfetto trace to MXTPU_XPROF_DIR. The '
              'fused fit path advances a whole window of steps per '
              'device call, so boundaries quantize to window multiples '
              'there. Honors the MXTPU_PROFILER_XLA_TRACE backend guard '
              '(no capture against the tunneled axon chip). Empty = off')
flags.declare('MXTPU_XPROF_DIR', str, 'xprof_trace',
              'Output directory for the MXTPU_XPROF device trace')
flags.declare('MXTPU_ROOFLINE', bool, False,
              'Roofline attribution (mxnet_tpu/telemetry/roofline.py, '
              'requires MXTPU_TELEMETRY=1): parse every registered '
              "program's HLO into per-layer FLOPs/bytes, join measured "
              'per-fusion device timings from the MXTPU_XPROF capture '
              'by jax.named_scope layer name, classify each layer '
              'compute-/memory-/overhead-bound against the chip peak '
              'table, and account collective bytes/time/overlap per '
              'step. Off = no HLO text is ever rendered or parsed (one '
              'cached-bool check at the program registrar)')
flags.declare('MXTPU_MEMORY', bool, False,
              'HBM attribution & forecast plane '
              '(mxnet_tpu/telemetry/memory.py, requires '
              'MXTPU_TELEMETRY=1): attribute every registered '
              "program's argument/temp/output/alias bytes to named "
              'layers (HLO buffer parse calibrated against '
              "XLA's own memory_analysis totals), keep a bounded "
              'live-bytes ring sampled at the scalars cadence, and '
              'forecast steps-to-OOM — a forecast at or below '
              'MXTPU_MEMORY_OOM_STEPS flips /healthz to mem_pressure '
              'and dumps the flight recorder BEFORE the allocator '
              'dies. Off = no HLO text is ever rendered or parsed and '
              'no ring is filled (one cached-bool check at the '
              'registrar and the step loops)')
flags.declare('MXTPU_MEMORY_OOM_STEPS', int, 200,
              'mem_pressure threshold for the MXTPU_MEMORY forecaster: '
              'a linear steps-to-OOM forecast at or below this many '
              'steps trips the alarm (healthz mem_pressure + the '
              'flight-mem-pressure dump). Forecasts above it only '
              'publish the mem.steps_to_oom gauge', min_value=1)
flags.declare('MXTPU_ROOFLINE_TRACE', str, '',
              'Path to a jax.profiler capture (directory, or a '
              '*.trace.json[.gz] file) supplying the roofline\'s '
              'measured per-layer timings. Empty = use MXTPU_XPROF_DIR '
              'when a capture exists there, else distribute the '
              'registry-measured step time across layers by their '
              'roofline-minimum times (source: modeled)')
flags.declare('MXTPU_PEAK_TFLOPS', float, 0.0,
              'Override the device peak dense bf16 TFLOP/s used by the '
              'MFU estimate and the roofline denominators (for chips '
              'missing from the telemetry/xla.py table — the '
              'warn-once path names this flag). 0 = use the table',
              min_value=0.0)
flags.declare('MXTPU_PEAK_HBM_GBS', float, 0.0,
              'Override the device peak HBM GB/s used by the roofline '
              'denominators (pairs with MXTPU_PEAK_TFLOPS). 0 = use '
              'the table', min_value=0.0)
flags.declare('MXTPU_PROFILER_XLA_TRACE', str, 'auto',
              "Attach jax.profiler alongside the host-span trace when the "
              "profiler runs: '1' always, '0' never, 'auto' = only on "
              "backends where a killed trace cannot wedge the device "
              "claim (skips the tunneled axon platform)",
              choices={'0', '1', 'auto'})
flags.declare('MXTPU_FORCE_PALLAS', bool, False,
              'Dispatch LayerNorm/softmax/attention to the Pallas kernels '
              'even off-TPU (interpret mode; exercises the kernel path on '
              'the CPU test mesh)')
flags.declare('MXTPU_KVSTORE_BIGARRAY_BOUND', int, 1 << 20,
              'Arrays with >= this many elements are striped across all '
              'servers on push/pull',
              aliases=('MXNET_KVSTORE_BIGARRAY_BOUND',), min_value=1)
flags.declare('MXTPU_KVSTORE_DEBUG', bool, False,
              'Verbose logging in the distributed kvstore tier')
flags.declare('MXTPU_NO_SPMD_MODULE', bool, False,
              'Disable the fused single-program (GSPMD) lowering for '
              'multi-context Module; fall back to the per-device loop')
flags.declare('MXTPU_FUSED_FIT', bool, True,
              'Allow Module.fit to compile a window of N train steps '
              'into one XLA call (lax.scan) when eligible '
              '(module/fused_fit.py); 0 forces the per-batch loop')
flags.declare('MXTPU_FIT_STEPS_PER_CALL', int, 0,
              'Window size for the fused Module.fit fast path; 0 = '
              'auto (32 on TPU, 4 elsewhere)', min_value=0)
flags.declare('MXTPU_FUSED_EVAL', bool, True,
              'Allow score/predict/iter_predict to compile a window of '
              'N forward steps into one XLA call (lax.scan) with '
              'on-device metric accumulation or a stacked-output '
              'fetch — one dispatch + one fetch per window instead of '
              'two per batch (module/fused_eval.py); 0 forces the '
              'per-batch loop')
flags.declare('MXTPU_EVAL_STEPS_PER_CALL', int, 0,
              'Window size for the fused eval/inference fast path; '
              '0 = auto (32 on TPU, 4 elsewhere)', min_value=0)
flags.declare('MXTPU_FUSED_EVAL_PREFETCH', bool, True,
              'Pipeline the fused-eval window input: window k+1\'s '
              'host-stack + host->device transfer run on a side '
              'thread while window k computes on device; 0 restores '
              'the serial stack/put/dispatch/fetch order')
flags.declare('MXTPU_COMPILE_CACHE', str, '',
              'Directory for jax\'s persistent XLA compilation cache '
              '(jax_compilation_cache_dir), wired at package import so '
              'even warmup compiles are cache-servable. Empty = off. '
              'Warm starts reuse cached executables instead of paying '
              'the 20-40s XLA compile; telemetry counts served '
              'compiles under xla.cache_hits')
flags.declare('MXTPU_SHARDED_UPDATE', bool, True,
              'ZeRO-style sharded weight update in the SPMD fused-fit '
              'window (arXiv:2004.13336): grads reduce-scatter, each '
              'replica updates 1/dp of EVERY param (leaves flattened '
              'and zero-padded to a multiple of dp), weights '
              'all-gather — optimizer state + master params live '
              'dp-sharded between windows, so their per-device bytes '
              'drop ~dp x (update.opt_state_bytes_per_device gauge). '
              'Engages only with an SPMD dp mesh (dp > 1) and the '
              'module not opted out (module.sharded_update = False); '
              'anywhere else the update runs replicated (warn-once '
              'when the flag was set explicitly). 0 keeps the '
              'replicated update everywhere')
flags.declare('MXTPU_GRAD_COMPRESS', str, 'off',
              'Quantized gradient collectives with error feedback '
              '(parallel/compression.py, EQuARX recipe): int8 = '
              'block-quantized grads with per-block scales and a '
              'persistent error-feedback residual carried through the '
              'fused window; bf16 = half-width cast, no scales; auto = '
              'start uncompressed, flip to int8 when a cluster sync '
              'round classifies the run communication_bound (the flip '
              'rebuilds the window program and emits one compression '
              'JSONL record with the step-time delta). Also switches '
              'the kvstore_dist push/pull wire format to compressed, '
              'version-tagged payloads. off lowers byte-identically '
              'to the uncompressed program. Gauges: comm.bytes_on_'
              'wire_per_step, comm.compression_ratio',
              choices={'off', 'int8', 'bf16', 'auto'})
flags.declare('MXTPU_GRAD_COMPRESS_BLOCK', int, 256,
              'Block size for int8 gradient quantization: one fp32 '
              'scale (amax/127) per this many gradient elements. '
              'Smaller blocks track outliers tighter at more scale '
              'overhead (4 bytes per block on the wire)',
              min_value=8)
flags.declare('MXTPU_BN_ONEPASS', bool, True,
              'BatchNorm training stats via one-pass moments '
              '(sum/sum-of-squares in one fused HBM read of the '
              'activation) instead of jnp.var\'s two-pass mean-then-'
              'centered-square. Default ON since the fused-window '
              'donation round: with the window\'s buffer economics '
              'fixed the one HBM read wins where the round-5 A/B '
              '(2406 vs 2535 img/s, bench_bn_*_20260802T061225Z) '
              'measured it 5% slower against the pre-donation '
              'program. 0 is the escape hatch back to the two-pass '
              'jnp.var form (byte-identical to the old default '
              'lowering); numerics are parity-tested both ways '
              '(tests/unittest/test_bn_onepass.py)')
flags.declare('MXTPU_FUSED_DONATE', bool, True,
              'Donate the fused-fit window\'s inputs to XLA: the '
              'param/optimizer/aux carry (aliased onto the matching '
              'outputs — the weight update runs in place) AND the '
              'stacked input window + per-step label stacks (freed '
              'by the runtime at their last in-program use instead '
              'of surviving until the next window rebinds them, so '
              'two windows\' stacks never need to be live at once '
              'under the prefetch pipeline). program.<window>.'
              'live_bytes / alias_bytes in the registrar carry the '
              'before/after evidence. 0 disables ALL window '
              'donation — the undonated reference program the '
              'donation-safety parity tests compare against')
flags.declare('MXTPU_REMAT_POLICY', str, '',
              "Rematerialization policy for the fused-fit window "
              "body, the roofline block's memory-bound lever: "
              "'none' = save every forward residual (explicitly "
              "overrides MXTPU_BACKWARD_DO_MIRROR for the window), "
              "'dots' = keep matmul/conv results and recompute the "
              "rest (jax checkpoint_dots policy), 'full' = "
              "rematerialize the whole forward in backward (max "
              "temp-memory saving, ~1/3 more FLOPs). Empty (default) "
              "defers to MXTPU_BACKWARD_DO_MIRROR exactly as before. "
              "Flipping it between fit() calls rebuilds the window",
              choices={'', 'none', 'dots', 'full'})
flags.declare('MXTPU_HOST_CROP', bool, True,
              'In ImageRecordIter device-augment mode, workers crop '
              '(rand or center) to the target HxW before handover, so '
              'the uploaded uint8 window carries H*W/S^2 of the source '
              'bytes (23% fewer for 224^2 crops of 256^2 sources); '
              'mirror + normalize stay on device. 0 ships the full '
              'fixed-size source and crops on device')
flags.declare('MXTPU_FUSED_FIT_PREFETCH', bool, True,
              'Pipeline the fused-fit window input: window k+1\'s '
              'host-stack + host->device transfer run on a side '
              'thread while window k computes on device (np.stack '
              'and the transfer release the GIL, so the overlap holds '
              'even on a one-core host). 0 restores the serial '
              'stack/put/dispatch/fetch order')
flags.declare('MXTPU_FUSED_FIT_TIMING', bool, False,
              'Log a per-epoch host-stage breakdown of the fused fit '
              'loop (draw / stack+put / dispatch / stats-fetch) — the '
              'diagnosis knob for fed-path throughput work')
flags.declare('MXTPU_DEVICE_AUGMENT', bool, False,
              'ImageRecordIter ships fixed-size uint8 batches and runs '
              'crop/mirror/normalize as one jitted device call per '
              'batch (io/image_record.py device-augment mode) — for '
              'few-core hosts that cannot feed the chip from the '
              'host-side augment path')
flags.declare('MXTPU_F16_AS_BF16', bool, False,
              'Resolve float16 dtype requests to bfloat16, the TPU '
              'native half type (the MXU has no fp16 datapath)')
flags.declare('MXTPU_EXEC_BULK_EXEC_MAX_NODE_TRAIN', int, 15,
              'Max ops bulked into one engine push by the executor',
              aliases=('MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN',), min_value=1)
flags.declare('MXTPU_PROFILER_AUTOSTART', bool, False,
              'Start the profiler at init (reference '
              'MXNET_PROFILER_AUTOSTART)',
              aliases=('MXNET_PROFILER_AUTOSTART',))
flags.declare('MXTPU_COORDINATOR', str, '',
              'host:port of the jax.distributed coordinator for the '
              'multi-host SPMD tier (set by tools/launch.py; the DCN '
              'analog of DMLC_PS_ROOT_URI/PORT)')
flags.declare('MXTPU_NUM_HOSTS', int, 1,
              'Process count of the multi-host SPMD job '
              '(DMLC_NUM_WORKER analog)', min_value=1)
flags.declare('MXTPU_HOST_ID', int, 0,
              'This process\'s rank in the multi-host SPMD job',
              min_value=0)
flags.declare('MXTPU_COORD_TIMEOUT', float, 0.0,
              'Bound (seconds) on each attempt to join the '
              'jax.distributed job in parallel/multihost.init_multihost '
              '(passed as initialization_timeout). 0 (default) = jax\'s '
              'own default (5 minutes). tools/gang_supervisor.py '
              'defaults its workers to 60 (an explicit setting wins) '
              'so workers orphaned by a dead coordinator fail fast and '
              'the gang can be torn down and relaunched on a fresh '
              'port', min_value=0.0)
flags.declare('MXTPU_FAULT_HOST', int, -1,
              'Restrict the MXTPU_FAULT_INJECT fault to ONE host of a '
              'multi-process job: the fault arms only in the process '
              'whose MXTPU_HOST_ID matches (the launcher env reaches '
              'every worker of a gang, and a chaos test usually wants '
              'to lose exactly one). -1 (default) = arm wherever the '
              'env reaches', min_value=-1)
flags.declare('MXTPU_SCALARS_EVERY', int, 25,
              'Run-ledger scalar cadence (telemetry/ledger.py, requires '
              'MXTPU_TELEMETRY=1): every N trained steps one `scalars` '
              'JSONL record banks the step\'s loss, learning rate, '
              'throughput, global + worst-layer gradient statistics and '
              'MFU — the bounded per-step timeseries tools/'
              'run_compare.py diffs across runs — and the per-layer '
              'dynamics plane (MXTPU_DYNAMICS) publishes its gauges at '
              'the same decimated cadence. With MXTPU_TFEVENTS_DIR set '
              'each record also lands as native TensorBoard scalars. '
              '0 = no scalar records (the manifest still writes)',
              min_value=0)
flags.declare('MXTPU_SERVE_BIND', str, '127.0.0.1',
              'Bind address for the model-serving HTTP frontend '
              '(mxnet_tpu/serving/http.py, tools/serve_model.py). '
              'Default 127.0.0.1 = loopback only; set to \'0.0.0.0\' '
              '(or empty) to serve on all interfaces — do that only '
              'behind a load balancer / access control '
              '(docs/serving.md)')
flags.declare('MXTPU_SERVE_MAX_BATCH', int, 32,
              'Largest serving batch bucket (mxnet_tpu/serving): the '
              'engine pre-compiles one forward program per power-of-'
              'two bucket up to this size, and the dynamic batcher '
              'coalesces queued requests up to the largest bucket per '
              'dispatch. Steady-state serving then never recompiles '
              '(every request pads to a warm bucket)',
              min_value=1, max_value=65536)
flags.declare('MXTPU_SERVE_MAX_WAIT_MS', float, 5.0,
              'Longest time (milliseconds) the serving batcher holds '
              'the oldest queued request while coalescing more '
              'arrivals into one padded dispatch. A dispatch fires as '
              'soon as the largest warm bucket is full OR this '
              'deadline expires, whichever comes first — the knob '
              'trades tail latency for batch efficiency '
              '(docs/serving.md). 0 dispatches each poll immediately',
              min_value=0.0)
flags.declare('MXTPU_SERVE_SESSIONS', int, 64,
              'Session capacity of the autoregressive serving step '
              'cache (mxnet_tpu/serving/step_cache.py): per-session '
              'carried state (RNN/LSTM hidden state) lives in a '
              'device-resident ring of this many slots, evicted LRU. '
              'A decode step then dispatches ONE fixed-shape program '
              'per token batch instead of re-running the prefix '
              '(arXiv:2603.09555\'s O(1) autoregressive caching)',
              min_value=1)
flags.declare('MXTPU_FLIGHT_RECORDER', int, 2048,
              'Incident flight recorder (telemetry/flight.py, requires '
              'MXTPU_TELEMETRY=1): a fixed-size in-memory ring retaining '
              'the last N telemetry records (spans, traces, health/'
              'anomaly events) at negligible cost — no extra I/O, no '
              'thread. Every incident path (watchdog stall, non-finite '
              'incident, OOM report, SLO burn, supervised restart) dumps '
              'the ring to a flight-<reason>.jsonl next to the telemetry '
              'log, so a postmortem has the seconds BEFORE the incident '
              'without full telemetry export. Render with '
              'tools/trace_report.py. 0 = off: no ring is ever allocated',
              min_value=0, max_value=1 << 20)
flags.declare('MXTPU_SLO_LATENCY_MS', float, 0.0,
              'Serving latency objective (telemetry/slo.py, requires '
              'MXTPU_TELEMETRY=1): a request slower than this many '
              'milliseconds counts against the error budget exactly '
              'like a server-side error. Together with '
              'MXTPU_SLO_ERROR_PCT it arms the SLO plane: slo.* gauges '
              'on /metrics (burn rate, budget remaining) and an '
              '"slo_degraded" /healthz state on sustained burn — '
              'distinct from "hung" and the non-finite "degraded". '
              '0 (default) = no latency objective', min_value=0.0)
flags.declare('MXTPU_SLO_ERROR_PCT', float, 0.0,
              'Serving error budget (telemetry/slo.py): the allowed '
              'share (%) of bad requests — server-side 5xx errors plus '
              'requests over MXTPU_SLO_LATENCY_MS. The rolling burn '
              'rate is bad_share/budget; burn >= 1 sustained over the '
              'MXTPU_SLO_WINDOW flips /healthz to slo_degraded (and '
              'back when the window clears). 0 (default) = no error '
              'objective; with only the latency objective set the '
              'budget defaults to 1%', min_value=0.0, max_value=100.0)
flags.declare('MXTPU_SLO_WINDOW', int, 128,
              'Rolling request window (count) backing the SLO burn-rate '
              'computation (telemetry/slo.py): burn and the degraded '
              'verdict are computed over the most recent this-many '
              'requests, so recovery is automatic once fresh traffic '
              'meets the objectives', min_value=8)
flags.declare('MXTPU_DYNAMICS', bool, False,
              'Per-layer training dynamics (telemetry/dynamics.py, '
              'requires MXTPU_TELEMETRY=1): extend the in-graph health '
              'sentinel from one global vector to a per-parameter '
              'matrix — per-layer gradient norm, parameter norm and '
              'update ratio ||dw||/||w||, plus an activation '
              'zero-fraction per named graph output (dead-ReLU '
              'detection) — computed inside the already-compiled '
              'fused-fit window and per-batch executor programs and '
              'shipped home in the window\'s EXISTING single fetch (no '
              'added device syncs). Publishes dynamics.<layer>.* '
              'gauges + `dynamics` JSONL records at the '
              'MXTPU_SCALARS_EVERY cadence and feeds each layer\'s '
              'grad-norm/update-ratio into the MXTPU_HEALTH spike '
              'detectors so a vanishing or exploding LAYER raises a '
              'named anomaly before the global norm moves. Off (or '
              'telemetry off) = true no-op: the compiled programs are '
              'byte-identical ("Following training dynamics", '
              'docs/observability.md)')
flags.declare('MXTPU_GANG_MIN_HOSTS', int, 0,
              'Elastic floor for tools/gang_supervisor.py (read from '
              'the environment — the supervisor never imports the '
              'framework; --elastic-min-hosts overrides): when a gang '
              'relaunch is triggered by a host-loss exit (code 113) '
              'and more than this many workers remain, the gang '
              'relaunches with one fewer worker instead of the full '
              'set — reshard-on-restore + io.auto_shard re-derive '
              'shard coverage from the smaller process set. 0 '
              '(default) = never shrink: relaunches always use the '
              'full worker count', min_value=0)


_compile_cache_enabled_here = False


def enable_compile_cache():
    """Wire jax's persistent XLA compilation cache to the
    MXTPU_COMPILE_CACHE directory. Called once at package import —
    before the process's first compile, so even warmup compiles are
    cache-servable — and safe to call again after
    ``flags.reload('MXTPU_COMPILE_CACHE')`` (tests). Returns the
    directory when enabled, else None."""
    global _compile_cache_enabled_here
    path = flags.get('MXTPU_COMPILE_CACHE')
    if not path:
        # a re-call after the flag was CLEARED must actually switch the
        # cache off — but ONLY a cache THIS function enabled: a user
        # cache configured via JAX_COMPILATION_CACHE_DIR or a direct
        # jax.config.update must survive importing the package
        if _compile_cache_enabled_here:
            try:
                import jax
                jax.config.update('jax_compilation_cache_dir', None)
            except Exception:  # noqa: BLE001
                pass
            _compile_cache_enabled_here = False
        return None
    path = os.path.expanduser(path)
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        # cache every executable, not just slow-to-compile ones: the
        # flag is opt-in, and the tunneled-runtime compiles it targets
        # are exactly the ones worth never repeating. Thresholds go
        # FIRST and the directory — the on-switch — last, so a partial
        # failure leaves the cache fully off, never half-configured
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
        jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
        jax.config.update('jax_compilation_cache_dir', path)
        _compile_cache_enabled_here = True
    except Exception as e:  # noqa: BLE001 — a bad cache dir must not
        import logging      # take the process down
        logging.warning('MXTPU_COMPILE_CACHE=%s not usable: %s', path, e)
        if _compile_cache_enabled_here:
            try:
                jax.config.update('jax_compilation_cache_dir', None)
            except Exception:  # noqa: BLE001
                pass
            _compile_cache_enabled_here = False
        return None
    return path


# ---- dmlc::Parameter analog ----------------------------------------------

class _Field:
    __slots__ = ('name', 'type', 'default', 'required', 'min_value',
                 'max_value', 'choices', 'doc')

    def __init__(self, type_, default=None, required=False, min_value=None,
                 max_value=None, choices=None, doc=''):
        self.name = None  # set by ParameterMeta
        self.type = type_
        self.default = default
        self.required = required
        self.min_value = min_value
        self.max_value = max_value
        self.choices = choices
        self.doc = doc

    def check(self, value, owner):
        if value is None:
            if self.required:
                raise ValueError('%s: required parameter %r missing'
                                 % (owner, self.name))
            return self.default
        if self.type is bool and isinstance(value, str):
            value = value.strip().lower() not in ('', '0', 'false', 'no')
        elif not isinstance(value, self.type):
            try:
                value = self.type(value)
            except (TypeError, ValueError):
                raise ValueError('%s.%s=%r: expected %s'
                                 % (owner, self.name, value,
                                    self.type.__name__))
        if self.choices is not None and value not in self.choices:
            raise ValueError('%s.%s=%r: must be one of %s'
                             % (owner, self.name, value,
                                sorted(self.choices)))
        if self.min_value is not None and value < self.min_value:
            raise ValueError('%s.%s=%r: must be >= %s'
                             % (owner, self.name, value, self.min_value))
        if self.max_value is not None and value > self.max_value:
            raise ValueError('%s.%s=%r: must be <= %s'
                             % (owner, self.name, value, self.max_value))
        return value


def field(type_, default=None, **kwargs):
    """Declare a validated field on a Parameter subclass
    (DMLC_DECLARE_FIELD)."""
    return _Field(type_, default, **kwargs)


class ParameterMeta(type):
    def __new__(mcls, name, bases, ns):
        fields = {}
        for base in bases:
            fields.update(getattr(base, '_fields', {}))
        for key, val in list(ns.items()):
            if isinstance(val, _Field):
                val.name = key
                fields[key] = val
                del ns[key]
        ns['_fields'] = fields
        return super().__new__(mcls, name, bases, ns)


class Parameter(metaclass=ParameterMeta):
    """Validated option struct (dmlc::Parameter::Init).

    >>> class ConvParam(Parameter):
    ...     kernel = field(tuple, required=True)
    ...     num_filter = field(int, required=True, min_value=1)
    ...     layout = field(str, 'NCHW', choices={'NCHW', 'NHWC'})
    >>> p = ConvParam(kernel=(3, 3), num_filter=8)
    """

    def __init__(self, **kwargs):
        cls = type(self).__name__
        unknown = set(kwargs) - set(self._fields)
        if unknown:
            raise ValueError('%s: unknown parameter(s) %s'
                             % (cls, sorted(unknown)))
        for name, f in self._fields.items():
            setattr(self, name, f.check(kwargs.get(name), cls))

    def asdict(self):
        return {name: getattr(self, name) for name in self._fields}

    def __repr__(self):
        return '%s(%s)' % (type(self).__name__,
                           ', '.join('%s=%r' % kv
                                     for kv in sorted(self.asdict().items())))
