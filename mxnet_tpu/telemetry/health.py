"""Training-health sentinels: why a run went bad, not just where time went.

The rest of :mod:`mxnet_tpu.telemetry` explains *cost* (spans, per-program
FLOPs/bytes, MFU); this module explains *failure*. Whole-window compilation
(module/fused_fit.py runs W steps per device call) hides intermediate state
exactly the way whole-program TPU compilation does (Julia->TPU,
arXiv:1810.09868): a NaN born at window step 3 surfaces 29 steps later as a
garbage loss with no attribution. Three pieces fix that:

- **in-graph sentinels** (:func:`step_stats`): cheap on-device reductions —
  global grad-norm, param-norm, update/param ratio, per-output finite
  flags — packed into one small f32 vector computed INSIDE the already
  compiled programs (``executor._fwd_bwd``, the fused fit/eval scan
  bodies). The fused scan carries one vector per step, so a mid-window
  NaN is attributed to its exact step while the host still performs a
  single fetch per window;
- **first-bad-layer bisect**: on a non-finite flag, a once-per-process
  diagnostic replays the staged per-node executor path
  (:meth:`~mxnet_tpu.executor.Executor.first_nonfinite_node`) on the
  offending batch and names the first symbol whose value is non-finite
  (for a window incident the replay uses the CURRENT parameters — the
  window already ran to completion, so a poisoned weight is named
  directly);
- **anomaly detectors** (:class:`SpikeDetector`): rolling-baseline
  median/MAD detectors over step time, loss and grad-norm (spike =
  k * MAD over a trailing window) plus an input-bound classifier over
  the ``io.prefetch_wait`` spans, all emitting structured ``health`` /
  ``anomaly`` JSONL records, ``health.*`` metrics and a "Run health"
  block in the end-of-run summary table.

Gating: ``MXTPU_HEALTH=1`` *and* ``MXTPU_TELEMETRY=1``. With telemetry
off this module is a true no-op — no registry writes, no I/O, and the
compile sites trace byte-identical programs (asserted by
tests/unittest/test_health.py). ``MXTPU_HEALTH_ACTION`` picks what a
non-finite incident does: ``warn`` (default) logs it, ``record`` only
writes the JSONL record, ``raise`` raises :class:`TrainingHealthError`
with the diagnostic attached. Spike anomalies never raise — they warn
(rate-limited) or record.
"""
import collections
import logging
import threading

import numpy as np

__all__ = ['TrainingHealthError', 'enabled', 'step_stats', 'decode',
           'note_batch', 'note_step', 'note_window', 'note_step_time',
           'note_loss', 'note_restart', 'detector', 'SpikeDetector',
           'finite_report', 'has_nonfinite', 'summarize',
           'snapshot_health']

# fixed head of the sentinel vector; per-output finite flags follow
N_FIXED = 4
_IDX_FINITE, _IDX_GRAD, _IDX_PARAM, _IDX_RATIO = range(N_FIXED)

# warn-rate caps: incidents and per-detector anomalies log loudly a few
# times, then drop to debug — a fully-NaN epoch must not flood stderr
_MAX_INCIDENT_WARNINGS = 3
_MAX_ANOMALY_WARNINGS = 3
_MAX_INCIDENTS_KEPT = 16    # incident DICTS retained in memory; the
                            # counter keeps the true total

_INPUT_BOUND_PCT = 30.0   # io-wait share of step time that classifies a
                          # run as input-bound

# span families whose summed time is the input-bound denominator —
# shared with tools/telemetry_report.py's offline twin so the live and
# offline classifications can never drift apart
FUSED_FIT_LOOP_SPANS = ('fused_fit.draw', 'fused_fit.put',
                        'fused_fit.dispatch', 'fused_fit.fetch')
EVAL_LOOP_SPANS = ('eval.dispatch', 'eval.metric', 'eval.fetch',
                   'fused_eval.draw', 'fused_eval.put',
                   'fused_eval.dispatch', 'fused_eval.fetch')


class TrainingHealthError(RuntimeError):
    """Raised by MXTPU_HEALTH_ACTION=raise on a non-finite incident.
    ``diagnostic`` carries the structured incident record (source, step,
    window_step, first_bad_layer, sentinel values)."""

    def __init__(self, message, diagnostic=None):
        super().__init__(message)
        self.diagnostic = dict(diagnostic or {})


class _HState:
    __slots__ = ('decided', 'active', 'action', 'incidents', 'anomaly_counts',
                 'last_anomaly', 'bisect_done', 'incident_warnings',
                 'anomaly_warnings', 'detectors', 'input_bound_noted',
                 'cur_step', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.action = 'warn'
        self.incidents = []
        self.anomaly_counts = {}
        self.last_anomaly = None
        self.bisect_done = False
        self.incident_warnings = 0
        self.anomaly_warnings = {}
        self.detectors = {}
        self.input_bound_noted = False
        self.cur_step = None
        self.lock = threading.Lock()


_state = _HState()
_decide_lock = threading.Lock()


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        tele_on = _tele().active
        on = False
        action = 'warn'
        if tele_on:
            from ..config import flags
            try:
                flags.reload('MXTPU_HEALTH')
                flags.reload('MXTPU_HEALTH_ACTION')
                on = bool(flags.get('MXTPU_HEALTH'))
                action = flags.get('MXTPU_HEALTH_ACTION')
            except Exception:  # noqa: BLE001 — stripped builds w/o the flag
                on, action = False, 'warn'
        _state.active = on
        _state.action = action
        _state.decided = True
    return _state.active


def enabled():
    """Whether the health sentinels are on: MXTPU_TELEMETRY=1 *and*
    MXTPU_HEALTH=1, decided once (telemetry off = true no-op). Compile
    sites read this at program-build time, hot loops per step — after
    the first call it is one attribute check."""
    if _state.decided:
        return _state.active
    return _decide()


def _flag(name, default):
    from ..config import flags
    try:
        return flags.get(name)
    except Exception:  # noqa: BLE001
        return default


# ---------------------------------------------------------------------------
# in-graph sentinels
# ---------------------------------------------------------------------------

def step_stats(outs, grads=None, params=None, new_params=None):
    """The per-step sentinel vector, traced INTO a compiled program.

    Layout (f32, length ``N_FIXED + len(outs)``):

    - ``[0]`` all-finite flag: 1.0 iff every output, gradient and
      parameter statistic below is finite;
    - ``[1]`` global gradient L2 norm (0 when no grads);
    - ``[2]`` global parameter L2 norm (0 when no params);
    - ``[3]`` update/param ratio: ``||new_params - params|| / ||params||``
      when the update ran in-graph (fused fit window), else the pre-lr
      proxy ``grad_norm / param_norm`` (per-batch executor path, where
      the optimizer update runs outside this program);
    - ``[4:]`` one finite flag per output.

    A handful of full-array reductions — XLA fuses them into the
    surrounding step; the bench's sentinel-overhead probe keeps the cost
    measured (<2% on the train step).
    """
    import jax.numpy as jnp

    def _sumsq(arrs):
        total = jnp.zeros((), jnp.float32)
        for a in arrs:
            total = total + jnp.sum(jnp.square(a.astype(jnp.float32)))
        return total

    eps = jnp.float32(1e-12)
    grad_norm = jnp.sqrt(_sumsq(grads or ()))
    param_norm = jnp.sqrt(_sumsq(params or ()))
    if new_params is not None and params:
        delta = [n.astype(jnp.float32) - p.astype(jnp.float32)
                 for n, p in zip(new_params, params)]
        ratio = jnp.sqrt(_sumsq(delta)) / (param_norm + eps)
    else:
        ratio = grad_norm / (param_norm + eps)
    out_flags = [jnp.all(jnp.isfinite(o.astype(jnp.float32)))
                 .astype(jnp.float32) for o in outs]
    head_finite = (jnp.isfinite(grad_norm) & jnp.isfinite(param_norm)
                   & jnp.isfinite(ratio))
    all_finite = head_finite
    for f in out_flags:
        all_finite = all_finite & (f > 0)
    return jnp.stack([all_finite.astype(jnp.float32), grad_norm,
                      param_norm, ratio] + out_flags)


def decode(row):
    """Host-side decode of one sentinel row -> plain dict (the
    per-output finite flags are the row's tail past N_FIXED). Non-finite
    statistics decode to None (strict-JSON safe; their non-finiteness
    is already what the all_finite flag says)."""
    row = np.asarray(row, np.float64)
    flags = row[N_FIXED:]
    bad_outs = [int(i) for i, f in enumerate(flags) if not f]

    def _f(v):
        v = float(v)
        return v if np.isfinite(v) else None

    return {'all_finite': bool(row[_IDX_FINITE]),
            'grad_norm': _f(row[_IDX_GRAD]),
            'param_norm': _f(row[_IDX_PARAM]),
            'update_ratio': _f(row[_IDX_RATIO]),
            'outputs_nonfinite': bad_outs}


# ---------------------------------------------------------------------------
# incident pipeline (host side)
# ---------------------------------------------------------------------------

def _emit(rec):
    st = _tele()
    if st.active and st.sink is not None:
        st.sink.emit(rec)


def _set_gauges(info):
    reg = _tele().registry
    for k in ('grad_norm', 'param_norm', 'update_ratio'):
        v = info.get(k)
        if v is not None and np.isfinite(v):
            reg.gauge('health.%s' % k).set(round(v, 6))


def _incident(info, bisect=None):
    """One non-finite step: record it, run the once-per-process
    first-bad-layer bisect, and apply MXTPU_HEALTH_ACTION."""
    st = _tele()
    reg = st.registry
    reg.counter('health.nonfinite_steps').inc()
    run_bisect = False
    with _state.lock:
        if not _state.bisect_done:
            _state.bisect_done = True
            run_bisect = True
    if run_bisect and bisect is not None:
        try:
            bad = bisect()
        except Exception as e:  # noqa: BLE001 — diagnostics must not kill
            logging.debug('health: first-bad-layer bisect failed: %s', e)
            bad = None
        if bad is not None:
            name, out_idx = bad
            info['first_bad_layer'] = name
            info['first_bad_output'] = out_idx
    rec = {'type': 'health', 'event': 'nonfinite'}
    rec.update(info)
    _emit(rec)
    # flight recorder: the window of records BEFORE the first bad step
    # (dump-bounded per reason, so a permanently-NaN run cannot spam)
    try:
        from . import flight
        flight.dump('nonfinite', extra={'step': info.get('step')})
    except Exception:  # noqa: BLE001 — forensics must not add a crash
        pass
    with _state.lock:
        # bounded: a warn-action run that goes permanently NaN keeps
        # training and flags every bad step — count them all (the
        # counter above), keep only the first few dicts (the summary
        # renders incidents[:8] anyway)
        if len(_state.incidents) < _MAX_INCIDENTS_KEPT:
            _state.incidents.append(dict(info))
        warn_ok = _state.incident_warnings < _MAX_INCIDENT_WARNINGS
        if warn_ok:
            _state.incident_warnings += 1
    msg = ('training health: non-finite values in %s step'
           % info.get('source', '?'))
    where = info.get('step')
    if where is not None:
        msg += ' %s' % where
    if info.get('window_step') is not None:
        msg += ' (window step %d)' % info['window_step']
    if info.get('first_bad_layer'):
        msg += ' — first non-finite symbol: %s' % info['first_bad_layer']
    if info.get('outputs_nonfinite'):
        msg += ' (non-finite outputs: %s)' % info['outputs_nonfinite']
    if _state.action == 'raise':
        raise TrainingHealthError(msg, diagnostic=info)
    if _state.action == 'warn':
        if warn_ok:
            logging.warning('%s', msg)
        else:
            logging.debug('%s', msg)


def note_batch(step):
    """Publish the fit loop's CURRENT batch index (None clears it).
    The executor has no loop context, so its incidents used to carry
    ``step=None``; the per-batch fit loop (and the fused tail path)
    call this right before dispatch — only while the sentinels are on —
    and :func:`note_step` falls back to it, so executor incidents name
    the real step. fit() clears the context on exit so a later
    custom-loop incident cannot inherit a stale index."""
    _state.cur_step = None if step is None else int(step)


def note_step(hv, source='executor', step=None, bisect=None):
    """Check one step's sentinel vector (per-batch executor path). The
    fetch of ``hv`` is this path's only added device sync — the
    per-batch loop already synchronizes per batch for its metric.
    ``step=None`` falls back to the fit loop's :func:`note_batch`
    context (still None for drivers outside a fit loop)."""
    if not enabled():
        return None
    row = np.asarray(hv)
    info = decode(row)
    _set_gauges(info)
    reg = _tele().registry
    reg.counter('health.steps').inc()
    if info['grad_norm'] is not None:
        _observe('grad_norm', info['grad_norm'])
    if not info['all_finite']:
        info['source'] = source
        if step is None:
            step = _state.cur_step
        if step is not None:
            info['step'] = step
        _incident(info, bisect=bisect)
    return info


def note_window(hmat, source, nbatch_base=0, bisect=None,
                has_grads=True):
    """Check a fused window's (W, k) sentinel matrix — fetched together
    with the window's one host fetch. A non-finite step is attributed
    to its exact window step; ``bisect`` (if given) takes the bad
    window-step index and replays that batch through the staged
    executor path. ``has_grads=False`` (eval windows: forward only, the
    norm slots are structurally zero) keeps the rows out of the
    grad-norm detector and the norm gauges — an eval pass must not
    flush the TRAINING baseline with zeros."""
    if not enabled():
        return None
    mat = np.asarray(hmat)
    if mat.ndim == 1:
        mat = mat[None, :]
    reg = _tele().registry
    reg.counter('health.steps').inc(mat.shape[0])
    if has_grads:
        for row in mat:
            g = float(row[_IDX_GRAD])
            if np.isfinite(g):
                _observe('grad_norm', g)
        _set_gauges(decode(mat[-1]))
    bad_rows = np.flatnonzero(mat[:, _IDX_FINITE] == 0.0)
    if bad_rows.size == 0:
        return None
    # count EVERY bad step (the per-batch path counts per step; a
    # window with 29 bad rows is 29 bad steps, one incident)
    reg.counter('health.nonfinite_steps').inc(int(bad_rows.size) - 1)
    i = int(bad_rows[0])
    info = decode(mat[i])
    info['source'] = source
    info['step'] = nbatch_base + i
    info['window_step'] = i
    info['nonfinite_steps_in_window'] = int(bad_rows.size)
    _incident(info, bisect=(lambda: bisect(i)) if bisect is not None
              else None)
    return info


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------

class SpikeDetector:
    """Rolling-baseline spike detector: an observation is anomalous when
    it sits more than ``k`` robust deviations (MAD, floored so a
    near-constant baseline cannot alarm on noise) from the median of the
    trailing ``window`` observations. Observations — spikes included, so
    a sustained level shift stops alarming once it becomes the new
    baseline — enter the window after the test."""

    def __init__(self, name, window=None, k=None, min_count=8):
        self.name = name
        self.window = int(window if window is not None
                          else _flag('MXTPU_HEALTH_WINDOW', 64))
        self.k = float(k if k is not None else _flag('MXTPU_HEALTH_K', 8.0))
        self.min_count = min_count
        self._vals = collections.deque(maxlen=self.window)
        self._lock = threading.Lock()

    def observe(self, v):
        """Feed one observation; returns the anomaly dict (value,
        baseline, mad, k) when it spikes, else None. Non-finite values
        are ignored (the finite sentinels own those)."""
        v = float(v)
        if not np.isfinite(v):
            return None
        anomaly = None
        with self._lock:
            if len(self._vals) >= self.min_count:
                vals = np.asarray(self._vals, np.float64)
                med = float(np.median(vals))
                mad = float(np.median(np.abs(vals - med)))
                floor = max(mad, abs(med) * 0.01, 1e-9)
                if abs(v - med) > self.k * floor:
                    anomaly = {'detector': self.name, 'value': round(v, 6),
                               'baseline': round(med, 6),
                               'mad': round(mad, 6), 'k': self.k}
            self._vals.append(v)
        return anomaly


def detector(name):
    """The process-wide detector registered under ``name`` (created on
    first use with the MXTPU_HEALTH_WINDOW / MXTPU_HEALTH_K config)."""
    with _state.lock:
        d = _state.detectors.get(name)
        if d is None:
            d = _state.detectors[name] = SpikeDetector(name)
        return d


def _observe(name, value):
    """Feed a detector and publish any anomaly it returns."""
    a = detector(name).observe(value)
    if a is None:
        return None
    return publish_anomaly(a)


def publish_anomaly(a):
    """Publish one pre-built anomaly dict (counter + ``anomaly`` JSONL
    record + the last-anomaly state): the shared tail of
    :func:`_observe`, also used by detectors living in other planes —
    the memory plane's ``mem_growth`` feeds its observations itself and
    publishes only upward excursions through here."""
    name = a['detector']
    reg = _tele().registry
    reg.counter('health.anomalies').inc()
    reg.counter('health.anomalies.%s' % name).inc()
    rec = {'type': 'anomaly'}
    rec.update(a)
    _emit(rec)
    with _state.lock:
        _state.anomaly_counts[name] = _state.anomaly_counts.get(name, 0) + 1
        _state.last_anomaly = dict(a)
        n_warned = _state.anomaly_warnings.get(name, 0)
        if n_warned < _MAX_ANOMALY_WARNINGS:
            _state.anomaly_warnings[name] = n_warned + 1
    msg = ('training health: %s spike — %.6g vs rolling baseline %.6g '
           '(k=%g, MAD=%.6g)' % (name, a['value'], a['baseline'],
                                 a['k'], a['mad']))
    # spikes never raise: MXTPU_HEALTH_ACTION=raise is for non-finite
    # incidents; a noisy loss curve must not kill a healthy run
    if _state.action != 'record' and n_warned < _MAX_ANOMALY_WARNINGS:
        logging.warning('%s', msg)
    else:
        logging.debug('%s', msg)
    return a


def note_step_time(seconds, steps=1):
    """Feed the step-time detector (ms per step). The fused loop feeds
    one observation per window (wall / W)."""
    if not enabled():
        return
    ms = seconds * 1e3 / max(1, steps)
    _tele().registry.gauge('health.step_time_ms').set(round(ms, 3))
    _observe('step_time', ms)


def note_restart(attempt, reason=None, message=None, restore_step=None,
                 diagnostic=None):
    """Record one supervised-training restart (module/resilient_fit.py
    / tools/train_supervisor.py): a ``restart`` JSONL record plus the
    ``health.restarts`` counter the run-health block renders. Works
    whenever telemetry is on — a restart is a run-level event, not a
    sentinel, so it does not require MXTPU_HEALTH."""
    st = _tele()
    if not st.active:
        return
    st.registry.counter('health.restarts').inc()
    rec = {'type': 'restart', 'attempt': int(attempt)}
    if reason:
        rec['reason'] = reason
    if message:
        rec['message'] = message
    if restore_step is not None:
        rec['restore_step'] = int(restore_step)
    if diagnostic:
        rec['diagnostic'] = dict(diagnostic)
    _emit(rec)
    # flight recorder: a restart is the supervision tier's observation
    # of an unclean exit — dump what led up to it before the restore
    # wipes the in-memory trail
    try:
        from . import flight
        flight.dump('restart', extra={'attempt': int(attempt),
                                      'reason': reason})
    except Exception:  # noqa: BLE001 — forensics must not add a crash
        pass


def note_loss(value):
    """Feed the loss detector (per-batch loss value — the fused stats
    mode feeds it from the in-graph CrossEntropy sufficient statistics;
    drivers with their own loss can call this directly)."""
    if not enabled():
        return
    _observe('loss', float(value))


# ---------------------------------------------------------------------------
# monitor preset + input-bound classifier + summary
# ---------------------------------------------------------------------------

def _finite_mask(a):
    """np.isfinite with an exotic-dtype fallback (ml_dtypes bf16 etc.
    cast to f32 first); None for non-numeric arrays (always finite)."""
    try:
        return np.isfinite(a)
    except TypeError:
        try:
            return np.isfinite(a.astype(np.float32))
        except (TypeError, ValueError):
            return None


def has_nonfinite(a):
    """True when the array holds any NaN/Inf (host-side finite-flag
    check: the bisect's per-node test and finite_report's core)."""
    a = np.asarray(a)
    if a.size == 0 or a.dtype.kind in 'biu?SU':
        return False
    mask = _finite_mask(a)
    return mask is not None and not mask.all()


def finite_report(a):
    """Host half of the finite-flag sentinel, as a Monitor stat string:
    'ok' when every element is finite, else 'nan=<n> inf=<n> of <size>'.
    Used by :meth:`mxnet_tpu.monitor.Monitor.nan_watch`."""
    a = np.asarray(a)
    if not has_nonfinite(a):
        return 'ok'
    if a.dtype.kind not in 'fc':
        a = a.astype(np.float32)
    n_nan = int(np.isnan(a).sum())
    n_bad = int(a.size - _finite_mask(a).sum())
    return 'nan=%d inf=%d of %d' % (n_nan, n_bad - n_nan, int(a.size))


def input_bound_pct():
    """Share (%) of driven loop time spent waiting on the input
    pipeline: the io.prefetch_wait histogram (recorded by EVERY
    prefetching iterator, train and eval alike) against the sum of the
    fit AND eval loops' own span time — both sides must cover the same
    iterators or a slow eval feed would read as a starved train loop.
    None when the run recorded no loop time. Works whenever telemetry
    is on — independent of MXTPU_HEALTH."""
    st = _tele()
    if not st.active:
        return None
    reg = st.registry
    io_h = reg.get('io.prefetch_wait')
    if io_h is None or not io_h.count:
        return None
    batch_h = reg.get('fit.batch')
    denom = batch_h.sum if batch_h is not None else 0.0
    if not denom:
        for name in FUSED_FIT_LOOP_SPANS:
            h = reg.get(name)
            if h is not None:
                denom += h.sum
    for name in EVAL_LOOP_SPANS:
        h = reg.get(name)
        if h is not None:
            denom += h.sum
    if denom <= 0.0:
        return None
    return min(100.0, 100.0 * io_h.sum / denom)


def summarize():
    """End-of-run hook (telemetry.write_summary): publish the derived
    ``fit.input_bound_pct`` gauge (whenever telemetry is on), run the
    input-bound classifier, and return the run-health snapshot for the
    summary table / JSONL record (None while MXTPU_HEALTH is off)."""
    st = _tele()
    if not st.active:
        return None
    on = enabled()
    pct = input_bound_pct()
    if pct is not None:
        st.registry.gauge('fit.input_bound_pct').set(round(pct, 1))
    if not on:
        return None
    if pct is not None and pct >= _INPUT_BOUND_PCT:
        with _state.lock:
            first = not _state.input_bound_noted
            _state.input_bound_noted = True
        if first:
            _emit({'type': 'health', 'event': 'input_bound',
                   'input_bound_pct': round(pct, 1)})
            logging.warning(
                'training health: run is input-bound — %.1f%% of fit '
                'time spent waiting on the input pipeline '
                '(io.prefetch_wait); the accelerator is starved', pct)
    return snapshot_health(input_bound=pct)


def snapshot_health(input_bound=None):
    """Point-in-time run-health dict (JSON-serializable) — the summary
    record's ``health`` key and the summary table's input. None while
    the sentinels are off."""
    if not _state.active:
        return None
    reg = _tele().registry
    with _state.lock:
        out = {
            'nonfinite_steps': int(reg.counter(
                'health.nonfinite_steps').value),
            'incidents': [dict(i) for i in _state.incidents[:8]],
            'anomaly_counts': dict(_state.anomaly_counts),
            'last_anomaly': dict(_state.last_anomaly)
            if _state.last_anomaly else None,
            'action': _state.action,
        }
    restarts = int(reg.counter('health.restarts').value)
    if restarts:
        out['restarts'] = restarts
    hangs = int(reg.counter('watchdog.hangs').value)
    if hangs:
        out['hangs'] = hangs
    if input_bound is not None:
        out['input_bound_pct'] = round(input_bound, 1)
    return out


def _reset_for_tests():
    global _state
    _state = _HState()
