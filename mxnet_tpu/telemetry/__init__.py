"""Runtime telemetry: spans, counters, gauges — the observability layer.

The engine/executor/kvstore stack only earns "as fast as the hardware
allows" if we can see where time goes. This package is the process-wide
instrumentation the hot paths report through:

- a metrics registry (:mod:`.registry`): counters, gauges, histograms
  with recent-window p50/p95;
- a low-overhead span tracer (:func:`span`): times a host-side region
  into a histogram AND — whenever the chrome-trace profiler is running
  — into the same trace file ``profiler.py`` writes, so telemetry
  spans and engine op spans land on one timeline;
- XLA gauges (:mod:`.xla`): compile count/seconds via jax.monitoring,
  retrace-storm detection, live/peak device bytes, an MFU estimate;
- per-program cost attribution (:mod:`.programs`): every compile site
  routes through a registrar that captures XLA's cost/memory analysis
  per compiled program (``program.*`` gauges, a per-program summary
  table, the automatic step-FLOPs feed behind the MFU gauge, and an
  on-RESOURCE_EXHAUSTED memory-breakdown report);
- training-health sentinels (:mod:`.health`, MXTPU_HEALTH=1): in-graph
  NaN/Inf detection with exact-step attribution through the fused
  windows, a first-bad-layer bisect, rolling-baseline anomaly detectors
  over step time / loss / grad-norm, an input-bound classifier, and a
  "Run health" block in the end-of-run summary (``health`` /
  ``anomaly`` JSONL records, ``MXTPU_HEALTH_ACTION={warn,record,raise}``);
- exporters (:mod:`.export`): an append-only JSONL log (host-stamped,
  size-capped via ``MXTPU_TELEMETRY_MAX_MB``) plus an end-of-run
  human-readable summary table (``tools/telemetry_report.py`` renders
  one or many per-host logs offline);
- the live plane (:mod:`.serve`, ``MXTPU_TELEMETRY_PORT``): a
  background HTTP endpoint exposing ``/metrics`` (Prometheus text),
  ``/healthz`` (200/503 from the health incident state) and
  ``/summary`` (snapshot JSON) — ``tools/telemetry_watch.py`` renders
  it as a refreshing dashboard;
- cluster aggregation (:mod:`.cluster`, ``MXTPU_TELEMETRY_SYNC_EVERY``):
  every N steps one small off-graph allgather carries each host's key
  gauges; process 0 publishes ``cluster.*`` per-host gauges, the
  step-time spread, the slowest-host id and a straggler classification
  (input-bound vs compute-bound). With ``MXTPU_ELASTIC_INPUT`` every
  host additionally derives the same shard-shift decision from the
  same gathered round and re-balances input shards away from an
  input-bound host at the next epoch boundary;
- request-level tracing (:mod:`.trace`): one trace id per serving
  request (minted, or client-supplied via ``X-Request-Id`` /
  ``traceparent``), a queue/coalesce/pad/dispatch/fetch/split stage
  breakdown per request as a ``trace`` JSONL record (N coalesced
  requests share ONE dispatch span id), exemplar trace ids on the
  ``serve.request_latency`` /metrics summary, and the request's spans
  merged into the chrome-trace timeline when the profiler runs;
- the SLO plane (:mod:`.slo`, ``MXTPU_SLO_LATENCY_MS`` /
  ``MXTPU_SLO_ERROR_PCT``): rolling error-budget burn rate over the
  serving request stream, ``slo.*`` gauges on ``/metrics``, and an
  ``slo_degraded`` /healthz state (distinct from hung/non-finite) on
  sustained burn, clearing on recovery;
- the incident flight recorder (:mod:`.flight`,
  ``MXTPU_FLIGHT_RECORDER``, default on with telemetry): a bounded
  in-memory ring of the most recent records, dumped to
  ``flight-<reason>.jsonl`` by every incident path — watchdog stall,
  non-finite incident, OOM report, SLO burn, supervised restart —
  so a postmortem has the seconds BEFORE the incident
  (``tools/trace_report.py`` renders a dump);
- per-layer training dynamics (:mod:`.dynamics`, ``MXTPU_DYNAMICS``):
  the in-graph sentinel extended from one global vector to a
  per-parameter matrix — per-layer grad-norm, param-norm, update
  ratio ``||dw||/||w||`` and activation zero-fractions on named
  outputs — computed inside the compiled fused window / executor
  programs and shipped home in the window's existing single fetch;
  per-layer spike detectors raise NAMED anomalies, non-finite layer
  statistics raise named-layer ``dynamics`` incidents, and
  ``dynamics.<layer>.*`` gauges publish at the decimated
  ``MXTPU_SCALARS_EVERY`` cadence;
- the run ledger (:mod:`.ledger`, ``MXTPU_SCALARS_EVERY``): a
  ``manifest`` JSONL record (resolved flags, jax version, device kind,
  mesh, git sha) plus a bounded per-step ``scalars`` timeseries (loss,
  lr, throughput, grad stats, eval metrics, MFU), mirrored as native
  TensorBoard event files through a dependency-free TFRecord/Event
  writer when ``MXTPU_TFEVENTS_DIR`` is set —
  ``tools/run_compare.py`` diffs two runs' ledgers with
  bench_diff-style verdicts;
- the hang watchdog (:mod:`.watchdog`, ``MXTPU_WATCHDOG_SECS``):
  a daemon-thread progress monitor fed by the hot loops' dispatch /
  sync / kvstore / checkpoint sites; a stall dumps all-thread stacks
  as a ``hang`` JSONL incident, flips ``/healthz`` to a 503 ``hung``
  digest, and (``MXTPU_WATCHDOG_ACTION=abort``) exits with the
  distinct code 85 so the supervisor relaunches from last-good.

Everything is OFF by default. ``MXTPU_TELEMETRY=1`` turns it on;
``MXTPU_TELEMETRY_PATH`` points the JSONL log (default
``telemetry.jsonl``). While off, every entry point degrades to a
shared no-op object — zero I/O, no registry writes, one cached-bool
check per call site (asserted by tests/unittest/test_telemetry.py).

Instrumented sites (the names to grep for in the log):
``fit.batch`` / ``fit.dispatch`` / ``fit.metric`` / ``fit.callback``
(reference per-batch loop), ``fused_fit.draw|put|dispatch|fetch|build``
+ gauge ``fused_fit.steps_per_call`` (compiled window loop),
``eval.dispatch|metric|fetch`` + counter ``eval.batches`` + gauge
``eval_samples_per_sec`` (per-batch score/predict loops),
``fused_eval.draw|put|dispatch|fetch|build`` + counter
``fused_eval.windows`` + gauge ``fused_eval.steps_per_call`` (compiled
eval window loop), ``executor.forward|backward``,
``exec_group.forward|backward``, ``module.update``, histogram
``io.prefetch_wait`` + counter ``io.batches``, ``kvstore.push|pull``
spans + ``kvstore.push_bytes`` / ``kvstore.pull_bytes`` counters,
gauge ``speedometer.samples_per_sec``, the ``xla.*`` compile/memory
metrics, and — with MXTPU_COMPILE_CACHE set — ``xla.cache_hits`` /
``xla.cache_saved_secs`` for compiles served from the persistent
cache. The serving plane (mxnet_tpu/serving) reports through the same
registry: ``serve.request_latency`` histogram + ``serve.requests`` /
``serve.errors`` / ``serve.dispatches`` counters, queue/batch/pad
gauges, and ``serve.decode_steps`` for the autoregressive step cache
(docs/serving.md).
"""
import atexit
import logging
import os
import threading
import time

from .registry import (Registry, NULL_COUNTER, NULL_GAUGE, NULL_HISTOGRAM)
from . import export as _export
from . import xla  # noqa: F401  (public submodule: telemetry.xla.*)
from . import programs  # noqa: F401  (public submodule: telemetry.programs.*)
from . import health  # noqa: F401  (public submodule: telemetry.health.*)
from . import cluster  # noqa: F401  (public submodule: telemetry.cluster.*)
from . import serve  # noqa: F401  (public submodule: telemetry.serve.*)
from . import roofline  # noqa: F401  (public submodule: telemetry.roofline.*)
from . import watchdog  # noqa: F401  (public submodule: telemetry.watchdog.*)
from . import trace  # noqa: F401  (public submodule: telemetry.trace.*)
from . import slo  # noqa: F401  (public submodule: telemetry.slo.*)
from . import flight  # noqa: F401  (public submodule: telemetry.flight.*)
from . import dynamics  # noqa: F401  (public submodule: telemetry.dynamics.*)
from . import ledger  # noqa: F401  (public submodule: telemetry.ledger.*)
from . import goodput  # noqa: F401  (public submodule: telemetry.goodput.*)
from . import memory  # noqa: F401  (public submodule: telemetry.memory.*)
from . import timeline  # noqa: F401  (public submodule: telemetry.timeline.*)

__all__ = ['enabled', 'counter', 'gauge', 'histogram', 'span', 'event',
           'snapshot', 'summary', 'write_summary', 'shutdown', 'xla',
           'programs', 'health', 'cluster', 'serve', 'roofline',
           'watchdog', 'trace', 'slo', 'flight', 'dynamics', 'ledger',
           'goodput', 'memory', 'timeline', 'get_registry']


class _State:
    __slots__ = ('decided', 'active', 'registry', 'sink', 't_start',
                 'retraces', 'lock', 'summary_written')

    def __init__(self):
        self.decided = False
        self.active = False
        self.registry = Registry()
        self.sink = None
        self.t_start = None
        self.retraces = {}
        self.lock = threading.Lock()
        self.summary_written = False


_state = _State()
_decide_lock = threading.Lock()
_atexit_registered = False


def _decide():
    global _atexit_registered
    with _decide_lock:
        if _state.decided:
            return _state.active
        from ..config import flags
        try:
            on = bool(flags.get('MXTPU_TELEMETRY'))
        except Exception:  # noqa: BLE001 — stripped builds without the flag
            on = False
        _state.active = on
        _state.decided = True
        if on:
            _state.t_start = time.time()
            from ..config import flags as _flags
            try:
                path = _flags.get('MXTPU_TELEMETRY_PATH')
            except Exception:  # noqa: BLE001
                path = ''
            path = os.path.expanduser(path or 'telemetry.jsonl')
            try:
                _flags.reload('MXTPU_TELEMETRY_MAX_MB')
                max_mb = float(_flags.get('MXTPU_TELEMETRY_MAX_MB'))
            except Exception:  # noqa: BLE001
                max_mb = 0.0
            try:
                _state.sink = _export.JsonlSink(
                    path,
                    max_bytes=int(max_mb * 2**20) if max_mb else None)
                # every record carries this process's host index so
                # multi-host logs merge on it (telemetry/cluster.py)
                _state.sink.host = cluster.host_index()
                _state.sink.emit({'type': 'start', 'pid': os.getpid(),
                                  'path': path})
            except OSError as e:
                logging.warning('telemetry: cannot open %s (%s) — metrics '
                                'stay in-process, no JSONL log', path, e)
                _state.sink = None
            xla.install()
            # live endpoint (telemetry/serve.py): only with
            # MXTPU_TELEMETRY_PORT set — port unset = no thread/socket
            serve.maybe_start()
            if not _atexit_registered:
                _atexit_registered = True
                atexit.register(shutdown)
    return _state.active


def enabled():
    """Whether telemetry is on (decided once from MXTPU_TELEMETRY; the
    first True decision opens the sink and installs the XLA listener).
    Hot call sites rely on this being one attribute check after the
    first call."""
    if _state.decided:
        return _state.active
    return _decide()


def get_registry():
    return _state.registry


def counter(name):
    """Live counter when enabled, shared no-op otherwise."""
    return _state.registry.counter(name) if enabled() else NULL_COUNTER


def gauge(name):
    return _state.registry.gauge(name) if enabled() else NULL_GAUGE


def histogram(name):
    return _state.registry.histogram(name) if enabled() else NULL_HISTOGRAM


# -- span tracer -------------------------------------------------------------

_TLS = threading.local()


def _stack():
    st = getattr(_TLS, 'stack', None)
    if st is None:
        st = _TLS.stack = []
    return st


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    """Times a host region: histogram (ms) + JSONL record, and a
    chrome-trace event whenever the profiler is running. Nesting is
    tracked per-thread; the JSONL record carries the full path
    ('fit.batch/fit.dispatch') so traces reconstruct the tree."""

    __slots__ = ('name', 'cat', 't0', 'path')

    def __init__(self, name, category):
        self.name = name
        self.cat = category

    def __enter__(self):
        stack = _stack()
        self.path = (stack[-1].path + '/' + self.name) if stack else self.name
        stack.append(self)
        self.t0 = time.time()
        return self

    def __exit__(self, *a):
        t1 = time.time()
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:           # unwound out of order (exception)
            stack.remove(self)
        dur_ms = (t1 - self.t0) * 1e3
        st = _state
        if st.active:
            st.registry.histogram(self.name).observe(dur_ms)
            # step-phase ledger (MXTPU_TIMELINE): leaf phase spans
            # bucket into per-phase accumulators — one cached bool off
            if timeline.enabled():
                timeline.note_span(self.name, dur_ms)
            if st.sink is not None:
                st.sink.emit({'type': 'span', 'name': self.name,
                              'path': self.path, 't': self.t0,
                              'dur_ms': round(dur_ms, 4)})
        from .. import profiler as _profiler
        if _profiler.is_running():
            _profiler.record_event(self.name, int(self.t0 * 1e6),
                                   int(t1 * 1e6), self.cat)


def span(name, category='telemetry'):
    """Context manager timing a host-side region.

    Enabled telemetry: records a histogram observation (ms) under
    ``name`` and appends a JSONL span record. Running profiler: emits a
    chrome-trace event into profiler.py's timeline (this works even
    with telemetry off, replacing profiler.maybe_span at call sites).
    Neither: returns the shared no-op."""
    if enabled():
        return _Span(name, category)
    from .. import profiler as _profiler
    if _profiler.is_running():
        return _Span(name, category)   # chrome-trace only; exit skips st
    return _NULL_SPAN


def current_span_path():
    """Dotted path of the innermost open span on this thread (tests)."""
    stack = getattr(_TLS, 'stack', None)
    return stack[-1].path if stack else None


def event(name, **fields):
    """Append an ad-hoc JSONL record (type='event')."""
    if enabled() and _state.sink is not None:
        rec = {'type': 'event', 'name': name}
        rec.update(fields)
        _state.sink.emit(rec)


# -- summary / shutdown ------------------------------------------------------

def snapshot():
    return _state.registry.snapshot()


def summary():
    """The human-readable end-of-run table, as a string. Renders the
    same Run health block write_summary() does — including the
    input-bound share — but read-only: no gauges are written, no
    classifier record is emitted."""
    elapsed = (time.time() - _state.t_start) if _state.t_start else None
    return _export.summary_table(_state.registry.snapshot(), elapsed,
                                 programs=programs.snapshot_programs()
                                 or None,
                                 health=health.snapshot_health(
                                     input_bound=health.input_bound_pct()),
                                 cluster=cluster.snapshot_cluster(),
                                 roofline=roofline.snapshot_roofline(),
                                 ledger=ledger.snapshot_ledger(),
                                 goodput=goodput.current(),
                                 memory=memory.snapshot_memory(),
                                 timeline=timeline.snapshot_timeline())


def write_summary(log=True):
    """Sample the XLA gauges one last time, append the JSONL summary
    record, and (by default) log the table. Returns the table string,
    or None when telemetry is off."""
    if not enabled():
        return None
    xla.sample_memory()
    mfu = xla.mfu_estimate()
    if mfu is not None:
        _state.registry.gauge('xla.mfu').set(round(mfu, 4))
    # run-health roll-up: publishes the derived fit.input_bound_pct
    # gauge and (with MXTPU_HEALTH=1) returns the "Run health" block's
    # input + the summary record's 'health' key
    hsnap = health.summarize()
    # roofline attribution (MXTPU_ROOFLINE): publishes roofline.*
    # gauges + the roofline JSONL record; must run before the snapshot
    # below so the gauges land in the summary record too
    rsnap = roofline.summarize()
    # memory attribution + forecast (MXTPU_MEMORY): publishes mem.*
    # gauges + the full memory JSONL record, same contract as roofline
    msnap = memory.summarize()
    csnap = cluster.snapshot_cluster()
    lsnap = ledger.snapshot_ledger()
    elapsed = time.time() - _state.t_start
    # wall-clock attribution: publishes goodput.* gauges + the goodput
    # JSONL record; after roofline (the comm bucket reads its published
    # provenance-labeled share) and before the snapshot below so the
    # gauges land in the summary record too
    gsnap = goodput.summarize(elapsed)
    # pod step timeline (MXTPU_TIMELINE): the last sync round's
    # critical-path attribution, or a local one on a run that never
    # synced — publishes timeline.* gauges + the timeline JSONL record
    # before the snapshot below so the gauges land in the summary too
    tsnap = timeline.summarize()
    snap = _state.registry.snapshot()
    progs = programs.snapshot_programs()
    if _state.sink is not None:
        rec = {'type': 'summary', 'elapsed_s': round(elapsed, 3),
               'snapshot': snap}
        if progs:
            rec['programs'] = progs
        if hsnap:
            rec['health'] = hsnap
        if csnap:
            rec['cluster'] = csnap
        if rsnap:
            rec['roofline'] = rsnap
        if lsnap:
            rec['ledger'] = lsnap
        if gsnap:
            rec['goodput'] = gsnap
        if msnap:
            rec['memory'] = msnap
        if tsnap:
            rec['timeline'] = tsnap
        _state.sink.emit(rec)
        _state.sink.flush()
    table = _export.summary_table(snap, elapsed, programs=progs or None,
                                  health=hsnap, cluster=csnap,
                                  roofline=rsnap, ledger=lsnap,
                                  goodput=gsnap, memory=msnap,
                                  timeline=tsnap)
    if log:
        logging.info('%s', table)
    _state.summary_written = True
    return table


def shutdown():
    """atexit hook: final summary + sink close. Idempotent — and when
    the program already called write_summary() itself, that record IS
    the end-of-run summary: no duplicate is appended here."""
    st = _state
    if not st.active:
        return
    if not st.summary_written:
        try:
            write_summary()
        except Exception:  # noqa: BLE001 — an atexit hook must not raise
            pass
    if st.sink is not None:
        try:
            st.sink.close()
        except Exception:  # noqa: BLE001
            pass
        st.sink = None
    serve.stop()
    st.active = False


def _reset_for_tests():
    """Close the current epoch of telemetry state and re-read the flags
    on next use (tests toggle MXTPU_TELEMETRY via monkeypatch +
    config.flags.reload)."""
    global _state
    if _state.sink is not None:
        try:
            _state.sink.close()
        except Exception:  # noqa: BLE001
            pass
    serve.stop()
    _state = _State()
    programs._reset_for_tests()
    health._reset_for_tests()
    cluster._reset_for_tests()
    roofline._reset_for_tests()
    watchdog._reset_for_tests()
    slo._reset_for_tests()
    flight._reset_for_tests()
    dynamics._reset_for_tests()
    ledger._reset_for_tests()
    goodput._reset_for_tests()
    memory._reset_for_tests()
    timeline._reset_for_tests()
    try:
        from ..parallel import compression
        compression._reset_for_tests()
    except Exception:  # noqa: BLE001 — parallel may not be importable
        pass
