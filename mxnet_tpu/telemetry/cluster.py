"""Multi-host telemetry aggregation: host stamping + straggler naming.

``parallel/multihost.py`` runs lockstep SPMD data-parallel jobs where
every collective is gated by the slowest host (cf. "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" for
why the update is lockstep, and EQuARX's motivation that allreduce
time dominates at scale) — yet per-process telemetry cannot NAME that
host. This module is the cluster half of the telemetry plane:

- **host stamping**: every JSONL record (telemetry/export.py) and every
  ``/metrics`` sample (telemetry/serve.py) carries ``host=<process
  index>``, so merged logs and scraped series attribute to a machine;
- **sync rounds**: every ``MXTPU_TELEMETRY_SYNC_EVERY`` training steps
  (fed by the per-batch fit loop and the fused-fit window — OFF the hot
  path by default, and off-sync steps cost one clock read + a deque
  append, no device work) each host contributes a small vector of key
  gauges — step-time p50, io-wait share, dispatch-span p50, live device
  bytes, and (with MXTPU_ROOFLINE) the roofline's collective share of
  the step — to ONE off-graph allgather (jax multihost_utils over the
  global mesh);
- **publication**: process 0 turns the gathered matrix into
  ``cluster.*`` gauges (per-host rows, step-time spread, slowest-host
  id, straggler classification — input-bound via the health module's
  io-wait classifier, communication-bound via the roofline's measured
  per-collective step share, compute-bound otherwise), a ``cluster``
  JSONL record, the
  "Cluster" block of the summary table, and the ``/metrics`` scrape.

Gating: ``MXTPU_TELEMETRY=1`` *and* ``MXTPU_TELEMETRY_SYNC_EVERY>0``.
While off, :func:`note_step` is one cached-bool check and the fit
loops never branch further (asserted by tests/unittest/test_serve.py).

LOCKSTEP REQUIREMENT: the sync is a collective, and the fire decision
is each host's local step count crossing the cadence — correct for
the SPMD jobs this framework runs multi-host (one global program,
every process advances the same global step). A driver that steps
hosts UNEQUALLY (per-host iterators of different lengths) would
diverge the collective schedule and hang at the next allgather; keep
the cadence off (the default) for such topologies.
"""
import collections
import logging
import threading
import time

import numpy as np

__all__ = ['enabled', 'host_index', 'set_host', 'note_step', 'sync_now',
           'snapshot_cluster', 'classify', 'round_verdict', 'SYNC_KEYS',
           'elastic_enabled', 'shard_shift', 'apply_shard_shift']

# slots of the per-host sync vector, in order ('comm_pct' — the
# roofline's collective share of the step — is NaN/omitted unless
# MXTPU_ROOFLINE runs; rows from an older sender with fewer slots are
# padded with NaN at publish). 'proc_index' carries each sender's TRUE
# jax.process_index(), proven on a real 2-process DCN job
# (tests/dist/gang_fit.py): the per-host gauges and /metrics series on
# process 0 are keyed off it instead of assuming the gathered row
# order is process order; rows without the slot (older senders,
# crafted test matrices) fall back to the positional index. The three
# trailing slots rode in with the goodput plane (appended AFTER
# proc_index so every earlier position is stable): 'goodput_pct' is the
# host's productive wall share, 'badput_top' its top badput bucket as a
# telemetry.goodput.BUCKETS index, and 'comm_src' the comm_pct sample's
# provenance (1.0 = measured from a joined trace, 0.0 = roofline
# modeled, NaN = no sample) — so the communication_bound verdict can
# never launder a model into a measurement. 'mem_headroom_pct' rode in
# with the memory plane (appended at the end, same stability rule):
# each host's latest device-byte headroom %, NaN while MXTPU_MEMORY is
# off or no sample carries a byte limit — process 0 names the most
# memory-pressured host from it. The eight trailing slots rode in with
# the timeline plane (appended at the end, same stability rule):
# 'clock_wall_s'/'clock_mono_s' are the clock pair each host sampled at
# the PREVIOUS round's allgather exit (the barrier exit is the shared
# time reference — zero new collectives) and 'tl_*_ms' the per-step
# phase milliseconds of its step-phase ledger over the round window;
# all NaN while MXTPU_TIMELINE is off. They feed process 0's clock-
# offset rings and critical-path attribution (telemetry/timeline.py),
# NOT the per-host cluster record rows (_TL_SLOTS below skips them)
SYNC_KEYS = ('step_time_ms', 'io_wait_pct', 'dispatch_ms', 'live_bytes',
             'comm_pct', 'proc_index', 'goodput_pct', 'badput_top',
             'comm_src', 'mem_headroom_pct',
             'clock_wall_s', 'clock_mono_s', 'tl_draw_ms', 'tl_put_ms',
             'tl_dispatch_ms', 'tl_fetch_ms', 'tl_ckpt_ms', 'tl_kv_ms')

# the timeline plane's slots: carried in the vector for the allgather,
# published through the separate 'timeline' record/gauges — raw clock
# epochs and ledger fragments in every per-host cluster row would be
# noise (telemetry/timeline.py asserts this slice matches its SLOTS)
_TL_SLOTS = frozenset(SYNC_KEYS[10:])

_SPREAD_BALANCED_PCT = 5.0   # step-time spread below this = no straggler
_COMM_BOUND_PCT = 30.0       # collective share of the step above which a
                             # straggling host reads communication_bound
_RING = 128                  # recent per-step wall samples backing the p50


class _CState:
    __slots__ = ('decided', 'active', 'every', 'since', 'steps', 'last_t',
                 'ring', 'snapshot', 'lock', 'elastic', 'shift', 'applied',
                 'last_shift', 'shift_warned')

    def __init__(self):
        self.decided = False
        self.active = False
        self.every = 0
        self.since = 0
        self.steps = 0
        self.last_t = None
        self.ring = collections.deque(maxlen=_RING)
        self.snapshot = None
        self.lock = threading.Lock()
        # MXTPU_ELASTIC_INPUT: the global shard-shift counter every host
        # derives identically from the same gathered sync rounds, and
        # how much of it this host has applied to its iterator
        self.elastic = False
        self.shift = 0
        self.applied = 0
        self.last_shift = None   # {'step', 'input_bound_host', 'shift'}
        self.shift_warned = False


_state = _CState()
_decide_lock = threading.Lock()
_host = None


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def host_index():
    """This process's host id. Read from the launcher env
    (MXTPU_HOST_ID) — NOT jax.process_index() — so stamping the JSONL
    sink at telemetry-decide time can never initialize the jax backend
    before jax.distributed is up. ``init_multihost`` pins the
    authoritative index via :func:`set_host` once the job is joined."""
    global _host
    if _host is None:
        try:
            from ..config import flags
            _host = int(flags.get('MXTPU_HOST_ID'))
        except Exception:  # noqa: BLE001 — stripped builds without the flag
            _host = 0
    return _host


def set_host(idx):
    """Pin the host id (called by parallel/multihost.py after
    jax.distributed init) and restamp the open JSONL sink."""
    global _host
    _host = int(idx)
    st = _tele()
    if st.sink is not None:
        st.sink.host = _host


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        on = False
        every = 0
        elastic = False
        if _tele().active:
            from ..config import flags
            try:
                flags.reload('MXTPU_TELEMETRY_SYNC_EVERY')
                every = int(flags.get('MXTPU_TELEMETRY_SYNC_EVERY'))
            except Exception:  # noqa: BLE001
                every = 0
            on = every > 0
            if on:
                try:
                    flags.reload('MXTPU_ELASTIC_INPUT')
                    elastic = bool(flags.get('MXTPU_ELASTIC_INPUT'))
                except Exception:  # noqa: BLE001
                    elastic = False
        _state.active = on
        _state.every = every
        _state.elastic = elastic
        _state.decided = True
    return _state.active


def enabled():
    """Whether cluster sync rounds are on: MXTPU_TELEMETRY=1 *and*
    MXTPU_TELEMETRY_SYNC_EVERY>0, decided once. After the first call it
    is one attribute check — the fit loops' gate."""
    if _state.decided:
        return _state.active
    return _decide()


def note_step(steps=1):
    """Hot-path hook: one call per trained batch (per-batch loop) or
    per dispatched window (fused loop, ``steps=W``). Off-sync steps do
    host bookkeeping only — a clock read and a deque append; the
    allgather (the only collective, and the only device-touching work)
    fires every MXTPU_TELEMETRY_SYNC_EVERY steps."""
    if not enabled():
        return
    st = _state
    now = time.time()
    fire = False
    with st.lock:
        if st.last_t is not None and steps > 0:
            st.ring.append((now - st.last_t) * 1e3 / steps)
        st.last_t = now
        st.steps += steps
        st.since += steps
        if st.since >= st.every:
            st.since = 0
            fire = True
    if fire:
        sync_now()


def _local_stats():
    """This host's sync vector (SYNC_KEYS order)."""
    reg = _tele().registry
    with _state.lock:
        ring = list(_state.ring)
    # no completed step interval yet (a sync round can fire before the
    # 2nd note_step): ship NaN so the aggregation marks the sample
    # unavailable instead of publishing a fake 0ms step time
    step_ms = float(np.median(ring)) if ring else float('nan')
    from . import health
    io_pct = health.input_bound_pct() or 0.0
    disp = 0.0
    h = reg.get('fit.dispatch')
    if h is not None and h.count:
        disp = h.percentile(50) or 0.0
    else:
        h = reg.get('fused_fit.dispatch')
        if h is not None and h.count:
            disp = h.percentile(50) or 0.0
            w = reg.get('fused_fit.steps_per_call')
            if w is not None and w.value:
                # the fused histogram records one observation per
                # W-step window; normalize so dispatch_ms is per-step,
                # commensurate with step_time_ms in the same row
                disp /= float(w.value)
    live_g = reg.get('xla.bytes_in_use')
    live = float(live_g.value) if live_g is not None and live_g.value else 0.0
    # the roofline's per-collective accounting (MXTPU_ROOFLINE): the
    # share of the step spent in all-reduce/all-gather/… — what grounds
    # a communication_bound straggler verdict in numbers instead of
    # inference. NaN = unavailable (flag off / nothing ingested yet)
    from . import roofline
    comm, comm_src = roofline.comm_share()
    try:
        import jax
        proc = float(jax.process_index())
    except Exception:  # noqa: BLE001 — backend not up
        proc = float(host_index())
    # the goodput plane's contribution: this host's productive wall
    # share and its top badput bucket (as a BUCKETS index) — what lets
    # a gang round report fleet goodput = the slowest host's with the
    # per-bucket culprit named
    from . import goodput
    good_pct, badput_idx = goodput.local_stats()
    # the memory plane's contribution: this host's latest headroom %
    # (NaN while off / no limit) — the fleet's min names the most
    # memory-pressured host
    from . import memory
    # the timeline plane's contribution (MXTPU_TIMELINE): the clock
    # pair sampled at the previous round's barrier exit + the per-step
    # phase ledger over the round window — all NaN while off
    from . import timeline
    return [step_ms, float(io_pct), float(disp), live,
            float(comm) if comm is not None else float('nan'), proc,
            good_pct, badput_idx,
            float('nan') if comm_src is None
            else (1.0 if comm_src == 'measured' else 0.0),
            memory.local_headroom()] + timeline.local_slots()


def _allgather(vals):
    """One small off-graph allgather over the global mesh; returns an
    (n_hosts, len(SYNC_KEYS)) float array. Single-process jobs come
    back as one row (older jax returns the input unchanged there)."""
    import jax
    from jax.experimental import multihost_utils
    arr = np.asarray(vals, np.float32)
    out = np.asarray(multihost_utils.process_allgather(arr))
    if out.ndim == arr.ndim:
        out = out[None, :]
    return out.reshape(max(1, jax.process_count()), -1)


def _host_ids(mat):
    """Row index -> host id for one gathered matrix: the proc_index
    slot when the sender carried it, else the positional fallback."""
    mat = np.asarray(mat, np.float64)
    idx = SYNC_KEYS.index('proc_index')
    ids = []
    for i in range(mat.shape[0]):
        v = float(mat[i, idx]) if idx < mat.shape[1] else float('nan')
        ids.append(int(v) if np.isfinite(v) else i)
    return ids


def round_verdict(mat):
    """(slowest_row, spread_pct, verdict) for one gathered matrix —
    the ONE implementation of the per-round straggler math, shared by
    the publication path (:func:`_publish`) and the elastic-input
    decision (:func:`_elastic_decide`) so the published verdict and the
    re-balance decision can never disagree on the same round.
    ``slowest_row`` is a ROW index (callers map to a host id via
    :func:`_host_ids`), or None when no host has a valid step time."""
    mat = np.asarray(mat, np.float64)
    times = mat[:, 0]
    valid = ~np.isnan(times)
    if not valid.any():
        return None, 0.0, 'balanced'
    t = np.where(valid, times, 0.0)
    slowest = int(np.argmax(t))
    med = float(np.median(t[valid]))
    tmax = float(t[valid].max())
    tmin = float(t[valid].min())
    spread = ((tmax - tmin) / med * 100.0) if med > 0 else 0.0
    if mat.shape[0] == 1 or spread < _SPREAD_BALANCED_PCT:
        return slowest, spread, 'balanced'
    comm_v = float(mat[slowest, 4]) if mat.shape[1] > 4 else float('nan')
    verdict = classify(float(mat[slowest, 1]),
                       None if np.isnan(comm_v) else comm_v)
    return slowest, spread, verdict


def classify(io_wait_pct, comm_pct=None):
    """The straggler classification for one host: where its time goes.
    Reuses the health module's input-bound threshold so the live
    cluster view and the end-of-run classifier agree; a host that is
    not input-bound but spends >= ``_COMM_BOUND_PCT`` of its step in
    collectives (the roofline's per-collective accounting, when
    MXTPU_ROOFLINE measured one) reads ``communication_bound`` — the
    verdict the quantized-collectives work keys off."""
    from .health import _INPUT_BOUND_PCT
    if (io_wait_pct or 0.0) >= _INPUT_BOUND_PCT:
        return 'input_bound'
    if comm_pct is not None and comm_pct >= _COMM_BOUND_PCT:
        return 'communication_bound'
    return 'compute_bound'


def sync_now():
    """Run one aggregation round now (the every-N hook's body; callable
    directly from tests/tools). All hosts contribute; process 0
    publishes. Returns the published snapshot on process 0, else
    None."""
    if not enabled():
        return None
    st = _tele()
    st.registry.counter('cluster.syncs').inc()
    # refresh the roofline.* gauges at the sync cadence (read-only
    # modeled analysis, no JSONL record) so mid-run /metrics scrapes —
    # and this round's own comm_pct slot — see live roofline state
    # instead of the values frozen at the last write_summary()
    from . import roofline
    try:
        roofline.republish()
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry.cluster: roofline republish failed: %s',
                      e)
    # same contract for the memory plane's mem.* gauges (read-only
    # analysis, no JSONL record)
    from . import memory
    try:
        memory.republish()
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry.cluster: memory republish failed: %s',
                      e)
    try:
        mat = _allgather(_local_stats())
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry.cluster: sync failed: %s', e)
        return None
    # the allgather is a barrier, so the instant it returns is the same
    # true time on every host — the clock sample the timeline plane
    # ships in the NEXT round's vector (MXTPU_TIMELINE; no-op while off)
    from . import timeline as _timeline
    try:
        _timeline.note_sync_exit()
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry.cluster: timeline clock sample failed: '
                      '%s', e)
    from . import watchdog as _watchdog
    _watchdog.note_progress('cluster.sync')
    with _state.lock:
        steps = _state.steps
    # elastic input re-balancing decides on EVERY host (the gathered
    # matrix is identical everywhere, so every host derives the same
    # shift) — the process-0 gate below only guards publication
    _elastic_decide(mat, steps)
    # gradient-compression auto trigger: MXTPU_GRAD_COMPRESS=auto
    # flips to int8 when a round reads communication_bound. Decided on
    # EVERY host from the identical matrix (same contract as the
    # elastic decision) — no extra collective, and every gang member
    # rebuilds its window program at the same dispatch edge
    try:
        from ..parallel import compression
        compression.note_round_verdict(round_verdict(mat)[2])
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry.cluster: compression trigger failed: '
                      '%s', e)
    try:
        import jax
        me = jax.process_index()
    except Exception:  # noqa: BLE001
        me = host_index()
    if me != 0:
        return None
    return _publish(mat, steps)


def _publish(mat, steps):
    """Turn one gathered (n_hosts, k) matrix into cluster.* gauges, a
    JSONL record and the snapshot the summary table / endpoints read."""
    st = _tele()
    reg = st.registry
    mat = np.asarray(mat, np.float64)
    n = mat.shape[0]
    host_ids = _host_ids(mat)
    per_host = []
    for i in range(n):
        # gauges/rows keyed by the row's OWN process index (carried in
        # the proc_index slot), not its gathered position — the real
        # 2-process drive pins the two agree, and a transport that ever
        # reordered rows could not silently swap two hosts' series
        hid = host_ids[i]
        row = {'host': hid}
        for j, key in enumerate(SYNC_KEYS):
            if key == 'proc_index':
                continue        # identity, already the 'host' field
            if key in _TL_SLOTS:
                continue        # the timeline plane's raw slots: they
                                # publish through publish_round below
            # rows shorter than SYNC_KEYS (a crafted test matrix, or a
            # sender predating a slot) pad with NaN = unavailable
            v = float(mat[i, j]) if j < mat.shape[1] else float('nan')
            # a NaN sample means that host hasn't measured this yet
            # (step ring still empty): omit it — JSON null, no gauge —
            # rather than publish a fake zero
            row[key] = None if np.isnan(v) else round(v, 3)
        # decode the encoded trailing slots to their real types:
        # badput_top is a telemetry.goodput.BUCKETS index, comm_src the
        # comm provenance flag (1.0 measured / 0.0 modeled) — the
        # record and gauges carry the NAMES so a modeled comm share is
        # labeled as such everywhere downstream
        bi = row.pop('badput_top', None)
        from . import goodput as _goodput
        row['badput_top'] = _goodput.BUCKETS[int(bi)] \
            if bi is not None and 0 <= int(bi) < len(_goodput.BUCKETS) \
            else None
        src = row.pop('comm_src', None)
        row['comm_src'] = None if src is None \
            else ('measured' if src >= 0.5 else 'modeled')
        per_host.append(row)
        if row['step_time_ms'] is not None:
            reg.gauge('cluster.h%d.step_time_ms' % hid).set(
                row['step_time_ms'])
        reg.gauge('cluster.h%d.io_wait_pct' % hid).set(row['io_wait_pct'])
        reg.gauge('cluster.h%d.dispatch_ms' % hid).set(row['dispatch_ms'])
        reg.gauge('cluster.h%d.live_mb' % hid).set(
            round(row['live_bytes'] / 2.0**20, 1))
        if row['comm_pct'] is not None:
            reg.gauge('cluster.h%d.comm_pct' % hid).set(row['comm_pct'])
        if row['comm_src'] is not None:
            reg.gauge('cluster.h%d.comm_src' % hid).set(row['comm_src'])
        if row['goodput_pct'] is not None:
            reg.gauge('cluster.h%d.goodput_pct' % hid).set(
                row['goodput_pct'])
        if row.get('mem_headroom_pct') is not None:
            reg.gauge('cluster.h%d.mem_headroom_pct' % hid).set(
                row['mem_headroom_pct'])
    slowest_row, spread, straggler = round_verdict(mat)
    slowest = host_ids[slowest_row] if slowest_row is not None else None
    reg.gauge('cluster.hosts').set(n)
    if slowest is not None:
        reg.gauge('cluster.slowest_host').set(slowest)
    reg.gauge('cluster.step_time_spread_pct').set(round(spread, 1))
    reg.gauge('cluster.straggler_class').set(straggler)
    snap = {'hosts': n, 'step': int(steps), 'per_host': per_host,
            'slowest_host': slowest, 'spread_pct': round(spread, 1),
            'straggler': straggler}
    # fleet goodput = the WORST host's (a gang advances in lockstep, so
    # one host's badput is everyone's wall-clock), with the culprit
    # host and its top badput bucket named
    goods = [(r['goodput_pct'], r['host'], r.get('badput_top'))
             for r in per_host if r.get('goodput_pct') is not None]
    if goods:
        fleet, c_host, c_bucket = min(goods)
        culprit = 'h%s%s' % (c_host,
                             ':%s' % c_bucket if c_bucket else '')
        reg.gauge('cluster.fleet_goodput_pct').set(round(fleet, 2))
        reg.gauge('cluster.goodput_culprit').set(culprit)
        snap['fleet_goodput_pct'] = round(fleet, 2)
        snap['goodput_culprit'] = culprit
    # fleet memory headroom = the TIGHTEST host's (the first allocator
    # to die takes the lockstep gang with it), with that host named
    heads = [(r['mem_headroom_pct'], r['host']) for r in per_host
             if r.get('mem_headroom_pct') is not None]
    if heads:
        fleet_head, m_host = min(heads)
        reg.gauge('cluster.fleet_mem_headroom_pct').set(
            round(fleet_head, 2))
        reg.gauge('cluster.mem_pressured_host').set(m_host)
        snap['fleet_mem_headroom_pct'] = round(fleet_head, 2)
        snap['mem_pressured_host'] = m_host
    # the timeline plane's per-round work (MXTPU_TIMELINE; one cached
    # bool while off): clock-offset rings from this round's gathered
    # samples, critical-path attribution, cluster.h<i>.clock_offset_ms
    # + timeline.* gauges and the 'timeline' JSONL record
    from . import timeline as _timeline
    try:
        _timeline.publish_round(mat, host_ids, steps)
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry.cluster: timeline publish failed: %s', e)
    with _state.lock:
        _state.snapshot = snap
    if st.sink is not None:
        rec = {'type': 'cluster'}
        rec.update(snap)
        st.sink.emit(rec)
    return snap


# ---------------------------------------------------------------------------
# straggler-aware input re-balancing (MXTPU_ELASTIC_INPUT)
# ---------------------------------------------------------------------------

def elastic_enabled():
    """Whether straggler-aware input re-balancing is on: the cluster
    sync cadence (which carries the decisions) AND MXTPU_ELASTIC_INPUT.
    One attribute check after the first call."""
    return enabled() and _state.elastic


def _elastic_decide(mat, steps):
    """One sync round's re-balance decision, computed identically on
    every host from the identical gathered matrix: when the round names
    an input-bound straggler, advance the global shard-shift counter by
    one. The shift is APPLIED at the next epoch boundary
    (:func:`apply_shard_shift`) so mid-epoch batches are never
    re-drawn. Deterministic by construction — no second collective, no
    coordinator: every host sees the same matrix, runs the same math,
    lands on the same shift."""
    if not elastic_enabled():
        return None
    mat = np.asarray(mat, np.float64)
    if mat.shape[0] < 2:
        return None
    slowest_row, spread, verdict = round_verdict(mat)
    slowest = _host_ids(mat)[slowest_row] if slowest_row is not None \
        else None
    if verdict != 'input_bound':
        return None
    with _state.lock:
        if _state.shift != _state.applied:
            # a rotation is already pending: an input-bound host keeps
            # reading input-bound every round until the boundary, and
            # accumulating one shift per ROUND would turn the applied
            # delta into an arbitrary rotation (0 mod num_parts = a
            # silent no-op). At most ONE step pends at a time; every
            # host gates identically (applied advances at the same
            # lockstep epoch boundary everywhere)
            return None
        _state.shift += 1
        info = {'step': int(steps), 'input_bound_host': slowest,
                'shift': _state.shift, 'spread_pct': round(spread, 1)}
        _state.last_shift = dict(info)
    st = _tele()
    st.registry.gauge('cluster.elastic_shift').set(info['shift'])
    if st.sink is not None:
        rec = {'type': 'elastic', 'event': 'shift'}
        rec.update(info)
        st.sink.emit(rec)
    logging.warning(
        'telemetry.cluster: host %d is input-bound (spread %.1f%%) — '
        'shard assignments rotate by one at the next epoch boundary '
        '(shift %d)', slowest, spread, info['shift'])
    return info


def _elastic_give_up(reason, logger):
    """This iterator cannot be re-balanced: warn ONCE and disable the
    elastic tier for the rest of the run, so sync rounds stop deciding
    (and logging, and gauging) shifts that can never be applied — a
    climbing cluster.elastic_shift over a never-moving assignment would
    be operator-misleading noise."""
    _state.elastic = False
    if not _state.shift_warned:
        _state.shift_warned = True
        logger.warning(
            'telemetry.cluster: MXTPU_ELASTIC_INPUT is on but %s; '
            'input re-balancing is disabled for this run', reason)


def shard_shift():
    """The current global shard-shift counter (0 = original
    assignment). Identical on every host of the job by construction."""
    with _state.lock:
        return _state.shift


def apply_shard_shift(train_data, logger=logging):
    """Epoch-boundary hook (both fit loops): apply any un-applied shard
    shift to ``train_data`` via the iterator shard protocol —
    ``shard_info() -> (num_parts, part_index)`` plus
    ``set_shard(part_index)`` (ImageRecordIter, MNISTIter; takes effect
    at the iterator's next reset). Every host applies the same delta to
    its own part index, so the rotated assignment still covers every
    shard exactly once. Returns the new part index, or None when
    nothing changed. Off (or no pending shift) = one cached check."""
    if not elastic_enabled():
        return None
    with _state.lock:
        delta = _state.shift - _state.applied
        if delta == 0:
            return None
        _state.applied = _state.shift
    info_fn = getattr(train_data, 'shard_info', None)
    set_fn = getattr(train_data, 'set_shard', None)
    if not callable(info_fn) or not callable(set_fn):
        _elastic_give_up(
            '%s exposes no shard_info()/set_shard()'
            % type(train_data).__name__, logger)
        return None
    num_parts, part = info_fn()
    if num_parts <= 1:
        _elastic_give_up(
            '%s holds a single shard (num_parts=%d) — nothing to '
            'rotate' % (type(train_data).__name__, num_parts), logger)
        return None
    new_part = (int(part) + delta) % int(num_parts)
    set_fn(new_part)
    st = _tele()
    if st.sink is not None:
        st.sink.emit({'type': 'elastic', 'event': 'reshard',
                      'num_parts': int(num_parts), 'part_index': new_part,
                      'was': int(part), 'shift': _state.shift})
    logger.info(
        'telemetry.cluster: elastic input re-balance — this host now '
        'reads shard %d/%d (was %d, shift %d); applies at the next '
        'epoch', new_part, num_parts, part, _state.shift)
    return new_part


def snapshot_cluster():
    """The last published aggregation round (process 0 only; None
    before the first sync or on other hosts) — the summary table's
    "Cluster" block and the /healthz digest's input."""
    with _state.lock:
        return dict(_state.snapshot) if _state.snapshot else None


def _reset_for_tests():
    global _state, _host
    _state = _CState()
    _host = None
