"""HBM attribution & forecast: per-layer peak memory, OOM prediction.

The registrar (:mod:`.programs`) knows each compiled program's total
argument/temp/output/alias bytes and the XLA gauges know the device's
live/peak byte counters — but neither says *which layer owns the peak*,
and the first warning of an out-of-memory run is RESOURCE_EXHAUSTED
itself. This module is the memory twin of :mod:`.roofline`: attribute
the peak to named layers, watch the live-bytes timeline, and alarm
BEFORE the allocator dies.

Data flow, all host-side (the compiled programs are untouched — the
lowered HLO is byte-identical with the flag on or off):

1. **per-layer peak attribution** — when a compile site registers a
   program, :func:`note_compiled` parses its HLO text with the same
   machinery the roofline uses (instruction shapes give buffer bytes,
   ``metadata={op_name="..."}`` carries the ``jax.named_scope`` layer
   names). ENTRY parameters are the argument buffers, the ENTRY ROOT
   is the output, everything else that materializes is temp; the three
   parsed buckets are calibrated against ``compiled.memory_analysis()``
   so the per-layer split always sums to what XLA reports for the
   whole program, and the donated ``alias_bytes`` are shared out in
   proportion to each layer's argument bytes. Programs merge
   largest-variant-per-name — the registrar's own rule.
2. **live-bytes timeline** — the step loops feed :func:`note_step`
   (one cached-bool check while off); at the MXTPU_SCALARS_EVERY
   cadence one host-side ``memory_stats()`` allocator query (no device
   sync) lands a ``(step, bytes_in_use, bytes_limit)`` sample in a
   bounded ring, publishes the ``mem.*`` gauges and a ``memory`` JSONL
   record, and feeds the ``mem_growth`` spike detector (the
   :mod:`.health` registry) so a leak — a serving session ring that
   never evicts, host-side accumulation across windows — raises a
   NAMED anomaly.
3. **forecast** — a least-squares slope over the ring turns headroom
   into ``mem.steps_to_oom``; a forecast at or below
   MXTPU_MEMORY_OOM_STEPS flips /healthz to ``mem_pressure`` and dumps
   the flight recorder (flight-mem-pressure.jsonl) while the process
   can still write — the seconds before the OOM, on disk before the
   allocator dies. The OOM report cross-links the last forecast.

Surfacing: a "Memory" block in the end-of-run summary table, ``memory``
JSONL records, ``mem.*`` gauges on /metrics and /summary, a headroom
slot in the cluster sync vector (process 0 names the most
memory-pressured host), a memory line in tools/telemetry_watch.py and
``tools/memory_report.py`` offline (byte-identical block + a what-if
sizing table).

Gating: ``MXTPU_MEMORY=1`` *and* ``MXTPU_TELEMETRY=1``. Off = the
zero-overhead no-op contract of the rest of the plane: no HLO text is
ever rendered or parsed, no ring is filled, no records are written,
one cached-bool check at the registrar hook and the step loops.
"""
import collections
import logging
import threading

__all__ = ['enabled', 'note_compiled', 'note_hlo', 'hlo_layer_buffers',
           'note_step', 'record_sample', 'analyze', 'summarize',
           'republish', 'snapshot_memory', 'local_headroom',
           'pressure_info', 'last_forecast', 'TOP_N', 'RING_CAP']

TOP_N = 8        # layer rows rendered in the summary block
RING_CAP = 256   # live-bytes samples retained (bounded by construction)

_lock = threading.Lock()
_decided = None
_programs = {}       # name -> parsed per-layer buffer store (see note_hlo)
_last = None         # last published analysis dict (snapshot_memory)
_ring = collections.deque(maxlen=RING_CAP)  # (step, bytes_in_use, limit)
_steps = 0           # cumulative trained steps fed through note_step
_next_sample = 0     # next _steps value that takes a ring sample
_pressure = None     # active mem_pressure digest (healthz), or None
_last_forecast = None  # last emitted memory record (OOM cross-link)
_flight_dumped = False
_cadence_cached = None
_threshold_cached = None


def _tele():
    from . import enabled as tele_enabled
    tele_enabled()
    from . import _state as st
    return st


def enabled():
    """MXTPU_MEMORY=1 and telemetry on (decided once; off = one
    cached-bool check at the registrar hook and the step loops)."""
    global _decided
    if _decided is None:
        from . import enabled as tele_enabled
        on = tele_enabled()
        if on:
            from ..config import flags
            try:
                on = bool(flags.get('MXTPU_MEMORY'))
            except Exception:  # noqa: BLE001 — stripped builds
                on = False
        _decided = on
    return _decided


def _cadence():
    global _cadence_cached
    if _cadence_cached is None:
        from ..config import flags
        try:
            n = int(flags.get('MXTPU_SCALARS_EVERY'))
        except Exception:  # noqa: BLE001 — stripped builds
            n = 25
        _cadence_cached = n if n > 0 else 25
    return _cadence_cached


def _oom_threshold():
    global _threshold_cached
    if _threshold_cached is None:
        from ..config import flags
        try:
            _threshold_cached = int(flags.get('MXTPU_MEMORY_OOM_STEPS'))
        except Exception:  # noqa: BLE001 — stripped builds
            _threshold_cached = 200
    return _threshold_cached


# ---------------------------------------------------------------------------
# HLO text -> per-layer buffer-byte parse
# ---------------------------------------------------------------------------

# ops whose output is a view/bookkeeping handle, not a fresh buffer —
# counting their shapes would double every real allocation. Derived
# from the roofline's free set, minus `parameter` (ENTRY parameters ARE
# the argument buffers here) and `custom-call` (its result
# materializes), plus `iota` (negligible, usually folded)
def _no_buffer_ops():
    from . import roofline
    return (roofline._FREE_OPS | frozenset(('iota',))) \
        - frozenset(('parameter', 'custom-call'))


def hlo_layer_buffers(hlo_text):
    """Parse an HLO module's text into the per-layer buffer store::

        {'layers':     {layer: {'args': b, 'temp': b, 'out': b}},
         'args_total': ENTRY-parameter bytes,
         'temp_total': materialized intermediate bytes,
         'out_total':  ENTRY-ROOT bytes}

    Best-effort by construction: unparsed lines contribute nothing,
    buffers without a named scope pool under ``_unattributed``, and the
    three buckets are later CALIBRATED against memory_analysis() so
    parse inflation (a while carry counted at both the instruction and
    its body) cannot move the totals — only the relative shares."""
    from . import roofline as _r
    no_buffer = _no_buffer_ops()
    layers = {}
    args_total = temp_total = out_total = 0.0
    in_entry = False
    for line in hlo_text.splitlines():
        s = line.strip()
        if s.startswith('ENTRY'):
            in_entry = True
            continue
        if s == '}':
            in_entry = False
            continue
        m = _r._INSTR_RE.match(line)
        if not m:
            continue
        _name, out_sig, opcode = m.groups()
        out_bytes = 0
        for dt, dims in _r._SHAPE_RE.findall(out_sig):
            b, _n = _r._shape_bytes(dt, dims)
            out_bytes += b
        # the ENTRY ROOT is usually a free op (a tuple of loss + grads +
        # carried state) but its shape IS the program's output
        # allocation — never skip it
        is_root = in_entry and s.startswith('ROOT')
        if out_bytes <= 0 or (opcode in no_buffer and not is_root):
            continue
        mo = _r._OP_NAME_RE.search(line)
        layer = (_r._layer_from_op_name(mo.group(1)) if mo else None) \
            or '_unattributed'
        rec = layers.setdefault(layer, {'args': 0.0, 'temp': 0.0,
                                        'out': 0.0})
        if opcode == 'parameter':
            if in_entry:
                rec['args'] += out_bytes
                args_total += out_bytes
        elif is_root:
            rec['out'] += out_bytes
            out_total += out_bytes
        else:
            rec['temp'] += out_bytes
            temp_total += out_bytes
    layers = {k: v for k, v in layers.items()
              if v['args'] or v['temp'] or v['out']}
    return {'layers': layers, 'args_total': args_total,
            'temp_total': temp_total, 'out_total': out_total}


# ---------------------------------------------------------------------------
# registrar hook (telemetry.programs.note_program calls this)
# ---------------------------------------------------------------------------

def note_hlo(name, hlo_text, analysis=None):
    """Ingest one program's HLO text (tests feed synthetic modules
    here; live compiles arrive via :func:`note_compiled`). ``analysis``
    is the registrar's memory_analysis dict — its ``argument_bytes`` /
    ``temp_bytes`` / ``output_bytes`` / ``alias_bytes`` calibrate the
    parsed per-layer split."""
    if not enabled():
        return
    buf = hlo_layer_buffers(hlo_text)
    buf['analysis'] = dict(analysis or {})
    buf['name'] = name
    buf['parsed_total'] = (buf['args_total'] + buf['temp_total']
                          + buf['out_total'])
    rank = float(buf['analysis'].get('live_bytes') or 0.0) \
        or buf['parsed_total']
    buf['rank'] = rank
    with _lock:
        prev = _programs.get(name)
        if prev is not None and prev['rank'] > rank:
            # keep the largest variant per name — the registrar's own
            # merge rule (a tail-batch recompile must not shrink the
            # peak the run is judged by)
            return
        _programs[name] = buf


def note_compiled(name, compiled, analysis=None):
    """The live hook: render ``compiled.as_text()`` and ingest it.
    Never raises — attribution is best-effort, execution is not."""
    if not enabled():
        return
    try:
        if analysis is None:
            from . import programs as _p
            analysis = _p.analyze_compiled(compiled)
        note_hlo(name, compiled.as_text(), analysis=analysis)
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('memory: HLO ingest of %s failed: %s', name, e)


def _pick_program():
    """The program whose peak the plane attributes: the one with the
    largest steady-state footprint (analysis live_bytes, else the
    parsed total)."""
    with _lock:
        progs = list(_programs.values())
    if not progs:
        return None
    return max(progs, key=lambda p: p['rank'])


def _calibrated_layers(prog):
    """Per-layer rows with each parsed bucket rescaled so the bucket
    sums equal XLA's own memory_analysis totals (when present). The
    donated alias bytes are shared in proportion to argument bytes —
    donation aliases inputs onto outputs, so the layers holding the
    arguments hold the refund."""
    ana = prog['analysis']
    targets = {'args': float(ana.get('argument_bytes') or 0.0),
               'temp': float(ana.get('temp_bytes') or 0.0),
               'out': float(ana.get('output_bytes') or 0.0)}
    parsed = {'args': prog['args_total'], 'temp': prog['temp_total'],
              'out': prog['out_total']}
    layers = {k: dict(v) for k, v in prog['layers'].items()}
    for k in targets:
        if targets[k] > 0 and parsed[k] <= 0:
            # the bucket never parsed (a ROOT/shape format the parser
            # doesn't know) — land the whole target unattributed so the
            # bucket sums still match XLA's totals
            u = layers.setdefault('_unattributed',
                                  {'args': 0.0, 'temp': 0.0, 'out': 0.0})
            u[k] += targets[k]
            parsed[k] = targets[k]
    scale = {k: (targets[k] / parsed[k]
                 if parsed[k] > 0 and targets[k] > 0 else 1.0)
             for k in targets}
    alias_total = float(ana.get('alias_bytes') or 0.0)
    args_cal = sum(v['args'] for v in layers.values()) \
        * scale['args']
    rows = []
    for layer, v in layers.items():
        args = v['args'] * scale['args']
        temp = v['temp'] * scale['temp']
        out = v['out'] * scale['out']
        alias = args / args_cal * alias_total if args_cal > 0 else 0.0
        rows.append({'layer': layer, 'args': int(round(args)),
                     'temp': int(round(temp)), 'out': int(round(out)),
                     'alias': int(round(alias)),
                     'total': int(round(args + temp + out))})
    rows.sort(key=lambda r: -r['total'])
    return rows


# ---------------------------------------------------------------------------
# live-bytes timeline + forecaster
# ---------------------------------------------------------------------------

def _fit_slope(ring):
    """Least-squares bytes-per-step over the ring (None below 4
    samples or with no step spread)."""
    if len(ring) < 4:
        return None
    n = float(len(ring))
    mx = sum(r[0] for r in ring) / n
    my = sum(r[1] for r in ring) / n
    sxx = sum((r[0] - mx) ** 2 for r in ring)
    if sxx <= 0:
        return None
    sxy = sum((r[0] - mx) * (r[1] - my) for r in ring)
    return sxy / sxx


def _note_growth(bytes_in_use):
    """Feed the mem_growth spike detector (the health registry's
    rolling-median/MAD family): a constant baseline never alarms, a
    leak's climb past k robust deviations raises the NAMED anomaly.
    Only upward excursions publish — a freed buffer is not a leak."""
    from . import health
    try:
        a = health.detector('mem_growth').observe(bytes_in_use / 2.0**20)
        if a is not None and a['value'] > a['baseline']:
            health.publish_anomaly(a)
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('memory: growth detector failed: %s', e)


def note_step(n=1):
    """Step-loop hook (fused window tail feeds W, the per-batch loop
    feeds 1). One cached-bool check while off; at the scalars cadence
    one host-side ``memory_stats()`` allocator query (no device sync)
    lands a ring sample. Backends without memory statistics (CPU) warn
    once through the xla plane and sample nothing."""
    if not enabled():
        return
    global _steps, _next_sample
    with _lock:
        _steps += n
        if _steps < _next_sample:
            return
        _next_sample = _steps + _cadence()
        step = _steps
    from . import xla
    stats = xla.sample_memory()
    if not stats:
        return
    live = stats.get('bytes_in_use')
    if live is None:
        return
    record_sample(step, live, stats.get('bytes_limit'))


def record_sample(step, bytes_in_use, bytes_limit=None):
    """Land one live-bytes sample: ring, ``mem.*`` gauges, the
    ``memory`` JSONL record, the growth detector, and the steps-to-OOM
    forecast verdict. Tests feed synthetic ramps here; live training
    arrives via :func:`note_step`. Returns the record dict."""
    if not enabled():
        return None
    global _pressure, _last_forecast, _flight_dumped
    bytes_in_use = float(bytes_in_use)
    limit = float(bytes_limit or 0.0)
    with _lock:
        _ring.append((int(step), bytes_in_use, limit))
        ring = list(_ring)
    st = _tele()
    reg = st.registry
    reg.gauge('mem.bytes_in_use').set(int(bytes_in_use))
    headroom = None
    if limit > 0:
        reg.gauge('mem.bytes_limit').set(int(limit))
        headroom = 100.0 * (limit - bytes_in_use) / limit
        reg.gauge('mem.headroom_pct').set(round(headroom, 2))
    slope = _fit_slope(ring)
    steps_to_oom = None
    if slope is not None:
        reg.gauge('mem.slope_bytes_per_step').set(round(slope, 1))
        if slope > 0 and limit > 0:
            steps_to_oom = max(0, int((limit - bytes_in_use) / slope))
            reg.gauge('mem.steps_to_oom').set(steps_to_oom)
    _note_growth(bytes_in_use)
    tripped = (steps_to_oom is not None
               and steps_to_oom <= _oom_threshold())
    reg.gauge('mem.pressure').set(1 if tripped else 0)
    rec = {'type': 'memory', 'step': int(step),
           'bytes_in_use': int(bytes_in_use)}
    if limit > 0:
        rec['bytes_limit'] = int(limit)
        rec['headroom_pct'] = round(headroom, 2)
    if slope is not None:
        rec['slope_bytes_per_step'] = round(slope, 1)
    if steps_to_oom is not None:
        rec['steps_to_oom'] = steps_to_oom
    if tripped:
        rec['pressure'] = True
    with _lock:
        _last_forecast = dict(rec)
        _pressure = ({'step': int(step), 'steps_to_oom': steps_to_oom,
                      'headroom_pct': (round(headroom, 2)
                                       if headroom is not None else None)}
                     if tripped else None)
    if st.sink is not None:
        st.sink.emit(rec)
    if tripped and not _flight_dumped:
        # dump while the process can still write — the whole point of
        # forecasting is beating RESOURCE_EXHAUSTED to the disk
        _flight_dumped = True
        logging.warning(
            'memory: forecast predicts OOM in ~%d steps (headroom '
            '%.1f%%, +%.0f bytes/step) — dumping flight recorder',
            steps_to_oom, headroom if headroom is not None else -1.0,
            slope or 0.0)
        from . import flight
        try:
            flight.dump('mem-pressure', {'forecast': dict(rec)})
        except Exception as e:  # noqa: BLE001
            logging.debug('memory: flight dump failed: %s', e)
    return rec


# ---------------------------------------------------------------------------
# analysis + publication
# ---------------------------------------------------------------------------

def analyze():
    """The full memory picture as one dict (None while off or before
    anything is ingested): the attributed step program's per-layer
    rows + bucket totals, every program's peak bytes, and the timeline
    /forecast state. Pure — no gauges, no records."""
    if not enabled():
        return None
    prog = _pick_program()
    with _lock:
        ring = list(_ring)
        peaks = {n: int(p['rank']) for n, p in _programs.items()}
        pressure = dict(_pressure) if _pressure else None
    if prog is None and not ring:
        return None
    d = {}
    if prog is not None:
        ana = prog['analysis']
        d['program'] = prog['name']
        for src, dst in (('argument_bytes', 'args_bytes'),
                         ('temp_bytes', 'temp_bytes'),
                         ('output_bytes', 'output_bytes'),
                         ('alias_bytes', 'alias_bytes'),
                         ('live_bytes', 'live_bytes')):
            v = ana.get(src)
            if v is not None:
                d[dst] = int(v)
        rows = _calibrated_layers(prog)
        d['layers'] = rows
        if rows:
            d['worst_layer'] = rows[0]['layer']
            d['worst_layer_bytes'] = rows[0]['total']
    if peaks:
        d['peaks'] = peaks
    if ring:
        step, bytes_in_use, limit = ring[-1]
        d['step'] = int(step)
        d['bytes_in_use'] = int(bytes_in_use)
        d['samples'] = len(ring)
        if limit > 0:
            d['bytes_limit'] = int(limit)
            d['headroom_pct'] = round(
                100.0 * (limit - bytes_in_use) / limit, 2)
        slope = _fit_slope(ring)
        if slope is not None:
            d['slope_bytes_per_step'] = round(slope, 1)
            if slope > 0 and limit > 0:
                d['steps_to_oom'] = max(
                    0, int((limit - bytes_in_use) / slope))
    d['pressure'] = bool(pressure)
    return d


def _publish_gauges(d, reg):
    """One analysis dict -> the mem.* gauge family (shared by
    :func:`summarize` and the cluster-cadence :func:`republish`)."""
    if d.get('worst_layer') is not None:
        reg.gauge('mem.worst_layer').set(d['worst_layer'])
        reg.gauge('mem.worst_layer_bytes').set(d['worst_layer_bytes'])
    if d.get('live_bytes') is not None:
        reg.gauge('mem.program_live_bytes').set(d['live_bytes'])
    if d.get('headroom_pct') is not None:
        reg.gauge('mem.headroom_pct').set(d['headroom_pct'])
    if d.get('steps_to_oom') is not None:
        reg.gauge('mem.steps_to_oom').set(d['steps_to_oom'])


def summarize():
    """Run :func:`analyze`, publish the ``mem.*`` gauges + the full
    ``memory`` JSONL record, and return the analysis dict (None when
    off/empty). Called from telemetry.write_summary."""
    global _last
    d = analyze()
    if d is None:
        return None
    st = _tele()
    _publish_gauges(d, st.registry)
    if st.sink is not None:
        rec = {'type': 'memory'}
        rec.update(d)
        st.sink.emit(rec)
    with _lock:
        _last = d
    return d


def republish():
    """Cluster-sync-cadence hook (telemetry/cluster.py): refresh the
    ``mem.*`` gauges from a read-only analysis so a mid-run /metrics
    scrape sees live memory state. No JSONL record — a sync round must
    stay cheap. Returns the analysis dict or None."""
    global _last
    if not enabled():
        return None
    d = analyze()
    if d is None:
        return None
    _publish_gauges(d, _tele().registry)
    with _lock:
        _last = d
    return d


def snapshot_memory():
    """The last published analysis dict (the /summary payload's and
    read-only summary()'s input), or None."""
    with _lock:
        return _last


def local_headroom():
    """This host's latest headroom %, NaN while off or before any
    sample carries a byte limit — the cluster sync vector's
    NaN-padding contract (old senders simply ship shorter rows)."""
    if not enabled():
        return float('nan')
    with _lock:
        if not _ring:
            return float('nan')
        _s, b, limit = _ring[-1]
    if limit <= 0:
        return float('nan')
    return 100.0 * (limit - b) / limit


def pressure_info():
    """The active mem_pressure digest for /healthz (step,
    steps_to_oom, headroom_pct), or None while the forecast is clear —
    pressure is recoverable: a sample whose forecast rises back above
    the threshold clears it."""
    if not enabled():
        return None
    with _lock:
        return dict(_pressure) if _pressure else None


def last_forecast():
    """The most recent ``memory`` sample record (the OOM report's
    cross-link: what the forecaster last said before the allocator
    died), or None."""
    if not enabled():
        return None
    with _lock:
        return dict(_last_forecast) if _last_forecast else None


def _reset_for_tests():
    global _decided, _last, _steps, _next_sample, _pressure, \
        _last_forecast, _flight_dumped, _cadence_cached, _threshold_cached
    with _lock:
        _programs.clear()
        _ring.clear()
        _last = None
        _pressure = None
        _last_forecast = None
    _decided = None
    _steps = 0
    _next_sample = 0
    _flight_dumped = False
    _cadence_cached = None
    _threshold_cached = None
