"""Telemetry exporters: append-only JSONL log + human-readable summary.

The JSONL sink is the machine-readable record a perf investigation
greps after the fact: one JSON object per line, each with a ``type``
('start', 'span', 'compile', 'cache_hit', 'retrace_storm', 'event',
'program', 'oom', 'health', 'anomaly', 'cluster', 'restart', 'hang',
'elastic', 'roofline', 'trace', 'slo', 'flight', 'manifest',
'scalars', 'dynamics', 'goodput', 'memory', 'timeline', 'summary')
and a ``t``
epoch-seconds
stamp —
the full list is documented (and lint-gated) under
MXTPU_TELEMETRY_PATH in docs/env_vars.md. Records buffer in memory and flush every
``_FLUSH_EVERY`` lines (and at shutdown) so the fit loop never blocks
on a per-batch fsync.

``summary_table`` renders a registry snapshot as the end-of-run table
docs/perf.md documents ("Reading the telemetry summary").
"""
import json
import logging
import os
import threading
import time

__all__ = ['JsonlSink', 'summary_table']

_FLUSH_EVERY = 64
# ...and at least this often in wall time: the supervisor's liveness
# tier (tools/train_supervisor.py, MXTPU_SUPERVISOR_LIVENESS) watches
# the FILE for growth, so a slow loop whose records sit in the buffer
# must not read as a hang
_FLUSH_SECS = 5.0

# Module-wide count of actual file I/O calls (open/write/flush) — the
# zero-overhead tests assert this stays put while telemetry is off.
_io_calls = 0


class JsonlSink:
    """Append-only JSONL writer; thread-safe, buffered.

    ``host`` (stamped by telemetry.cluster when the sink opens) labels
    every record with this process's host index so multi-host logs
    merge on it. ``max_bytes`` (MXTPU_TELEMETRY_MAX_MB) caps the file:
    once the NEXT record would push the file past the cap, writing
    stops for good — metrics stay live in-process and the
    ``telemetry.dropped_records`` counter keeps the true drop count —
    so a week-long run cannot fill a disk."""

    def __init__(self, path, max_bytes=None):
        global _io_calls
        self.path = path
        self.host = None
        self._lock = threading.Lock()
        self._buf = []
        self._closed = False
        self._max_bytes = max_bytes
        self._capped = False
        self._last_flush = time.time()
        try:
            # append mode: what is already on disk counts against the cap
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        _io_calls += 1
        self._f = open(path, 'a')

    def _count_dropped(self):
        from . import _state
        if _state.active:
            _state.registry.counter('telemetry.dropped_records').inc()

    def emit(self, record):
        if self._closed:
            return
        record.setdefault('t', time.time())
        if self.host is not None:
            record.setdefault('host', self.host)
        # the flight recorder rides the emit chokepoint: everything
        # headed for the log (including records a capped sink drops)
        # enters the bounded in-memory ring too — one deque append
        from . import flight
        flight.note(record)
        if self._capped:
            self._count_dropped()
            self._heartbeat()
            return
        line = json.dumps(record)
        tripped = False
        raced = False
        with self._lock:
            if self._capped:
                # a concurrent emit tripped the cap between the
                # unlocked check and here — it owns the one warning,
                # this record is just another drop
                raced = True
            elif self._max_bytes is not None and \
                    self._bytes + len(line) + 1 > self._max_bytes:
                self._capped = True
                tripped = True
            else:
                self._bytes += len(line) + 1
                self._buf.append(line)
                if len(self._buf) >= _FLUSH_EVERY or \
                        record['t'] - self._last_flush >= _FLUSH_SECS:
                    self._flush_locked()
        if tripped:
            logging.warning(
                'telemetry: %s reached MXTPU_TELEMETRY_MAX_MB '
                '(%.1f MB) — no further JSONL records will be written; '
                'metrics stay live in-process and '
                'telemetry.dropped_records counts the drops',
                self.path, self._max_bytes / 2.0**20)
        if tripped or raced:
            self._count_dropped()

    def _heartbeat(self):
        """A capped sink appends nothing ever again, but the supervisor
        liveness tier (tools/train_supervisor.py) reads 'file stopped
        changing' as 'child is wedged' — touch the mtime (no growth, so
        the size cap's contract holds) at the flush cadence so a
        healthy-but-capped child is never liveness-killed in a loop."""
        now = time.time()
        if now - self._last_flush < _FLUSH_SECS:
            return
        self._last_flush = now
        try:
            os.utime(self.path)
        except OSError:
            pass

    def _flush_locked(self):
        global _io_calls
        self._last_flush = time.time()
        if self._buf and not self._closed:
            _io_calls += 1
            self._f.write('\n'.join(self._buf) + '\n')
            self._f.flush()
            self._buf = []

    def flush(self):
        with self._lock:
            self._flush_locked()

    def close(self):
        with self._lock:
            self._flush_locked()
            if not self._closed:
                self._closed = True
                self._f.close()


def _fmt(v):
    if v is None:
        return '-'
    if isinstance(v, float):
        if v != v:   # nan
            return 'nan'
        if abs(v) >= 1e6 or (abs(v) < 1e-3 and v != 0):
            return '%.3e' % v
        return '%.3f' % v
    return str(v)


def _mib(n):
    return '%.1f' % (n / 2.0**20)


def _health_lines(health):
    """The "Run health" block (telemetry.health.snapshot_health's
    dict): non-finite incidents, anomaly counts, the last anomaly and
    the input-bound share, rendered deterministically so the offline
    CLI reproduces the live table byte-for-byte."""
    lines = ['-- run health --']
    n_bad = int(health.get('nonfinite_steps') or 0)
    lines.append('  status            %s'
                 % ('DEGRADED (%d non-finite step%s)'
                    % (n_bad, 's' if n_bad != 1 else '')
                    if n_bad else 'ok'))
    incidents = health.get('incidents') or []
    if incidents:
        first = incidents[0]
        desc = '%s' % first.get('source', '?')
        if first.get('step') is not None:
            desc += ' step %s' % first['step']
        if first.get('window_step') is not None:
            desc += ' (window step %d)' % first['window_step']
        if first.get('first_bad_layer'):
            desc += ': first non-finite symbol %s' % first['first_bad_layer']
        lines.append('  first_incident    %s' % desc)
    counts = health.get('anomaly_counts') or {}
    if counts:
        lines.append('  anomalies         %s'
                     % ', '.join('%s=%d' % (k, counts[k])
                                 for k in sorted(counts)))
    last = health.get('last_anomaly')
    if last:
        lines.append('  last_anomaly      %s=%s (baseline %s)'
                     % (last.get('detector', '?'), _fmt(last.get('value')),
                        _fmt(last.get('baseline'))))
    if health.get('restarts'):
        lines.append('  restarts          %d' % int(health['restarts']))
    if health.get('hangs'):
        lines.append('  hangs             %d' % int(health['hangs']))
    if health.get('input_bound_pct') is not None:
        lines.append('  input_bound_pct   %s'
                     % _fmt(float(health['input_bound_pct'])))
    return lines


def _roofline_lines(roof):
    """The "roofline" block (telemetry.roofline.analyze()'s dict): the
    ranked top-N bottleneck layers — class, achieved/peak %, estimated
    headroom — plus the whole-step communication accounting. Rendered
    deterministically from the dict alone so the offline CLI
    (tools/roofline_report.py) reproduces the live block byte-for-byte
    from the JSONL record."""
    from .roofline import TOP_N
    lines = ['-- roofline: %s (%s) --'
             % (roof.get('program', '?'), roof.get('source', '?'))]
    if roof.get('peak_tflops') is not None:
        lines.append('  device            %s (%s peaks: %s TFLOP/s, %s GB/s)'
                     % (roof.get('device') or '?', roof.get('peaks'),
                        _fmt(float(roof['peak_tflops'])),
                        _fmt(float(roof['peak_hbm_gbs']))
                        if roof.get('peak_hbm_gbs') is not None else '-'))
    else:
        lines.append('  device            %s (no peak table entry — set '
                     'MXTPU_PEAK_TFLOPS/MXTPU_PEAK_HBM_GBS)'
                     % (roof.get('device') or '?'))
    if roof.get('step_time_ms') is not None:
        lines.append('  step_time_ms      %s'
                     % _fmt(float(roof['step_time_ms'])))
    layers = roof.get('layers') or []
    if layers:
        w = max(max(len(str(r.get('layer', '?'))) for r in layers[:TOP_N]),
                len('layer'))
        lines.append('  %-*s  %-14s %8s %10s %12s'
                     % (w, 'layer', 'class', 'roof%', 'time_ms',
                        'headroom_ms'))
        for r in layers[:TOP_N]:
            lines.append('  %-*s  %-14s %8s %10s %12s'
                         % (w, r.get('layer', '?'), r.get('class', '?'),
                            _fmt(r.get('roof_pct')), _fmt(r.get('time_ms')),
                            _fmt(r.get('headroom_ms'))))
        if len(layers) > TOP_N:
            lines.append('  (+%d more layers)' % (len(layers) - TOP_N))
    comm = roof.get('comm')
    if comm:
        line = '  comm              %s MiB/step' % _mib(comm.get('bytes')
                                                        or 0)
        if comm.get('time_ms') is not None:
            line += ', %s ms' % _fmt(float(comm['time_ms']))
        if comm.get('pct_of_step') is not None:
            line += ' = %s%% of step' % _fmt(float(comm['pct_of_step']))
        if comm.get('overlap_pct') is not None:
            line += ', overlap %s%%' % _fmt(float(comm['overlap_pct']))
        ops = comm.get('ops') or {}
        opstr = ', '.join('%s %s MiB' % (k, _mib(ops[k]))
                          for k in sorted(ops))
        line += ' (%s%s)' % (comm.get('source', '?'),
                             ('; ' + opstr) if opstr else '')
        lines.append(line)
    return lines


def _memory_lines(mem):
    """The "memory" block (telemetry.memory.analyze()'s dict): the
    ranked per-layer peak attribution — args/temp/out/alias bytes,
    calibrated to memory_analysis totals — plus the live-bytes
    timeline and the steps-to-OOM forecast. Rendered deterministically
    from the dict alone so the offline CLI (tools/memory_report.py)
    reproduces the live block byte-for-byte from the JSONL record."""
    from .memory import TOP_N
    prog = mem.get('program')
    lines = ['-- memory: %s --' % prog if prog else '-- memory --']
    layers = mem.get('layers') or []
    if layers:
        w = max(max(len(str(r.get('layer', '?'))) for r in layers[:TOP_N]),
                len('layer'))
        lines.append('  %-*s  %9s %9s %9s %9s %10s'
                     % (w, 'layer', 'args_MiB', 'temp_MiB', 'out_MiB',
                        'alias_MiB', 'total_MiB'))
        for r in layers[:TOP_N]:
            lines.append('  %-*s  %9s %9s %9s %9s %10s'
                         % (w, r.get('layer', '?'),
                            _mib(r.get('args') or 0),
                            _mib(r.get('temp') or 0),
                            _mib(r.get('out') or 0),
                            _mib(r.get('alias') or 0),
                            _mib(r.get('total') or 0)))
        if len(layers) > TOP_N:
            lines.append('  (+%d more layers)' % (len(layers) - TOP_N))
    if mem.get('live_bytes') is not None:
        lines.append('  program_live      %s MiB (args %s + temp %s + '
                     'out %s - alias %s)'
                     % (_mib(mem['live_bytes']),
                        _mib(mem.get('args_bytes') or 0),
                        _mib(mem.get('temp_bytes') or 0),
                        _mib(mem.get('output_bytes') or 0),
                        _mib(mem.get('alias_bytes') or 0)))
    if mem.get('bytes_in_use') is not None:
        line = '  device_bytes      %s MiB' % _mib(mem['bytes_in_use'])
        if mem.get('bytes_limit'):
            line += ' of %s MiB' % _mib(mem['bytes_limit'])
        if mem.get('headroom_pct') is not None:
            line += ' (headroom %s%%)' % _fmt(float(mem['headroom_pct']))
        if mem.get('samples'):
            line += ', %d samples' % int(mem['samples'])
        lines.append(line)
    if mem.get('slope_bytes_per_step') is not None:
        line = ('  forecast          %+.0f bytes/step'
                % float(mem['slope_bytes_per_step']))
        if mem.get('steps_to_oom') is not None:
            line += ' -> ~%d steps to OOM' % int(mem['steps_to_oom'])
        lines.append(line)
    if mem.get('pressure'):
        lines.append('  pressure          MEM_PRESSURE (forecast at or '
                     'below MXTPU_MEMORY_OOM_STEPS)')
    return lines


def _ledger_lines(led):
    """The "run ledger" block (telemetry.ledger.snapshot_ledger's
    dict): the manifest roll-up, the scalar cadence and the last
    banked point — rendered deterministically so the offline CLI
    reproduces the live table byte-for-byte."""
    lines = ['-- run ledger --']
    man = led.get('manifest') or {}
    if man:
        bits = []
        if man.get('device_kind') or man.get('platform'):
            dev = man.get('device_kind') or man.get('platform')
            if man.get('device_count'):
                dev += ' x%d' % int(man['device_count'])
            bits.append('device=%s' % dev)
        if man.get('jax_version'):
            bits.append('jax=%s' % man['jax_version'])
        if man.get('git_sha'):
            bits.append('git=%s' % man['git_sha'])
        if man.get('mesh'):
            bits.append('mesh=%s' % json.dumps(man['mesh'],
                                               sort_keys=True))
        if bits:
            lines.append('  manifest          %s' % ', '.join(bits))
        if man.get('env_set'):
            lines.append('  flags_set         %s'
                         % ', '.join(man['env_set']))
    if led.get('steps'):
        lines.append('  scalars           %d steps, every %d'
                     % (int(led['steps']), int(led.get('every') or 0)))
    last = led.get('last')
    if last:
        line = '  last              step %s' % last.get('step')
        if last.get('loss') is not None:
            line += ', loss %s' % _fmt(float(last['loss']))
        if led.get('final_loss') is not None \
                and led['final_loss'] != last.get('loss'):
            line += ' (final_loss %s)' % _fmt(float(led['final_loss']))
        lines.append(line)
    if led.get('tfevents'):
        lines.append('  tfevents          %s' % led['tfevents'])
    return lines


def _goodput_lines(good):
    """The "Where the time went" block (telemetry.goodput's dict): one
    row per bucket with seconds and wall share, the goodput verdict and
    the rework/provenance context — rendered deterministically so the
    offline CLI reproduces the live table byte-for-byte."""
    lines = ['-- where the time went --']
    wall = float(good.get('wall_s') or 0.0)
    buckets = good.get('buckets') or {}
    # canonical bucket order (telemetry.goodput.BUCKETS), without
    # importing the live module: the record carries the order
    order = ('step', 'compile', 'input_wait', 'checkpoint', 'eval',
             'comm', 'rework', 'overhead')
    names = [n for n in order if n in buckets]
    names += [n for n in sorted(buckets) if n not in order]
    for name in names:
        secs = float(buckets[name] or 0.0)
        pct = (100.0 * secs / wall) if wall > 0.0 else 0.0
        label = name
        if name == 'comm' and good.get('comm_source'):
            label = 'comm (%s)' % good['comm_source']
        lines.append('  %-18s  %9ss  %5.1f%%'
                     % (label, _fmt(round(secs, 3)), pct))
    lines.append('  %-18s  %9ss' % ('wall', _fmt(round(wall, 3))))
    verdict = 'goodput           %s%%' % _fmt(good.get('goodput_pct'))
    if good.get('badput_top'):
        verdict += ' (top badput: %s)' % good['badput_top']
    lines.append('  %s' % verdict)
    if good.get('rework_steps'):
        lines.append('  rework_steps      %d' % int(good['rework_steps']))
    if good.get('prior_lost_s'):
        lines.append('  prior_lost        %ss across relaunches -> '
                     'job goodput %s%% of %ss'
                     % (_fmt(good['prior_lost_s']),
                        _fmt(good.get('job_goodput_pct')),
                        _fmt(good.get('job_wall_s'))))
    return lines


def _timeline_lines(tl):
    """The "step timeline" block (telemetry.timeline's attribution
    dict): one decomposition row per host from the last sync round —
    step time split into compute / collective-wait / io / host-side,
    plus the estimated clock offset — then the skew (fastest-host idle
    at the allreduce) and the gating host+phase. Rendered
    deterministically from the dict alone so the offline CLI
    (tools/timeline_report.py) reproduces the live block byte-for-byte
    from the JSONL record."""
    lines = ['-- step timeline --']
    lines.append('  hosts             %s' % tl.get('hosts'))
    per = tl.get('per_host') or []
    if per:
        lines.append('  host   step_ms    compute    collect    io    '
                     '     host_side  offset_ms')
        crit = tl.get('critical_host')
        for r in per:
            mark = '*' if (r.get('host') == crit and len(per) > 1) else ''
            lines.append('  %-5s  %-9s  %-9s  %-9s  %-9s  %-9s  %s'
                         % ('%s%s' % (r.get('host'), mark),
                            _fmt(r.get('step_time_ms')),
                            _fmt(r.get('compute_ms')),
                            _fmt(r.get('collective_ms')),
                            _fmt(r.get('io_ms')),
                            _fmt(r.get('host_ms')),
                            _fmt(r.get('clock_offset_ms'))))
    if tl.get('skew_ms') is not None:
        lines.append('  skew              %s ms/step (fastest-host idle '
                     'at the allreduce)' % _fmt(float(tl['skew_ms'])))
    if tl.get('critical_phase') is not None:
        line = '  critical_path     host %s %s' % (tl.get('critical_host'),
                                                   tl['critical_phase'])
        if tl.get('phase_excess_ms') is not None:
            if (tl.get('hosts') or 1) > 1:
                line += ' (+%s ms/step of skew)' \
                    % _fmt(float(tl['phase_excess_ms']))
            else:
                line += ' (%s ms/step)' % _fmt(float(tl['phase_excess_ms']))
        lines.append(line)
    return lines


def _cluster_lines(cluster):
    """The "Cluster" block (telemetry.cluster.snapshot_cluster's dict):
    one row per host from the last aggregation round, the spread, and
    the straggler classification — rendered deterministically so the
    offline CLI reproduces the live table byte-for-byte."""
    lines = ['-- cluster --']
    lines.append('  hosts             %s' % cluster.get('hosts'))
    per = cluster.get('per_host') or []
    if per:
        lines.append('  host   step_ms    io_wait%   dispatch_ms  live_MiB')
        slow = cluster.get('slowest_host')
        for r in per:
            mark = '*' if (r.get('host') == slow and len(per) > 1) else ''
            lines.append('  %-5s  %-9s  %-9s  %-11s  %s'
                         % ('%s%s' % (r.get('host'), mark),
                            _fmt(r.get('step_time_ms')),
                            _fmt(r.get('io_wait_pct')),
                            _fmt(r.get('dispatch_ms')),
                            _mib(r.get('live_bytes') or 0)))
    if cluster.get('spread_pct') is not None:
        lines.append('  step_time_spread  %s%%'
                     % _fmt(float(cluster['spread_pct'])))
    if cluster.get('straggler'):
        extra = ''
        if cluster.get('slowest_host') is not None and len(per) > 1:
            extra = ' (slowest host %s)' % cluster['slowest_host']
        lines.append('  straggler         %s%s'
                     % (cluster['straggler'], extra))
    return lines


def summary_table(snapshot, elapsed_s=None, programs=None, health=None,
                  cluster=None, roofline=None, ledger=None, goodput=None,
                  memory=None, timeline=None):
    """Registry snapshot -> aligned text table (one block per kind).
    ``programs`` is telemetry.programs.snapshot_programs()'s {name:
    record} — rendered as a per-program cost table (and the redundant
    ``program.<name>.*`` gauges are elided from the gauges block);
    ``health`` is telemetry.health.snapshot_health()'s dict — rendered
    as the "Run health" block; ``cluster`` is
    telemetry.cluster.snapshot_cluster()'s dict — rendered as the
    "Cluster" block (its per-host ``cluster.*`` gauges are elided the
    same way); ``roofline`` is telemetry.roofline.analyze()'s dict —
    rendered as the ranked-bottleneck "roofline" block (the
    ``roofline.*`` gauges are elided the same way); ``ledger`` is
    telemetry.ledger.snapshot_ledger()'s dict — rendered as the
    "run ledger" block (manifest roll-up + last scalars; its
    ``dynamics.*`` per-layer gauges stay in the gauges block);
    ``goodput`` is telemetry.goodput.summarize()'s dict — rendered as
    the "Where the time went" block (the ``goodput.*`` gauges are
    elided the same way); ``memory`` is telemetry.memory.analyze()'s
    dict — rendered as the per-layer-peak "memory" block (the
    ``mem.*`` gauges are elided the same way); ``timeline`` is
    telemetry.timeline's attribution dict — rendered as the
    critical-path "step timeline" block (the ``timeline.*`` gauges
    are elided the same way)."""
    lines = ['== telemetry summary%s ==' %
             (' (%.1fs)' % elapsed_s if elapsed_s is not None else '')]
    counters = snapshot.get('counters', {})
    gauges = snapshot.get('gauges', {})
    hists = snapshot.get('histograms', {})
    if programs:
        # one row per compiled program already carries these values
        gauges = {n: v for n, v in gauges.items()
                  if not n.startswith('program.')}
    if cluster:
        # the Cluster block already carries these values
        gauges = {n: v for n, v in gauges.items()
                  if not n.startswith('cluster.')}
    if roofline:
        # the roofline block already carries these values
        gauges = {n: v for n, v in gauges.items()
                  if not n.startswith('roofline.')}
    if goodput:
        # the "Where the time went" block already carries these values
        gauges = {n: v for n, v in gauges.items()
                  if not n.startswith('goodput.')}
    if memory:
        # the memory block already carries these values
        gauges = {n: v for n, v in gauges.items()
                  if not n.startswith('mem.')}
    if timeline:
        # the step-timeline block already carries these values
        gauges = {n: v for n, v in gauges.items()
                  if not n.startswith('timeline.')}
    if counters:
        lines.append('-- counters --')
        w = max(len(n) for n in counters)
        for name in sorted(counters):
            lines.append('  %-*s  %s' % (w, name, _fmt(counters[name])))
    if gauges:
        lines.append('-- gauges --')
        w = max(len(n) for n in gauges)
        for name in sorted(gauges):
            lines.append('  %-*s  %s' % (w, name, _fmt(gauges[name])))
    if programs:
        lines.append('-- programs --')
        w = max(max(len(n) for n in programs), len('name'))
        lines.append('  %-*s  %8s %10s %10s %10s %9s %9s %9s' %
                     (w, 'name', 'compiles', 'calls', 'flops',
                      'bytes_acc', 'temp_MiB', 'arg_MiB', 'out_MiB'))
        for name in sorted(programs):
            r = programs[name]
            lines.append('  %-*s  %8s %10s %10s %10s %9s %9s %9s' %
                         (w, name, _fmt(r.get('compiles', 0)),
                          _fmt(r.get('dispatches', 0)),
                          _fmt(float(r.get('flops', 0.0))),
                          _fmt(float(r.get('bytes_accessed', 0.0))),
                          _mib(r.get('temp_bytes', 0)),
                          _mib(r.get('argument_bytes', 0)),
                          _mib(r.get('output_bytes', 0))))
    if roofline:
        lines.extend(_roofline_lines(roofline))
    if memory:
        lines.extend(_memory_lines(memory))
    if goodput:
        lines.extend(_goodput_lines(goodput))
    if cluster:
        lines.extend(_cluster_lines(cluster))
    if timeline:
        lines.extend(_timeline_lines(timeline))
    if ledger:
        lines.extend(_ledger_lines(ledger))
    if health:
        lines.extend(_health_lines(health))
    if hists:
        lines.append('-- histograms (ms) --')
        w = max(len(n) for n in hists)
        lines.append('  %-*s  %8s %10s %10s %10s %10s' %
                     (w, 'name', 'count', 'mean', 'p50', 'p95', 'max'))
        for name in sorted(hists):
            st = hists[name]
            lines.append('  %-*s  %8s %10s %10s %10s %10s' %
                         (w, name, _fmt(st['count']), _fmt(st['mean']),
                          _fmt(st['p50']), _fmt(st['p95']),
                          _fmt(st['max'])))
    if len(lines) == 1:
        lines.append('  (no metrics recorded)')
    return '\n'.join(lines)
