"""Per-layer training dynamics: watch the MODEL, not just the system.

The observability planes so far watch the run as a system — where time
goes (spans, roofline), whether values are finite (health), how hosts
compare (cluster). Nothing watches the model's layers: PR 4's sentinel
packs ONE global grad-norm, so a head whose gradients vanish or a layer
that silently dies is invisible until the final metric. XLA fuses
layers away (the compiler-opacity problem behind the named-scope
attribution; cf. arXiv:1810.09868), so per-layer statistics must be
computed IN-GRAPH — inside the already-compiled programs — and ride
the fused window's existing single fetch, exactly the machinery
``window_pipeline.health_sentinel`` proves out.

Per step, per parameter: gradient L2 norm, parameter L2 norm, update
ratio ``||dw|| / ||w||`` (the in-window delta on the fused path, the
``||g||/||w||`` pre-lr proxy on the per-batch executor path where the
optimizer runs outside the program); per named graph output: the
activation zero-fraction (a ReLU head whose output is mostly zeros is
dying). All of it packs into one f32 vector per step —
``N_STATS * n_layers + n_outputs`` floats — stacked by the fused scan
into a (W, k) matrix that comes home in the window's EXISTING fetch:
no new host<->device syncs (asserted via the registrar dispatch and
``fused_fit.fetch`` counters in tests/unittest/test_dynamics.py).

Host side, each row:

- feeds every layer's grad-norm and update-ratio into PR 4's
  :class:`~mxnet_tpu.telemetry.health.SpikeDetector` (detectors named
  ``grad_norm.<layer>`` / ``update_ratio.<layer>``) — a vanishing or
  exploding LAYER raises a named anomaly before the global norm moves;
- raises a named-layer ``dynamics`` incident on a non-finite per-layer
  statistic (``event=layer_nonfinite``, the first bad layer named) —
  complementary to health's global flag + bisect;
- publishes ``dynamics.<layer>.*`` gauges, the worst-layer roll-up
  (``dynamics.worst_layer`` / ``worst_update_ratio`` /
  ``dead_frac_max``) and a ``dynamics`` JSONL record at the decimated
  ``MXTPU_SCALARS_EVERY`` cadence (per-step publication of n_layers
  gauges would dwarf the training loop's own host work).

Gating: ``MXTPU_DYNAMICS=1`` *and* ``MXTPU_TELEMETRY=1``. Off, the
compile sites trace byte-identical programs (the PR 4/7 contract,
asserted by tests) and every entry point is one cached-bool check.
"""
import logging
import threading

import numpy as np

__all__ = ['enabled', 'every', 'step_stats', 'decode', 'note_step',
           'note_window', 'snapshot_dynamics', 'N_STATS']

N_STATS = 3
_IDX_GRAD, _IDX_PARAM, _IDX_RATIO = range(N_STATS)

_MAX_INCIDENT_WARNINGS = 3
_MAX_INCIDENTS_KEPT = 16    # dicts retained; the counter keeps the total
_DEAD_DEFAULT_EVERY = 25    # decimation fallback when MXTPU_SCALARS_EVERY=0


class _DState:
    __slots__ = ('decided', 'active', 'every', 'seen', 'incidents',
                 'incident_warnings', 'last', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.every = _DEAD_DEFAULT_EVERY
        self.seen = 0           # rows observed (== trained steps)
        self.incidents = []
        self.incident_warnings = 0
        self.last = None        # last decoded {'layers':…, 'outputs':…}
        self.lock = threading.Lock()


_state = _DState()
_decide_lock = threading.Lock()


def _tele():
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        tele_on = _tele().active
        on = False
        ev = _DEAD_DEFAULT_EVERY
        if tele_on:
            from ..config import flags
            try:
                flags.reload('MXTPU_DYNAMICS')
                flags.reload('MXTPU_SCALARS_EVERY')
                on = bool(flags.get('MXTPU_DYNAMICS'))
                ev = int(flags.get('MXTPU_SCALARS_EVERY')) \
                    or _DEAD_DEFAULT_EVERY
            except Exception:  # noqa: BLE001 — stripped builds w/o the flag
                on, ev = False, _DEAD_DEFAULT_EVERY
        _state.active = on
        _state.every = ev
        _state.decided = True
    return _state.active


def enabled():
    """Whether the per-layer dynamics plane is on: MXTPU_TELEMETRY=1
    *and* MXTPU_DYNAMICS=1, decided once. Compile sites read this at
    program-build time; after the first call it is one attribute
    check."""
    if _state.decided:
        return _state.active
    return _decide()


def every():
    """Decimation cadence (steps) for gauge/JSONL publication — the
    ledger's MXTPU_SCALARS_EVERY (its default when that is 0)."""
    enabled()
    return _state.every


# ---------------------------------------------------------------------------
# in-graph statistics
# ---------------------------------------------------------------------------

def step_stats(outs, grads, params, new_params=None):
    """The per-step per-layer dynamics vector, traced INTO a compiled
    program. Layout (f32, length ``N_STATS * len(params) + len(outs)``):

    - ``[3*i + 0]`` layer i gradient L2 norm;
    - ``[3*i + 1]`` layer i parameter L2 norm;
    - ``[3*i + 2]`` layer i update ratio ``||new - old|| / ||old||``
      when the update ran in-graph (fused window), else the pre-lr
      proxy ``||g|| / ||w||`` (per-batch executor path);
    - ``[3*n:]`` one activation zero-fraction per graph output.

    Per-layer reductions — XLA fuses them into the surrounding step the
    same way the global health sentinel fuses; the fused window ships
    the stacked (W, k) matrix home in its existing single fetch.
    """
    import jax.numpy as jnp

    eps = jnp.float32(1e-12)

    def _norm(a):
        return jnp.sqrt(jnp.sum(jnp.square(a.astype(jnp.float32))))

    rows = []
    for i, p in enumerate(params):
        gn = _norm(grads[i])
        pn = _norm(p)
        if new_params is not None:
            delta = new_params[i].astype(jnp.float32) \
                - p.astype(jnp.float32)
            ratio = _norm(delta) / (pn + eps)
        else:
            ratio = gn / (pn + eps)
        rows.extend([gn, pn, ratio])
    for o in outs:
        of = o.astype(jnp.float32)
        rows.append(jnp.mean((of == 0).astype(jnp.float32)))
    return jnp.stack(rows)


def decode(row, layer_names, out_names):
    """Host-side decode of one dynamics row -> plain dict. Non-finite
    statistics decode to None (strict-JSON safe; their non-finiteness
    is what the incident path reports)."""
    row = np.asarray(row, np.float64)

    def _f(v):
        v = float(v)
        return round(v, 8) if np.isfinite(v) else None

    layers = {}
    for i, n in enumerate(layer_names):
        base = N_STATS * i
        layers[n] = {'grad_norm': _f(row[base + _IDX_GRAD]),
                     'param_norm': _f(row[base + _IDX_PARAM]),
                     'update_ratio': _f(row[base + _IDX_RATIO])}
    tail = row[N_STATS * len(layer_names):]
    outputs = {n: _f(tail[i]) for i, n in enumerate(out_names)}
    return {'layers': layers, 'outputs': outputs}


# ---------------------------------------------------------------------------
# host-side pipeline
# ---------------------------------------------------------------------------

def _emit(rec):
    st = _tele()
    if st.active and st.sink is not None:
        st.sink.emit(rec)


def _first_bad_layer(info):
    """(layer, stat) of the first non-finite per-layer statistic, or
    None (decode turned non-finite values into None)."""
    for n, stats in info['layers'].items():
        for stat in ('grad_norm', 'param_norm', 'update_ratio'):
            if stats[stat] is None:
                return n, stat
    return None


def _incident(layer, stat, step):
    """A named-layer non-finite statistic: a `dynamics` JSONL record +
    counter + rate-limited warning. The global health sentinel fires
    for the same step when MXTPU_HEALTH is on; this record adds the
    LAYER name without waiting for the once-per-process bisect."""
    reg = _tele().registry
    reg.counter('dynamics.layer_incidents').inc()
    info = {'type': 'dynamics', 'event': 'layer_nonfinite',
            'layer': layer, 'stat': stat}
    if step is not None:
        info['step'] = int(step)
    _emit(info)
    with _state.lock:
        if len(_state.incidents) < _MAX_INCIDENTS_KEPT:
            _state.incidents.append({k: v for k, v in info.items()
                                     if k != 'type'})
        warn_ok = _state.incident_warnings < _MAX_INCIDENT_WARNINGS
        if warn_ok:
            _state.incident_warnings += 1
    msg = ('training dynamics: non-finite %s in layer %s%s'
           % (stat, layer, '' if step is None else ' at step %s' % step))
    if warn_ok:
        logging.warning('%s', msg)
    else:
        logging.debug('%s', msg)


def _feed_detectors(info):
    """Per-layer spike detection through PR 4's SpikeDetector registry
    (named ``grad_norm.<layer>`` / ``update_ratio.<layer>``) — only
    while the health plane is on; the detectors, counters and anomaly
    records belong to it."""
    from . import health as _health
    if not _health.enabled():
        return
    for n, stats in info['layers'].items():
        g = stats['grad_norm']
        if g is not None:
            _health._observe('grad_norm.%s' % n, g)
        r = stats['update_ratio']
        if r is not None:
            _health._observe('update_ratio.%s' % n, r)


def _worst(info):
    """(worst_layer, worst_update_ratio, dead_frac_max) roll-up of one
    decoded row — the layer changing fastest relative to its size, and
    the deadest output."""
    worst_layer, worst_ratio = None, None
    for n, stats in info['layers'].items():
        r = stats['update_ratio']
        if r is not None and (worst_ratio is None or r > worst_ratio):
            worst_layer, worst_ratio = n, r
    dead = [v for v in info['outputs'].values() if v is not None]
    return worst_layer, worst_ratio, (max(dead) if dead else None)


def _publish(info, step):
    """Decimated publication: per-layer gauges + the `dynamics` JSONL
    record + the worst-layer roll-up."""
    reg = _tele().registry
    for n, stats in info['layers'].items():
        for stat, v in stats.items():
            if v is not None:
                reg.gauge('dynamics.%s.%s' % (n, stat)).set(round(v, 6))
    for n, v in info['outputs'].items():
        if v is not None:
            reg.gauge('dynamics.out.%s.zero_frac' % n).set(round(v, 4))
    worst_layer, worst_ratio, dead_max = _worst(info)
    if worst_layer is not None:
        reg.gauge('dynamics.worst_layer').set(worst_layer)
        reg.gauge('dynamics.worst_update_ratio').set(round(worst_ratio, 8))
    if dead_max is not None:
        reg.gauge('dynamics.dead_frac_max').set(round(dead_max, 4))
    rec = {'type': 'dynamics', 'layers': info['layers'],
           'outputs': info['outputs']}
    if step is not None:
        rec['step'] = int(step)
    if worst_layer is not None:
        rec['worst_layer'] = worst_layer
        rec['worst_update_ratio'] = round(worst_ratio, 8)
    if dead_max is not None:
        rec['dead_frac_max'] = round(dead_max, 4)
    _emit(rec)


def _note_row(row, layer_names, out_names, step):
    """Decode + detector-feed one row; returns (info, first_bad) —
    incident emission is the caller's (so a fully-NaN window raises
    ONE incident, like the health plane, not W)."""
    info = decode(row, layer_names, out_names)
    _feed_detectors(info)
    with _state.lock:
        _state.seen += 1
        _state.last = info
        due = (_state.seen % _state.every) == 0 or _state.seen == 1
    if due:
        _publish(info, step)
    return info, _first_bad_layer(info)


def note_step(dv, layer_names, out_names, step=None):
    """Check one step's dynamics vector (per-batch executor path —
    ``dv`` rides the same host sync the health sentinel already pays).
    ``step=None`` falls back to the fit loop's health.note_batch
    context."""
    if not enabled():
        return None
    if step is None:
        from . import health as _health
        step = _health._state.cur_step
    info, bad = _note_row(np.asarray(dv), layer_names, out_names, step)
    if bad is not None:
        _incident(bad[0], bad[1], step)
    return info


def note_window(dmat, layer_names, out_names, nbatch_base=0):
    """Check a fused window's (W, k) dynamics matrix — fetched together
    with the window's one host fetch; each row keeps its exact step
    index. A window with many bad steps raises ONE incident (the
    first bad row, exact step attribution) — the health plane's
    one-incident-per-window convention."""
    if not enabled():
        return None
    mat = np.asarray(dmat)
    if mat.ndim == 1:
        mat = mat[None, :]
    last = None
    first_bad = None
    for i, row in enumerate(mat):
        last, bad = _note_row(row, layer_names, out_names,
                              nbatch_base + i)
        if bad is not None and first_bad is None:
            first_bad = (bad[0], bad[1], nbatch_base + i)
    if first_bad is not None:
        _incident(*first_bad)
    return last


def snapshot_dynamics():
    """Point-in-time per-layer dynamics dict (JSON-serializable) — the
    watch line's and the ledger's input. None while the plane is off
    or before the first observed step."""
    if not enabled():
        return None
    with _state.lock:
        if _state.last is None:
            return None
        info = _state.last
        out = {'steps': _state.seen,
               'layers': {n: dict(s) for n, s in info['layers'].items()},
               'outputs': dict(info['outputs']),
               'incidents': [dict(i) for i in _state.incidents[:8]]}
    reg = _tele().registry
    n_inc = int(reg.counter('dynamics.layer_incidents').value)
    if n_inc:
        out['layer_incidents'] = n_inc
    worst_layer, worst_ratio, dead_max = _worst(info)
    if worst_layer is not None:
        out['worst_layer'] = worst_layer
        out['worst_update_ratio'] = round(worst_ratio, 8)
    if dead_max is not None:
        out['dead_frac_max'] = round(dead_max, 4)
    return out


def _reset_for_tests():
    global _state
    _state = _DState()
