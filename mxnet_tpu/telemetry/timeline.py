"""Pod-level step timeline: clock alignment + critical-path attribution.

The cluster plane (telemetry/cluster.py) can already NAME the slowest
host of a gang; this module answers the next question — which PHASE on
that host gates the pod, and by how much. Three pieces:

- **clock alignment**: every sync-round allgather already acts as a
  barrier, so the instant it returns is (approximately) the same true
  time on every host. Each host samples ``(wall, monotonic)`` at that
  instant (:func:`note_sync_exit`) and contributes the pair in the
  NEXT round's sync vector — zero new collectives, the vector just
  grows (NaN-padded for senders predating the slots). Process 0 turns
  each round's wall samples into per-host offsets against the fleet
  median, keeps a bounded ring per host, and publishes the ring median
  as ``cluster.h<i>.clock_offset_ms`` — NTP-style, drift-tolerant, and
  robust to one noisy barrier exit. A wall clock that STEPS (ntpdate)
  betrays itself against the monotonic companion and its ring is
  discarded rather than averaged across the step.
- **step-phase ledger**: the hot loops already emit spans for every
  host-side phase (draw, put, dispatch, fetch, checkpoint, kvstore
  push/pull); :func:`note_span` buckets their durations per phase
  (:data:`PHASE_SPANS`), and each sync round ships this host's
  per-step phase milliseconds over the round window in the same grown
  sync vector. ``tools/trace_merge.py`` stitches the per-host span
  records / chrome traces into ONE offset-corrected Perfetto trace
  with ``pid=host``.
- **critical-path attribution**: per sync round, process 0 decomposes
  the gang step into compute / collective-wait / io / host-side per
  host (:func:`decompose`), reads the skew (fastest-host idle at the
  allreduce = slowest minus fastest step time) and names the gating
  host AND phase — the phase on the slowest host with the largest
  excess over the fleet's best (``timeline.critical_host``,
  ``timeline.critical_phase``, ``timeline.skew_ms`` gauges, a "step
  timeline" summary block, ``timeline`` JSONL records). That sharpens
  "host 3 is slow" into "host 3's input draw adds 4.1 ms of skew per
  step".

Gating: ``MXTPU_TIMELINE=1`` *and* ``MXTPU_TELEMETRY=1``. Off = true
no-op: one cached-bool check per entry point, no registry writes, no
I/O, and the lowered programs are byte-identical (everything here is
host-side arithmetic over already-collected numbers — asserted by
tests/unittest/test_timeline.py like every prior plane).
"""
import collections
import math
import threading
import time

import numpy as np

__all__ = ['PHASES', 'PHASE_SPANS', 'SLOTS', 'CLOCK_RING', 'enabled',
           'note_span', 'note_step', 'note_sync_exit', 'local_slots',
           'estimate_offsets', 'decompose', 'attribute', 'publish_round',
           'summarize', 'snapshot_timeline', 'phase_breakdown']

# the ledger's phases, in sync-vector slot order (SLOTS[2 + k] carries
# PHASES[k]); 'collective' and 'compute' are DERIVED per round from the
# step time + the roofline comm share, never shipped
PHASES = ('draw', 'put', 'dispatch', 'fetch', 'checkpoint', 'kvstore')

# this plane's appended cluster.SYNC_KEYS slots, in order: the clock
# pair sampled at the PREVIOUS round's allgather exit, then each
# phase's per-step milliseconds over the round window. All NaN while
# MXTPU_TIMELINE is off (the append-only/NaN-pad vector rule holds)
SLOTS = ('clock_wall_s', 'clock_mono_s', 'tl_draw_ms', 'tl_put_ms',
         'tl_dispatch_ms', 'tl_fetch_ms', 'tl_ckpt_ms', 'tl_kv_ms')

# LEAF span -> phase. Only leaves (goodput.py's double-count rule):
# parents like fit.batch never feed, or a phase would count twice.
PHASE_SPANS = {
    'fit.draw': 'draw', 'fused_fit.draw': 'draw',
    'fused_fit.put': 'put',
    'fit.dispatch': 'dispatch', 'fused_fit.dispatch': 'dispatch',
    'bench.dispatch': 'dispatch',
    'fused_fit.fetch': 'fetch', 'fit.metric': 'fetch',
    'ckpt.save': 'checkpoint', 'ckpt.capture': 'checkpoint',
    'kvstore.push': 'kvstore', 'kvstore.pull': 'kvstore',
}

CLOCK_RING = 16        # per-host offset samples backing the median
# wall minus monotonic advancing differently by more than this between
# two rounds = the wall clock STEPPED (ntpdate, not drift): the host's
# ring history predates a different clock and is discarded
_WALL_STEP_MS = 250.0
# the sync vector travels as float32 (cluster._allgather), whose
# resolution at epoch magnitude (~1.7e9 s) is ~2 MINUTES — raw
# time.time() would swallow any skew. Both clock samples therefore
# ship modulo this window: float32 below 64 resolves ~8 µs, and the
# offset math is circular (true inter-host skews beyond ±32 s alias,
# far past anything clock sync leaves standing)
CLOCK_MOD = 64.0


def _wrap(d):
    """Centre a CLOCK_MOD-circular difference into [-32 s, +32 s)."""
    return float(d - CLOCK_MOD * np.floor(d / CLOCK_MOD + 0.5))


class _TState:
    __slots__ = ('decided', 'active', 'lock', 'steps', 'wall_ms',
                 'last_t', 't_start', 'phase_ms', 'round_base',
                 'round_steps', 'pend_wall', 'pend_mono', 'offset_rings',
                 'last_pair', 'last')

    def __init__(self):
        self.decided = False
        self.active = False
        self.lock = threading.Lock()
        # local step/wall bookkeeping (every host)
        self.steps = 0
        self.wall_ms = 0.0          # wall between note_step calls
        self.last_t = None
        self.t_start = None
        self.phase_ms = {p: 0.0 for p in PHASES}   # cumulative, run-long
        self.round_base = dict(self.phase_ms)      # snapshot at last round
        self.round_steps = 0
        # the clock pair sampled at the last sync-round barrier exit,
        # shipped in the NEXT round's vector (NaN before the first)
        self.pend_wall = float('nan')
        self.pend_mono = float('nan')
        # process-0 aggregation state
        self.offset_rings = {}      # host -> deque of per-round offsets
        self.last_pair = {}         # host -> (wall, mono) of prior round
        self.last = None            # last attribution dict


_state = _TState()
_decide_lock = threading.Lock()


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        on = False
        if _tele().active:
            from ..config import flags
            try:
                flags.reload('MXTPU_TIMELINE')
                on = bool(flags.get('MXTPU_TIMELINE'))
            except Exception:  # noqa: BLE001 — stripped builds w/o the flag
                on = False
        _state.active = on
        _state.decided = True
    return _state.active


def enabled():
    """Whether the timeline plane is on: MXTPU_TIMELINE=1 *and*
    MXTPU_TELEMETRY=1, decided once. One attribute check after the
    first call — the span tap's and the fit loops' gate."""
    if _state.decided:
        return _state.active
    return _decide()


# ---------------------------------------------------------------------------
# local ledger (every host)
# ---------------------------------------------------------------------------

def note_span(name, dur_ms):
    """Span tap (telemetry._Span.__exit__, already inside the
    telemetry-active branch): bucket a finished leaf span's duration
    into its phase. Non-phase spans cost one dict miss."""
    if not enabled():
        return
    p = PHASE_SPANS.get(name)
    if p is None:
        return
    st = _state
    with st.lock:
        st.phase_ms[p] += dur_ms


def note_step(steps=1):
    """Hot-path hook (both fit loops, same seam as memory.note_step):
    count trained steps and the wall between calls, so the phase
    ledger can normalize to per-step milliseconds."""
    if not enabled():
        return
    now = time.time()
    st = _state
    with st.lock:
        if st.t_start is None:
            st.t_start = now
        if st.last_t is not None and steps > 0:
            st.wall_ms += (now - st.last_t) * 1e3
        st.last_t = now
        st.steps += steps
        st.round_steps += steps


def note_sync_exit():
    """Called on EVERY host the instant the sync-round allgather
    returns (cluster.sync_now): the barrier exit is the shared-time
    reference. The pair ships in the NEXT round's vector. An armed
    ``clock-skew`` fault (faults.py) shifts the wall sample here —
    injected drift the estimator must then name."""
    if not enabled():
        return
    from .. import faults
    wall = time.time() + faults.clock_skew_ms() / 1e3
    mono = time.monotonic()
    st = _state
    with st.lock:
        st.pend_wall = wall
        st.pend_mono = mono


def local_slots():
    """This host's contribution to the sync vector (SLOTS order): the
    pending clock pair + per-step phase ms over the round window.
    All-NaN while off — the vector's shape never depends on the flag."""
    if not enabled():
        return [float('nan')] * len(SLOTS)
    st = _state
    with st.lock:
        wall, mono = st.pend_wall, st.pend_mono
        steps = st.round_steps
        deltas = [st.phase_ms[p] - st.round_base[p] for p in PHASES]
        st.round_base = dict(st.phase_ms)
        st.round_steps = 0
    # modulo the float32-safe window (see CLOCK_MOD); NaN stays NaN
    out = [wall % CLOCK_MOD, mono % CLOCK_MOD]
    out.extend((d / steps) if steps > 0 else float('nan') for d in deltas)
    return out


# ---------------------------------------------------------------------------
# offset estimation (pure math + the process-0 rings)
# ---------------------------------------------------------------------------

def estimate_offsets(walls):
    """One round's wall samples -> per-row offset_ms against the fleet
    median (NaN rows — senders without a sample yet — stay NaN). The
    samples arrive modulo CLOCK_MOD, so the math is circular: deltas
    against the first finite sample, centred into ±CLOCK_MOD/2, then
    re-based on their median — identical to a plain median for
    non-wrapping inputs. Pure; the unit the drift tests pin."""
    walls = np.asarray(walls, np.float64)
    valid = np.isfinite(walls)
    if not valid.any():
        return [float('nan')] * len(walls)
    anchor = float(walls[valid][0])
    d = np.array([_wrap(w - anchor) for w in walls])
    ref = float(np.median(d[valid]))
    return [float((x - ref) * 1e3) if ok else float('nan')
            for x, ok in zip(d, valid)]


def _note_round_clocks(walls, monos, host_ids):
    """Fold one round's gathered clock samples into the per-host
    offset rings; returns {host: ring-median offset_ms}. A wall that
    stepped against its monotonic companion resets that host's ring."""
    st = _state
    offs = estimate_offsets(walls)
    out = {}
    with st.lock:
        for i, hid in enumerate(host_ids):
            w = float(walls[i])
            m = float(monos[i]) if i < len(monos) else float('nan')
            if not math.isfinite(w):
                continue
            prev = st.last_pair.get(hid)
            if prev is not None and math.isfinite(m) \
                    and math.isfinite(prev[1]) \
                    and abs(_wrap((w - prev[0]) - (m - prev[1]))) * 1e3 \
                    > _WALL_STEP_MS:
                st.offset_rings.pop(hid, None)
            st.last_pair[hid] = (w, m)
            if math.isfinite(offs[i]):
                ring = st.offset_rings.get(hid)
                if ring is None:
                    ring = st.offset_rings[hid] = collections.deque(
                        maxlen=CLOCK_RING)
                ring.append(offs[i])
        for hid in sorted(st.offset_rings):
            ring = st.offset_rings[hid]
            if ring:
                out[hid] = float(np.median(list(ring)))
    return out


# ---------------------------------------------------------------------------
# critical-path attribution (pure, shared with the offline CLIs)
# ---------------------------------------------------------------------------

def _finite(v):
    try:
        return v is not None and math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


def decompose(step_ms, phases, comm_pct=None):
    """One host's per-step decomposition (ms): collective-wait from the
    roofline's comm share, io = draw + put, host-side = fetch +
    checkpoint + kvstore, compute = the clamped remainder. Pure."""
    step = float(step_ms) if _finite(step_ms) else None
    def f(name):
        v = phases.get(name)
        return float(v) if _finite(v) else 0.0
    coll = step * float(comm_pct) / 100.0 \
        if step is not None and _finite(comm_pct) else 0.0
    io = f('draw') + f('put')
    host = f('fetch') + f('checkpoint') + f('kvstore')
    compute = max(0.0, step - coll - io - host) if step is not None else 0.0
    return {'compute_ms': compute, 'collective_ms': coll, 'io_ms': io,
            'host_ms': host}


def attribute(mat, host_ids=None, step=None, offsets=None):
    """Critical-path attribution for one gathered sync matrix: the
    per-host gang-step decomposition, the skew (fastest-host idle at
    the allreduce), and the gating host AND phase — the phase on the
    slowest host with the largest excess over the fleet's best host
    (a single-host round falls back to the largest share). Pure math
    over the matrix — shared by the live publish path, the offline
    CLIs and the unit tests."""
    from . import cluster as _cluster
    mat = np.asarray(mat, np.float64)
    if mat.ndim == 1:
        mat = mat[None, :]
    n = mat.shape[0]
    if host_ids is None:
        host_ids = _cluster._host_ids(mat)
    keys = _cluster.SYNC_KEYS

    def col(name):
        j = keys.index(name)
        return [float(mat[i, j]) if j < mat.shape[1] else float('nan')
                for i in range(n)]

    times = col('step_time_ms')
    comms = col('comm_pct')
    phase_cols = {p: col(SLOTS[2 + k]) for k, p in enumerate(PHASES)}
    decomps = []
    per_host = []
    for i in range(n):
        phases = {p: phase_cols[p][i] for p in PHASES}
        d = decompose(times[i], phases,
                      comms[i] if _finite(comms[i]) else None)
        decomps.append(d)
        row = {'host': host_ids[i],
               'step_time_ms': round(times[i], 3) if _finite(times[i])
               else None}
        row.update({k: round(v, 3) for k, v in d.items()})
        row['phases'] = {p: round(phases[p], 3) if _finite(phases[p])
                         else None for p in PHASES}
        if offsets and host_ids[i] in offsets:
            row['clock_offset_ms'] = round(offsets[host_ids[i]], 3)
        per_host.append(row)
    out = {'hosts': n, 'per_host': per_host}
    if step is not None:
        out['step'] = int(step)
    valid = [i for i in range(n) if _finite(times[i])]
    if not valid:
        return out
    crit = max(valid, key=lambda i: times[i])
    tmax, tmin = times[crit], min(times[i] for i in valid)
    out['gang_step_ms'] = round(tmax, 3)
    out['skew_ms'] = round(tmax - tmin, 3) if len(valid) > 1 else 0.0
    out['critical_host'] = host_ids[crit]
    # candidates: every measured ledger phase plus the derived compute/
    # collective splits. Multi-host: a candidate's score is the slowest
    # host's EXCESS over the fleet's best host — how much skew that
    # phase adds per step. Single host: the raw share (largest wins).
    cand = {}
    series = {p: phase_cols[p] for p in PHASES}
    series['compute'] = [d['compute_ms'] for d in decomps]
    series['collective'] = [d['collective_ms'] for d in decomps]
    for name, vals in series.items():
        v = vals[crit]
        if not _finite(v):
            continue
        if len(valid) > 1:
            others = [vals[i] for i in valid if _finite(vals[i])]
            if not others:
                continue
            cand[name] = float(v) - min(float(o) for o in others)
        else:
            cand[name] = float(v)
    if cand:
        phase = max(sorted(cand), key=lambda k: cand[k])
        out['critical_phase'] = phase
        out['phase_excess_ms'] = round(max(0.0, cand[phase]), 3)
    return out


# ---------------------------------------------------------------------------
# publication (process 0, once per sync round) + summary
# ---------------------------------------------------------------------------

def publish_round(mat, host_ids, steps):
    """Process 0, per sync round (cluster._publish): fold the round's
    clock samples into the offset rings, attribute the gang step, and
    publish the gauges + the ``timeline`` JSONL record. Returns the
    attribution dict, or None while off."""
    if not enabled():
        return None
    from . import cluster as _cluster
    mat = np.asarray(mat, np.float64)
    if mat.ndim == 1:
        mat = mat[None, :]
    keys = _cluster.SYNC_KEYS
    n = mat.shape[0]

    def col(name):
        j = keys.index(name)
        return [float(mat[i, j]) if j < mat.shape[1] else float('nan')
                for i in range(n)]

    offsets = _note_round_clocks(col('clock_wall_s'), col('clock_mono_s'),
                                 host_ids)
    out = attribute(mat, host_ids, step=steps, offsets=offsets)
    _publish_snapshot(out, offsets)
    return out


def _publish_snapshot(out, offsets=None):
    """Gauges + JSONL record + the stored snapshot for one attribution
    dict (the sync-round path and the end-of-run fallback share it)."""
    st = _tele()
    reg = st.registry
    for hid, off in sorted((offsets or {}).items()):
        reg.gauge('cluster.h%d.clock_offset_ms' % hid).set(round(off, 3))
    if out.get('gang_step_ms') is not None:
        reg.gauge('timeline.gang_step_ms').set(out['gang_step_ms'])
    if out.get('skew_ms') is not None:
        reg.gauge('timeline.skew_ms').set(out['skew_ms'])
    if out.get('critical_host') is not None:
        reg.gauge('timeline.critical_host').set(out['critical_host'])
    if out.get('critical_phase') is not None:
        reg.gauge('timeline.critical_phase').set(out['critical_phase'])
    with _state.lock:
        _state.last = out
    if st.sink is not None:
        rec = {'type': 'timeline'}
        rec.update(out)
        st.sink.emit(rec)


def _local_attribution():
    """A single-host attribution from this host's own ledger (no sync
    round ever published): per-step wall from the note_step stream,
    phases from the span tap, comm share from the roofline. None
    before any counted step."""
    st = _state
    with st.lock:
        steps = st.steps
        wall_ms = st.wall_ms
        phases = {p: st.phase_ms[p] for p in PHASES}
    if steps <= 0:
        return None
    from . import cluster as _cluster, roofline
    keys = _cluster.SYNC_KEYS
    row = [float('nan')] * len(keys)
    # the first note_step opens the wall window, so wall_ms spans
    # steps-1 intervals in the per-batch loop; the fused loop notes
    # whole windows, where steps per interval is exact — use the
    # honest denominator and accept the per-batch off-by-one
    if wall_ms > 0:
        step_ms = wall_ms / steps
    else:
        # a run short enough to fit in ONE window never opened a wall
        # interval — fall back to the span histograms, with the same
        # per-step normalization the offline per-host table uses
        snap = _tele().registry.snapshot()
        hists, gauges = snap['histograms'], snap['gauges']
        h = hists.get('fit.batch')
        w = gauges.get('fused_fit.steps_per_call')
        if h and h.get('count') and h.get('p50') is not None:
            step_ms = float(h['p50'])
        elif w:
            h = hists.get('fused_fit.dispatch')
            step_ms = float(h['p50']) / float(w) \
                if h and h.get('count') and h.get('p50') is not None \
                else float('nan')
        else:
            step_ms = float('nan')
    row[keys.index('step_time_ms')] = step_ms
    comm, _src = roofline.comm_share()
    if comm is not None:
        row[keys.index('comm_pct')] = float(comm)
    row[keys.index('proc_index')] = float(_cluster.host_index())
    for k, p in enumerate(PHASES):
        row[keys.index(SLOTS[2 + k])] = phases[p] / steps
    return attribute([row], step=steps)


def summarize():
    """End-of-run roll-up (telemetry.write_summary): the last published
    sync-round attribution, or — on a run that never synced — a
    single-host attribution from the local ledger, published the same
    way. Returns the summary record's 'timeline' dict, or None."""
    if not enabled():
        return None
    with _state.lock:
        last = dict(_state.last) if _state.last else None
    if last is not None:
        return last
    out = _local_attribution()
    if out is None:
        return None
    _publish_snapshot(out)
    return out


def snapshot_timeline():
    """The last attribution (sync round or end-of-run local), or None
    — the /summary key and the summary table's block input."""
    with _state.lock:
        return dict(_state.last) if _state.last else None


def phase_breakdown():
    """{compute,collective,io,host}_pct of the step for bench.py's
    ``step_phase_breakdown`` BENCH field (host_overhead_pct is what
    bench_diff gates). Reads the last attribution, else derives a
    local one read-only. None while off / before any counted step."""
    if not enabled():
        return None
    out = snapshot_timeline() or _local_attribution()
    if not out or not out.get('per_host'):
        return None
    rows = out['per_host']
    # the slowest host's row is the pod's step (bench runs are
    # single-host, where the only row is it)
    crit = out.get('critical_host')
    row = next((r for r in rows if r.get('host') == crit), rows[0])
    step = row.get('step_time_ms')
    if not step:
        return None
    return {k + '_pct': round(100.0 * (row.get(k + '_ms') or 0.0) / step, 2)
            for k in ('compute', 'collective', 'io', 'host')}


def _reset_for_tests():
    global _state
    _state = _TState()
