"""Hang watchdog: detect a wedged training loop and act on it.

The health plane (telemetry/health.py) catches runs that compute the
*wrong* numbers; nothing so far catches a run that stops computing at
all — a collective waiting on a dead host, a tunneled dispatch that
never returns, a deadlocked input pipeline. Those block forever: the
process is alive (so ``tools/train_supervisor.py`` sees nothing wrong)
but no step ever completes.

``MXTPU_WATCHDOG_SECS=<t>`` arms a daemon-thread progress monitor fed
by the hot loops' existing progress sites — per-batch/per-window
dispatch (fit and eval), cluster sync rounds, kvstore push/pull,
checkpoint commits — each calling :func:`note_progress` (one
cached-bool check plus a clock store; nothing is ever traced into a
compiled program). The monitor arms at the FIRST mark (so a long
initial compile cannot false-trip) and then requires a mark at least
every ``t`` seconds. On a stall it:

- dumps every thread's stack plus the last progress mark and key
  telemetry counters as a ``hang`` JSONL incident (when telemetry is
  on) and logs the same digest;
- flips ``/healthz`` to 503 with a ``hung`` status until progress
  resumes (telemetry/serve.py reads :func:`hang_info`);
- under ``MXTPU_WATCHDOG_ACTION=abort`` exits the process with the
  distinct code :data:`HANG_EXIT_CODE` (85) after flushing the JSONL
  sink, so the supervisor relaunches from the last-good checkpoint.
  The exit is ``os._exit`` by design: a thread wedged inside a
  collective cannot be unwound, only replaced.

Off (the default) = no thread is ever created and every progress site
costs one cached-bool check — the telemetry stack's asserted
zero-overhead contract. The watchdog is independent of
``MXTPU_TELEMETRY`` (a hang is worth aborting on even without the
metrics plane); only the JSONL record and the /healthz digest need
telemetry on. Pick ``t`` above the worst LEGITIMATE gap between marks:
an XLA recompile (new shapes mid-run) can take 20-40s on a tunneled
chip, and marks pause while it runs.
"""
import logging
import os
import sys
import threading
import time
import traceback

__all__ = ['HANG_EXIT_CODE', 'enabled', 'note_progress', 'suspend',
           'hang_info', 'snapshot_watchdog', 'stop', 'add_abort_hook',
           'remove_abort_hook']

# distinct from every exit code the training stack produces (python
# tracebacks exit 1, CLI misuse 2, signals 128+n): the supervisor's
# restart records name it, and an operator grepping exit codes can
# attribute the death to the watchdog. Mirrored as _HANG_EXIT in
# tools/train_supervisor.py (which must not import the framework).
HANG_EXIT_CODE = 85

_MIN_POLL_S = 0.05
_STACK_LIMIT = 24          # frames kept per thread in the hang digest
_ABORT_HOOK_CAP_S = 30.0   # hard bound on abort-hook work: the exit
                           # must happen even if a hook wedges too

# callables run (bounded, best-effort) before an abort exit — the
# checkpointer registers its drain-and-certify here so the last
# in-flight save still becomes the relaunch's last-good instead of
# dying uncommitted with the wedged main thread
_abort_hooks = []
_hook_lock = threading.Lock()


def add_abort_hook(fn):
    """Register ``fn`` to run (on a side thread, bounded by
    _ABORT_HOOK_CAP_S in total) before an ``action=abort`` exit.
    Idempotent per callable."""
    with _hook_lock:
        if fn not in _abort_hooks:
            _abort_hooks.append(fn)


def remove_abort_hook(fn):
    with _hook_lock:
        try:
            _abort_hooks.remove(fn)
        except ValueError:
            pass


class _WState:
    __slots__ = ('decided', 'active', 'secs', 'action', 'thread',
                 'stop_ev', 'last_mark', 'last_what', 'marks',
                 'tripped', 'hang', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.secs = 0.0
        self.action = 'warn'
        self.thread = None
        self.stop_ev = None
        self.last_mark = None     # time.time() of the newest mark
        self.last_what = None
        self.marks = 0
        self.tripped = False      # an un-recovered hang is on record
        self.hang = None          # the last hang digest (dict)
        self.lock = threading.Lock()


_state = _WState()
_decide_lock = threading.Lock()


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        secs = 0.0
        action = 'warn'
        try:
            from ..config import flags
            flags.reload('MXTPU_WATCHDOG_SECS')
            flags.reload('MXTPU_WATCHDOG_ACTION')
            secs = float(flags.get('MXTPU_WATCHDOG_SECS'))
            action = flags.get('MXTPU_WATCHDOG_ACTION')
        except Exception:  # noqa: BLE001 — stripped builds without the flag
            secs = 0.0
        _state.secs = secs
        _state.action = action
        _state.active = secs > 0.0
        _state.decided = True
    return _state.active


def enabled():
    """Whether the watchdog is armed (MXTPU_WATCHDOG_SECS > 0, decided
    once). One attribute check after the first call — the progress
    sites' gate. The monitor thread only starts at the first
    :func:`note_progress` call, so an armed-but-idle process still has
    no extra thread."""
    if _state.decided:
        return _state.active
    return _decide()


def note_progress(what):
    """Hot-path progress mark: the loop made forward progress of kind
    ``what`` ('fit.step', 'fused_fit.window', 'eval.step',
    'cluster.sync', 'kvstore.push', 'ckpt.save', ...). Off = one
    cached-bool check. The first mark arms the monitor thread; a mark
    arriving after a hang incident marks it recovered (and /healthz
    goes green again)."""
    if not enabled():
        return
    st = _state
    # monotonic, not wall: an NTP step across a mark gap must neither
    # false-trip a hang (forward step > threshold would, under abort,
    # kill a healthy run) nor mask a real one (backward step)
    st.last_mark = time.monotonic()
    st.last_what = what
    st.marks += 1
    if st.thread is None:
        _start()
    elif st.tripped:
        recovered = None
        with st.lock:
            if st.tripped:
                st.tripped = False
                if st.hang is not None:
                    st.hang['active'] = False
                    recovered = st.hang.get('stalled_s')
        if recovered is not None:
            logging.warning(
                'watchdog: progress resumed (%s) after a %.1fs stall — '
                'clearing the hang state', what, recovered)


def suspend():
    """The supervised region ended (fit returned or unwound): stop
    expecting marks until the next one arrives, so a process doing
    legitimate post-training host work — or idling between
    epoch-at-a-time fit() calls — can never false-trip (and, under
    action=abort, never gets killed while healthy). An ACTIVE hang is
    cleared too: with the region over, "the loop is stalled right now"
    is no longer a claim anyone can stand behind, and a stale 503
    ``hung`` /healthz would get a healthy process evicted. The next
    :func:`note_progress` re-arms automatically."""
    if not enabled():
        return
    _state.last_mark = None
    _state.last_what = None
    with _state.lock:
        if _state.tripped:
            _state.tripped = False
            if _state.hang is not None:
                _state.hang['active'] = False


def hang_info():
    """The ACTIVE hang digest (the loop is stalled right now), or None.
    telemetry/serve.py flips /healthz to 503 on it."""
    with _state.lock:
        if _state.hang is not None and _state.hang.get('active'):
            return dict(_state.hang)
    return None


def snapshot_watchdog():
    """Point-in-time watchdog state for reports: the last hang digest
    (recovered or not) or None when the run never stalled."""
    with _state.lock:
        return dict(_state.hang) if _state.hang is not None else None


# ---------------------------------------------------------------------------
# monitor thread
# ---------------------------------------------------------------------------

def _start():
    with _state.lock:
        if _state.thread is not None:
            return
        _state.stop_ev = threading.Event()
        _state.thread = threading.Thread(
            target=_monitor, name='mxtpu-watchdog', daemon=True)
        _state.thread.start()


def _monitor():
    st = _state
    poll = max(_MIN_POLL_S, st.secs / 4.0)
    ev = st.stop_ev
    while not ev.wait(poll):
        last = st.last_mark
        if last is None or st.tripped:
            continue
        stalled = time.monotonic() - last
        if stalled > st.secs:
            try:
                _trip(stalled)
            except Exception as e:  # noqa: BLE001 — the monitor must
                # survive anything (incl. a test reset racing the trip):
                # a watchdog that dies of its own reporting is worse
                # than the hang it watches for
                logging.warning('watchdog: hang reporting failed: %s', e)


def _thread_stacks():
    """{thread name: [frame lines]} for every live thread, the
    watchdog thread excluded (its own stack is noise)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    out = {}
    for ident, frame in sys._current_frames().items():
        if ident == me:
            continue
        lines = traceback.format_stack(frame, limit=_STACK_LIMIT)
        out[names.get(ident, 'thread-%d' % ident)] = \
            [ln.rstrip('\n') for ln in lines]
    return out


def _telemetry_digest():
    """The last telemetry state worth having in a hang report: the
    step/window counters and the newest health step time. Empty when
    telemetry is off — the watchdog does not require it."""
    from . import _state as tst
    if not tst.active:
        return {}
    reg = tst.registry
    out = {}
    for name in ('fit.steps', 'fused_fit.windows', 'cluster.syncs',
                 'ckpt.saves', 'eval.batches'):
        c = reg.get(name)
        if c is not None and getattr(c, 'value', 0):
            out[name] = c.value
    g = reg.get('health.step_time_ms')
    if g is not None and g.value:
        out['health.step_time_ms'] = g.value
    return out


def _trip(stalled):
    """One stall crossed the threshold: record the hang incident and
    apply MXTPU_WATCHDOG_ACTION (runs on the monitor thread — the
    wedged thread cannot run anything)."""
    st = _state
    digest = {
        'active': True,
        'stalled_s': round(stalled, 2),
        'threshold_s': st.secs,
        'last_progress': st.last_what,
        'marks': int(st.marks),
        'action': st.action,
        'telemetry': _telemetry_digest(),
        'stacks': _thread_stacks(),
    }
    with st.lock:
        if st.tripped:     # raced a concurrent trip
            return
        st.tripped = True
        st.hang = digest
    from . import _state as tst, counter as _counter
    _counter('watchdog.hangs').inc()
    rec = {'type': 'hang'}
    rec.update(digest)
    rec.pop('active')
    if tst.active and tst.sink is not None:
        tst.sink.emit(rec)
        tst.sink.flush()    # the process may be about to die — no buffer
    # flight recorder: the spans/records BEFORE the stall are exactly
    # what the postmortem wants (and under action=abort this is the
    # last chance to write them)
    try:
        from . import flight
        flight.dump('hang', extra={'stalled_s': digest['stalled_s'],
                                   'last_progress':
                                   digest['last_progress']})
    except Exception:  # noqa: BLE001 — forensics must not add a crash
        pass
    logging.warning(
        'watchdog: no training progress for %.1fs (threshold %.1fs; '
        'last mark: %s) — the run looks hung. Thread stacks recorded%s',
        stalled, st.secs, st.last_what or 'none',
        ' in the telemetry JSONL' if tst.active and tst.sink is not None
        else ' in this log')
    for name, frames in digest['stacks'].items():
        logging.warning('watchdog: stack of %s:\n%s', name,
                        ''.join('%s\n' % f for f in frames[-6:]))
    if st.action == 'abort':
        logging.warning(
            'watchdog: MXTPU_WATCHDOG_ACTION=abort — exiting with code '
            '%d so the supervisor relaunches from last-good',
            HANG_EXIT_CODE)
        # bounded drain: give the checkpointer a chance to commit and
        # certify its in-flight save (the wedged main thread never
        # will), but NEVER let a wedged hook block the exit itself
        with _hook_lock:
            hooks = list(_abort_hooks)
        if hooks:
            def _run_hooks():
                for fn in hooks:
                    try:
                        fn()
                    except Exception as e:  # noqa: BLE001
                        logging.warning('watchdog: abort hook %r failed: '
                                        '%s', fn, e)
            ht = threading.Thread(target=_run_hooks,
                                  name='mxtpu-watchdog-drain', daemon=True)
            ht.start()
            ht.join(timeout=_ABORT_HOOK_CAP_S)
            if ht.is_alive():
                logging.warning('watchdog: abort hooks still running '
                                'after %.0fs — exiting anyway',
                                _ABORT_HOOK_CAP_S)
        if tst.active and tst.sink is not None:
            try:
                tst.sink.close()
            except Exception:  # noqa: BLE001
                pass
        # os._exit, not sys.exit: the hung thread is wedged inside a
        # dispatch/collective and will never unwind; atexit hooks would
        # block on it (and orbax's commit pool) forever
        os._exit(HANG_EXIT_CODE)


def stop():
    """Tear the monitor thread down (telemetry shutdown / test resets).
    No-op when it never started."""
    with _state.lock:
        th, ev = _state.thread, _state.stop_ev
        _state.thread = _state.stop_ev = None
    if ev is not None:
        ev.set()
    if th is not None:
        th.join(timeout=5)


def _reset_for_tests():
    global _state
    stop()
    with _hook_lock:
        del _abort_hooks[:]
    _state = _WState()
