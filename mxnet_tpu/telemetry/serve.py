"""Live telemetry plane: /metrics, /healthz and /summary over HTTP.

Everything PRs 1-4 record is post-hoc (JSONL + end-of-run table); a
production run serving heavy traffic needs metrics that can be scraped
*while the run is live*. This module is the opt-in endpoint:

- ``/metrics`` — the registry snapshot (counters, gauges incl. the
  ``program.*`` / ``health.*`` / ``cluster.*`` families, histograms) in
  Prometheus text exposition format, every sample labeled with this
  process's ``host`` index;
- ``/healthz`` — 200 while no non-finite incident is on record, 503
  once one is (telemetry/health.py's incident state), with the
  incident/anomaly digest as the JSON body — a probe's view of PR 4;
- ``/summary`` — the ``export.summary_table`` inputs (registry
  snapshot, programs, health, cluster, roofline) plus the rendered
  table, as JSON — what ``tools/telemetry_watch.py`` polls.

Transport is stdlib ``http.server`` (ThreadingHTTPServer) on a daemon
thread — no new dependencies, dies with the process. Gating:
``MXTPU_TELEMETRY=1`` *and* ``MXTPU_TELEMETRY_PORT`` set (0 binds an
OS-assigned ephemeral port; -1/unset = off). With the port unset or
telemetry off, no thread or socket is ever created — the asserted
zero-overhead no-op contract extends here (tests/unittest/
test_serve.py). Scrapes only READ registry state; a scrape can never
perturb, block or kill the training loop (handler errors answer 500).
"""
import json
import logging
import re
import threading

__all__ = ['maybe_start', 'start', 'stop', 'port', 'render_prometheus',
           'healthz_payload', 'summary_payload']

_CONTENT_PROM = 'text/plain; version=0.0.4; charset=utf-8'
_THREAD_NAME = 'mxtpu-telemetry-serve'

_server = None
_thread = None
_lock = threading.Lock()


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _prom_name(name):
    return 'mxtpu_' + re.sub(r'[^a-zA-Z0-9_]', '_', name)


def _prom_num(v):
    """Prometheus sample value, or None for non-numeric gauges."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    if f != f:
        return 'NaN'
    if f == float('inf'):
        return '+Inf'
    if f == float('-inf'):
        return '-Inf'
    if f == int(f) and abs(f) < 1e15:
        return '%d' % int(f)
    return repr(f)


def _prom_label_val(v):
    """A value escaped for a Prometheus label position (text format
    0.0.4: backslash, double-quote and newline must be escaped, in that
    order — an info-style gauge carrying a path or an error string must
    not break the whole scrape)."""
    return str(v).replace('\\', '\\\\').replace('"', '\\"') \
        .replace('\n', '\\n')


def render_prometheus(snapshot, host=None):
    """A registry snapshot as Prometheus text exposition (format 0.0.4).

    Counters render with the conventional ``_total`` suffix, histograms
    as summaries carrying the recent-window p50/p95 quantiles plus
    ``_sum``/``_count`` (values are milliseconds, hence the ``_ms``
    suffix). Every sample is labeled ``host="<process index>"`` so a
    Prometheus scraping all hosts of a multi-host job can aggregate and
    diff them. Non-numeric gauges (e.g. ``cluster.straggler_class``)
    render info-style: value in a label, sample fixed at 1."""
    hl = 'host="%s"' % host if host is not None else ''

    def lbl(extra=''):
        parts = [p for p in (hl, extra) if p]
        return '{%s}' % ','.join(parts) if parts else ''

    lines = []
    counters = snapshot.get('counters', {})
    for name in sorted(counters):
        m = _prom_name(name) + '_total'
        lines.append('# HELP %s mxnet_tpu counter %s' % (m, name))
        lines.append('# TYPE %s counter' % m)
        lines.append('%s%s %s' % (m, lbl(), _prom_num(counters[name])))
    gauges = snapshot.get('gauges', {})
    for name in sorted(gauges):
        v = gauges[name]
        m = _prom_name(name)
        lines.append('# HELP %s mxnet_tpu gauge %s' % (m, name))
        lines.append('# TYPE %s gauge' % m)
        num = _prom_num(v)
        if num is None:
            lines.append('%s%s 1'
                         % (m, lbl('value="%s"' % _prom_label_val(v))))
        else:
            lines.append('%s%s %s' % (m, lbl(), num))
    hists = snapshot.get('histograms', {})
    for name in sorted(hists):
        st = hists[name]
        m = _prom_name(name) + '_ms'
        lines.append('# HELP %s mxnet_tpu span histogram %s '
                     '(milliseconds; quantiles over the recent window)'
                     % (m, name))
        lines.append('# TYPE %s summary' % m)
        for q, key in (('0.5', 'p50'), ('0.95', 'p95')):
            if st.get(key) is not None:
                lines.append('%s%s %s' % (m, lbl('quantile="%s"' % q),
                                          _prom_num(st[key])))
        # exemplar: a sibling info-style gauge (NOT an OpenMetrics '#'
        # suffix — the 0.0.4 text format this endpoint declares has no
        # exemplar syntax, and a strict scraper would fail the whole
        # scrape on one). The highest-valued recent exemplar-carrying
        # observation lands with its labels, so a scraped p95/p99
        # still links to a concrete trace id
        ex = st.get('exemplar')
        if ex and ex.get('labels'):
            em = m + '_exemplar'
            lines.append('# HELP %s mxnet_tpu exemplar for %s (recent '
                         'high sample and the trace that produced it)'
                         % (em, name))
            lines.append('# TYPE %s gauge' % em)
            lines.append('%s%s %s' % (
                em,
                lbl(','.join('%s="%s"'
                             % (k, _prom_label_val(ex['labels'][k]))
                             for k in sorted(ex['labels']))),
                _prom_num(float(ex['value']))))
        lines.append('%s_sum%s %s' % (m, lbl(),
                                      _prom_num(float(st.get('sum') or 0.0))))
        lines.append('%s_count%s %s' % (m, lbl(),
                                        _prom_num(int(st.get('count') or 0))))
    return '\n'.join(lines) + '\n'


# ---------------------------------------------------------------------------
# endpoint payloads
# ---------------------------------------------------------------------------

def healthz_payload():
    """(ok, digest) for /healthz. ``ok`` flips False — the endpoint
    answers 503 — once a non-finite incident is on record, the hang
    watchdog says the loop is stalled right now, the SLO plane's
    error budget is burning (telemetry/slo.py), OR the memory plane's
    steps-to-OOM forecast is at/below threshold (telemetry/memory.py).
    The unhealthy states are DISTINCT (``degraded`` / ``hung`` /
    ``slo_degraded`` / ``mem_pressure``) so a supervisor or load
    balancer can choose its reaction: evict a hung replica, page on
    slo_degraded, checkpoint-and-shrink on mem_pressure, keep a
    warn-action NaN run visible. The digest carries the health
    snapshot, the active hang digest, the SLO snapshot, the memory
    forecast and the last cluster round; hang, SLO and mem-pressure
    states clear automatically on recovery."""
    from . import health, cluster, watchdog, slo, memory
    st = _tele()
    hs = health.snapshot_health(input_bound=health.input_bound_pct()) \
        if st.active else None
    bad = int(hs.get('nonfinite_steps') or 0) if hs else 0
    hang = watchdog.hang_info()
    slo_bad = slo.degraded()
    mem_bad = memory.pressure_info()
    body = {
        'status': 'hung' if hang is not None
        else ('slo_degraded' if slo_bad is not None
              else ('mem_pressure' if mem_bad is not None
                    else ('ok' if not bad else 'degraded'))),
        'telemetry': bool(st.active),
        'health_sentinels': bool(health.enabled()),
        'host': cluster.host_index(),
    }
    if hang is not None:
        body['hang'] = hang
    if mem_bad is not None:
        body['mem_pressure'] = mem_bad
    if hs is not None:
        body['health'] = hs
    slo_snap = slo.snapshot_slo()
    if slo_snap is not None:
        body['slo'] = slo_snap
    clus = cluster.snapshot_cluster()
    if clus:
        body['cluster'] = clus
    return (bad == 0 and hang is None and slo_bad is None
            and mem_bad is None), body


def summary_payload():
    """The /summary JSON: the same inputs the end-of-run summary table
    renders from, read-only (no gauges written, no records emitted),
    plus the rendered table itself."""
    import time
    from . import programs, health, cluster, roofline, slo
    from . import dynamics, ledger, goodput, memory, timeline
    from .export import summary_table
    st = _tele()
    snap = st.registry.snapshot()
    elapsed = (time.time() - st.t_start) if st.t_start else None
    progs = programs.snapshot_programs() or None
    hs = health.snapshot_health(input_bound=health.input_bound_pct())
    clus = cluster.snapshot_cluster()
    led = ledger.snapshot_ledger()
    # roofline (MXTPU_ROOFLINE): the last published analysis, else a
    # fresh read-only one (warn_unknown=False: analyze writes no
    # gauges — not even peaks_unknown — and emits no records; the
    # scrape convention holds). events=[] forces the MODELED path: a
    # scrape must never re-load and re-parse a multi-MB profiler
    # capture from disk
    roof = roofline.snapshot_roofline() \
        or roofline.analyze(events=[], warn_unknown=False)
    # goodput: a fresh read-only attribution (no gauges, no record) so
    # a mid-run scrape sees live numbers, not the last summary's
    good = goodput.current()
    # memory: same convention — a fresh read-only analysis (pure: no
    # gauges written, no records emitted)
    mem = memory.analyze()
    # timeline: the last sync round's critical-path attribution, read
    # only — a scrape never advances the clock rings or emits a record
    tl = timeline.snapshot_timeline()
    return {
        'elapsed_s': round(elapsed, 3) if elapsed is not None else None,
        'host': cluster.host_index(),
        'snapshot': snap,
        'programs': progs,
        'health': hs,
        'cluster': clus,
        'roofline': roof,
        'slo': slo.snapshot_slo(),
        'ledger': led,
        'dynamics': dynamics.snapshot_dynamics(),
        'goodput': good,
        'memory': mem,
        'timeline': tl,
        'table': summary_table(snap, elapsed, programs=progs, health=hs,
                               cluster=clus, roofline=roof, ledger=led,
                               goodput=good, memory=mem, timeline=tl),
    }


# ---------------------------------------------------------------------------
# HTTP plumbing
# ---------------------------------------------------------------------------

def _make_handler():
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        server_version = 'mxtpu-telemetry'

        def log_message(self, fmt, *args):   # no stderr line per scrape
            logging.debug('telemetry.serve: ' + fmt, *args)

        def _send(self, code, body, ctype):
            data = body.encode('utf-8')
            self.send_response(code)
            self.send_header('Content-Type', ctype)
            self.send_header('Content-Length', str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path = self.path.split('?', 1)[0].rstrip('/') or '/'
            try:
                if path == '/metrics':
                    from . import cluster
                    body = render_prometheus(_tele().registry.snapshot(),
                                             host=cluster.host_index())
                    self._send(200, body, _CONTENT_PROM)
                elif path == '/healthz':
                    ok, digest = healthz_payload()
                    self._send(200 if ok else 503,
                               json.dumps(digest, indent=2) + '\n',
                               'application/json')
                elif path == '/summary':
                    self._send(200,
                               json.dumps(summary_payload(), indent=2)
                               + '\n', 'application/json')
                elif path == '/':
                    self._send(200, 'mxnet_tpu telemetry endpoints: '
                               '/metrics /healthz /summary\n', 'text/plain')
                else:
                    self._send(404, 'not found\n', 'text/plain')
            except Exception as e:  # noqa: BLE001 — a scrape must not kill
                logging.debug('telemetry.serve: handler failed: %s', e)
                try:
                    self._send(500, 'internal error\n', 'text/plain')
                except Exception:  # noqa: BLE001
                    pass

    return Handler


def maybe_start():
    """Start the endpoint iff telemetry is on AND MXTPU_TELEMETRY_PORT
    is set (>= 0). Called from telemetry's decide path; with the port
    unset (or telemetry off) this touches no socket and spawns no
    thread. Returns the bound port, or None."""
    if not _tele().active:
        return None
    from ..config import flags
    try:
        flags.reload('MXTPU_TELEMETRY_PORT')
        p = flags.get('MXTPU_TELEMETRY_PORT')
    except Exception:  # noqa: BLE001 — stripped builds without the flag
        p = -1
    if p is None or p < 0:
        return None
    return start(p)


def _bind_address():
    """MXTPU_TELEMETRY_BIND: loopback by default — exposing /metrics
    to the network is an explicit opt-in ('0.0.0.0' or empty = all
    interfaces, documented in docs/observability.md)."""
    from ..config import flags
    try:
        flags.reload('MXTPU_TELEMETRY_BIND')
        addr = flags.get('MXTPU_TELEMETRY_BIND')
    except Exception:  # noqa: BLE001 — stripped builds without the flag
        addr = '127.0.0.1'
    if addr is None:
        return '127.0.0.1'
    addr = addr.strip()
    return '' if addr == '0.0.0.0' else addr


def start(port_):
    """Bind and serve on a daemon thread; idempotent (returns the
    already-bound port). ``port_=0`` asks the OS for an ephemeral port;
    the bind address comes from MXTPU_TELEMETRY_BIND (loopback unless
    opted out). A bind failure warns and returns None — observability
    must not take the run down."""
    global _server, _thread
    with _lock:
        if _server is not None:
            return _server.server_address[1]
        from http.server import ThreadingHTTPServer
        try:
            srv = ThreadingHTTPServer((_bind_address(), int(port_)),
                                      _make_handler())
        except OSError as e:
            logging.warning('telemetry: cannot bind the live endpoint on '
                            'port %s (%s) — live scraping disabled for '
                            'this run', port_, e)
            return None
        srv.daemon_threads = True
        _server = srv
        _thread = threading.Thread(target=srv.serve_forever,
                                   name=_THREAD_NAME, daemon=True)
        _thread.start()
        bound = srv.server_address[1]
    logging.info('telemetry: live endpoint on :%d '
                 '(/metrics /healthz /summary)', bound)
    return bound


def port():
    """The live endpoint's bound port, or None while it is not up."""
    with _lock:
        return _server.server_address[1] if _server is not None else None


def stop():
    """Shut the endpoint down (telemetry.shutdown / test resets).
    No-op when it never started."""
    global _server, _thread
    with _lock:
        srv, th = _server, _thread
        _server = _thread = None
    if srv is not None:
        try:
            srv.shutdown()
            srv.server_close()
        except Exception:  # noqa: BLE001
            pass
    if th is not None:
        th.join(timeout=5)
