"""Roofline attribution: per-layer achieved-vs-peak diagnosis.

The registrar (:mod:`.programs`) knows each compiled program's total
FLOPs and bytes; the MXTPU_XPROF capture knows where device time went;
neither alone says *which layer to fix*. This module joins them the way
cost-model-driven compiler stacks do (TVM, arXiv:1802.04799): place
every layer on the device's roofline (Williams et al., the
operational-intensity model) and classify what bounds it.

Data flow, all host-side (the compiled programs are untouched — the
lowered HLO is byte-identical with the flag on or off):

1. **per-layer costs** — when a compile site registers a program,
   :func:`note_compiled` parses its HLO text. Every instruction carries
   ``metadata={op_name="..."}`` with the ``jax.named_scope`` layer name
   PR 3 planted (executor nodes, fused-window bodies); shapes give
   bytes, and dot/convolution contraction dims give FLOPs. The parsed
   totals are calibrated against XLA's own ``cost_analysis()`` /
   ``memory_analysis()`` numbers so the per-layer split always sums to
   what XLA reports for the whole program.
2. **measured timings** — a ``jax.profiler`` capture (the MXTPU_XPROF
   trace, or MXTPU_ROOFLINE_TRACE) is parsed as chrome-trace JSON;
   events are keyed back to layers through the HLO instruction names.
   Without a capture the measured step time is *distributed* across
   layers in proportion to each layer's roofline-minimum time
   (``source: modeled`` — the CPU/best-effort fallback).
3. **classification** — per layer: achieved FLOP/s, achieved bytes/s,
   arithmetic intensity, and the placement against the peak table
   (:func:`.xla.device_peaks`): the roofline-minimum time is
   ``max(flops/peak_flops, bytes/peak_hbm)``; a layer whose FLOPs term
   dominates is **compute-bound**, one whose bytes term dominates is
   **memory-bound**, and one running far below both ceilings
   (< ``OVERHEAD_UTIL_PCT`` of its roofline) — or carrying no cost at
   all — is **overhead-bound**.
4. **communication accounting** — all-reduce / all-gather /
   collective-permute / reduce-scatter / all-to-all instructions are
   summed separately: bytes on the wire per step, measured (or
   modeled) collective time, the comm share of the step, and the
   fraction of collective time overlapped with compute — the
   per-collective numbers the cluster straggler classifier's
   ``communication_bound`` verdict is grounded in.

Surfacing: a ranked bottleneck block in the end-of-run summary table
("layer, class, achieved/peak %, est. headroom ms"), a ``roofline``
JSONL record carrying the full analysis, ``roofline.*`` gauges on
/metrics and /summary, a ``roofline`` section in BENCH json, and
``tools/roofline_report.py`` offline (byte-identical block).

Gating: ``MXTPU_ROOFLINE=1`` *and* ``MXTPU_TELEMETRY=1``. Off = the
zero-overhead no-op contract of the rest of the plane: no HLO text is
ever rendered or parsed, no registry writes, one cached-bool check at
the registrar hook.
"""
import gzip
import json
import logging
import os
import re
import threading

__all__ = ['enabled', 'note_compiled', 'note_hlo', 'hlo_layer_costs',
           'load_trace_events', 'analyze', 'summarize', 'republish',
           'snapshot_roofline', 'comm_bytes_by_op', 'comm_share',
           'comm_pct_of_step', 'suggest_action',
           'RECLAIM_ACTIONS', 'TOP_N',
           'OVERHEAD_UTIL_PCT', 'CLASS_COMPUTE', 'CLASS_MEMORY',
           'CLASS_OVERHEAD']

TOP_N = 8                  # bottleneck rows rendered in the summary block
OVERHEAD_UTIL_PCT = 10.0   # below this % of its roofline ceiling a
                           # measured layer classifies overhead-bound
CLASS_COMPUTE = 'compute-bound'
CLASS_MEMORY = 'memory-bound'
CLASS_OVERHEAD = 'overhead-bound'
CLASS_UNKNOWN = 'unknown'  # no peak table entry for this device

# class -> the concrete lever to pull (the docs/perf.md "Closing the
# MFU gap" guide, kept next to the classifier so the two never drift):
# which knob in THIS codebase reclaims a layer of that class
RECLAIM_ACTIONS = {
    CLASS_MEMORY: 'cut HBM traffic: MXTPU_BN_ONEPASS=1 one-pass stats, '
                  'full window donation (MXTPU_FUSED_DONATE=1), '
                  'layout work',
    CLASS_COMPUTE: 'remove work: MXTPU_REMAT_POLICY=none keeps forward '
                   'residuals (no backward recompute); shrink the math',
    CLASS_OVERHEAD: 'fuse/batch: raise MXTPU_FIT_STEPS_PER_CALL, keep '
                    'the upload overlapped (MXTPU_FUSED_FIT_PREFETCH=1); '
                    'MXTPU_REMAT_POLICY=dots/full if temp-bound',
}


def suggest_action(cls):
    """The lever string for a bottleneck class ('' for unknown): what
    docs/perf.md's class->action guide says to pull, machine-readable
    so the worst layer's record/gauge names its remedy directly."""
    return RECLAIM_ACTIONS.get(cls, '')

# HLO opcode prefixes that move bytes between chips instead of running
# math — the communication-accounting family ('-start' variants match
# by prefix; '-done' halves are skipped so nothing counts twice)
COMM_OPS = ('all-reduce', 'all-gather', 'collective-permute',
            'reduce-scatter', 'all-to-all', 'collective-broadcast')

_lock = threading.Lock()
_decided = None
_programs = {}   # name -> parsed per-layer cost store (see _ingest)
_last = None     # last published analysis dict (snapshot_roofline)
_explicit_step_ms = None   # measured per-step ms a caller fed summarize()


def _tele():
    from . import enabled as tele_enabled
    tele_enabled()
    from . import _state as st
    return st


def enabled():
    """MXTPU_ROOFLINE=1 and telemetry on (decided once; off = one
    cached-bool check at the registrar hook)."""
    global _decided
    if _decided is None:
        from . import enabled as tele_enabled
        on = tele_enabled()
        if on:
            from ..config import flags
            try:
                on = bool(flags.get('MXTPU_ROOFLINE'))
            except Exception:  # noqa: BLE001 — stripped builds
                on = False
        _decided = on
    return _decided


# ---------------------------------------------------------------------------
# HLO text -> per-layer cost parse
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    'pred': 1, 's2': 1, 'u2': 1, 's4': 1, 'u4': 1, 's8': 1, 'u8': 1,
    'f8e5m2': 1, 'f8e4m3': 1, 'f8e4m3fn': 1, 'f8e4m3b11fnuz': 1,
    'f8e5m2fnuz': 1, 'f8e4m3fnuz': 1,
    's16': 2, 'u16': 2, 'f16': 2, 'bf16': 2,
    's32': 4, 'u32': 4, 'f32': 4,
    's64': 8, 'u64': 8, 'f64': 8, 'c64': 8, 'c128': 16,
}

_INSTR_RE = re.compile(
    r'^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\([^)]*\)|\S+)\s+([\w\-]+)\(')
_SHAPE_RE = re.compile(r'\b([a-z]+[0-9]+[a-z0-9]*|pred)\[([0-9,]*)\]')
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_CONTRACT_RE = re.compile(r'lhs_contracting_dims=\{([0-9,]*)\}')
_DIM_LABELS_RE = re.compile(r'dim_labels=([\w?]+)_([\w?]+)->([\w?]+)')

# scope segments that are tracing machinery, not layer names. jit()
# segments are FUNCTION boundaries (jit(main), jit(relu)) — dropped
# whole; AD/transform wrappers carry the layer name INSIDE
# (jvp(fc1), transpose(jvp(fc1))) — peeled until the bare name appears
_JIT_RE = re.compile(r'^(jit|pjit)\(')
_XFORM_RE = re.compile(
    r'^(jvp|vjp|transpose|vmap|pmap|xmap|shard_map|remat|'
    r'checkpoint|custom_jvp|custom_vjp|named)\((.*)\)$')
_WRAP_WORDS = frozenset(('while', 'body', 'cond', 'branch', 'scan',
                         'closed_call', 'core_call'))

# opcodes that are pure data movement / bookkeeping: no FLOPs, and no
# bytes either (a reshape/bitcast costs nothing at run time; counting
# its shapes would double every real operand)
_FREE_OPS = frozenset((
    'parameter', 'constant', 'tuple', 'get-tuple-element', 'bitcast',
    'reshape', 'transpose', 'broadcast', 'iota', 'copy', 'copy-start',
    'copy-done', 'after-all', 'partition-id', 'replica-id', 'domain',
    'opt-barrier', 'custom-call', 'rng-get-and-update-state',
    'get-dimension-size',
))

# wrapper instructions whose cost lives in a separately-parsed called
# computation: contribute nothing here (their bodies' instructions are
# parsed on their own lines), but their NAMES are what device-trace
# events carry, so they are indexed for the trace join
_CALL_OPS = frozenset(('fusion', 'while', 'call', 'conditional',
                       'async-start', 'async-done'))


def _unwrap_seg(seg):
    """One scope segment -> the layer name it carries, or None.
    ``transpose(jvp(fc1))`` -> ``fc1``; ``jit(relu)`` -> None (a
    function boundary, not a layer); ``while``/``body`` -> None."""
    while True:
        if _JIT_RE.match(seg):
            return None
        m = _XFORM_RE.match(seg)
        if not m:
            break
        seg = m.group(2)
    if not seg or seg in _WRAP_WORDS:
        return None
    return seg


def _layer_from_op_name(op_name):
    """The ``jax.named_scope`` layer in an HLO ``op_name`` path, or
    None. ``jit(f)/jit(main)/fc1/dot_general`` -> ``fc1`` and
    ``jit(f)/while/body/transpose(jvp(fc1))/dot_general`` -> ``fc1``:
    function/loop wrappers are dropped, transform wrappers are peeled,
    the last remaining segment is the primitive, the first before it
    is the layer the framework planted."""
    segs = []
    for s in str(op_name).split('/'):
        u = _unwrap_seg(s)
        if u is not None:
            segs.append(u)
    if len(segs) >= 2:
        return segs[0]
    return None


def _shape_bytes(dtype, dims):
    n = 1
    for d in dims.split(','):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4), n


def _instr_flops(opcode, line, out_elems, operands):
    """Estimated FLOPs for one instruction. Exact-ish for the terms
    that matter (dot: 2*out*K from the contracting dims; convolution:
    2*out*kernel/out_features from dim_labels); one-flop-per-output for
    the elementwise/reduce rest; zero for data movement."""
    if opcode == 'dot':
        k = 1
        m = _CONTRACT_RE.search(line)
        if m and operands:
            lhs_dims = operands[0][1]
            for idx in m.group(1).split(','):
                if idx and int(idx) < len(lhs_dims):
                    k *= lhs_dims[int(idx)]
        return 2.0 * out_elems * k
    if opcode == 'convolution':
        if len(operands) >= 2:
            kern = operands[1][1]
            kern_elems = 1
            for d in kern:
                kern_elems *= d
            out_feat = 1
            m = _DIM_LABELS_RE.search(line)
            if m:
                o_idx = m.group(2).find('o')
                if 0 <= o_idx < len(kern):
                    out_feat = kern[o_idx]
            elif kern:
                out_feat = kern[0]
            return 2.0 * out_elems * kern_elems / max(1, out_feat)
        return 0.0
    if opcode in ('reduce', 'reduce-window'):
        # one op per INPUT element, not per output
        if operands:
            n = 1
            for d in operands[0][1]:
                n *= d
            return float(n)
        return float(out_elems)
    if opcode in _FREE_OPS:
        return 0.0
    return float(out_elems)


def hlo_layer_costs(hlo_text):
    """Parse an HLO module's text into the per-layer cost store::

        {'layers':      {layer: {'flops': f, 'bytes': b}},
         'instr_layer': {instruction_name: layer},
         'comm_instrs': set(instruction names of collective ops),
         'comm_bytes':  total bytes written by collectives (per step),
         'comm_ops':    {opcode: bytes},
         'flops_total': parsed-FLOPs sum, 'bytes_total': parsed-bytes sum}

    Best-effort by construction: unparsed lines cost nothing, ops
    without a named scope pool under ``_unattributed``. A scan/while
    body is parsed once — the same per-step convention XLA's own
    cost_analysis uses."""
    layers = {}
    instr_layer = {}
    comm_instrs = set()
    comm_ops = {}
    comm_bytes = 0.0
    flops_total = bytes_total = 0.0
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_sig, opcode = m.groups()
        out_bytes = out_elems = 0
        for dt, dims in _SHAPE_RE.findall(out_sig):
            b, n = _shape_bytes(dt, dims)
            out_bytes += b
            out_elems += n
        rest = line[m.end():]
        # operand shapes live between the opcode '(' and the attrs; the
        # attr tail (window/dim_labels/metadata) carries no shapes, so
        # scanning the rest of the line is safe
        operands = []
        for dt, dims in _SHAPE_RE.findall(rest):
            b, _n = _shape_bytes(dt, dims)
            dims_t = tuple(int(d) for d in dims.split(',') if d)
            operands.append((b, dims_t))
        is_comm = any(opcode.startswith(c) for c in COMM_OPS)
        if is_comm:
            comm_instrs.add(name)
            if not opcode.endswith('-done'):
                comm_bytes += out_bytes
                comm_ops[opcode] = comm_ops.get(opcode, 0.0) + out_bytes
            continue
        if opcode in _FREE_OPS:
            continue
        mo = _OP_NAME_RE.search(line)
        layer_hint = _layer_from_op_name(mo.group(1)) if mo else None
        if opcode in _CALL_OPS:
            # zero cost (the called computation's lines carry it), but
            # the name->layer index is what the trace join keys on —
            # device events are fusion-granular
            if layer_hint is not None:
                instr_layer[name] = layer_hint
            continue
        flops = _instr_flops(opcode, line, out_elems, operands)
        nbytes = float(out_bytes + sum(b for b, _d in operands))
        layer = layer_hint or '_unattributed'
        rec = layers.setdefault(layer, {'flops': 0.0, 'bytes': 0.0})
        rec['flops'] += flops
        rec['bytes'] += nbytes
        instr_layer[name] = layer
        flops_total += flops
        bytes_total += nbytes
    return {'layers': layers, 'instr_layer': instr_layer,
            'comm_instrs': comm_instrs, 'comm_bytes': comm_bytes,
            'comm_ops': comm_ops, 'flops_total': flops_total,
            'bytes_total': bytes_total}


# ---------------------------------------------------------------------------
# registrar hook (telemetry.programs.note_program calls this)
# ---------------------------------------------------------------------------

def note_hlo(name, hlo_text, analysis=None, step_flops=False):
    """Ingest one program's HLO text (tests feed synthetic modules
    here; live compiles arrive via :func:`note_compiled`). ``analysis``
    is the registrar's cost/memory dict — its ``flops`` /
    ``bytes_accessed`` calibrate the parsed per-layer split."""
    if not enabled():
        return
    costs = hlo_layer_costs(hlo_text)
    costs['analysis'] = dict(analysis or {})
    costs['step'] = bool(step_flops)
    costs['name'] = name
    with _lock:
        prev = _programs.get(name)
        if prev is not None and \
                prev['flops_total'] > costs['flops_total']:
            # keep the largest variant per name — the registrar's own
            # merge rule (a tail-batch recompile must not shrink the
            # roofline the run is judged by)
            return
        _programs[name] = costs


def note_compiled(name, compiled, analysis=None, step_flops=False):
    """The live hook: render ``compiled.as_text()`` and ingest it.
    Never raises — attribution is best-effort, execution is not."""
    if not enabled():
        return
    try:
        note_hlo(name, compiled.as_text(), analysis=analysis,
                 step_flops=step_flops)
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('roofline: HLO ingest of %s failed: %s', name, e)


def _pick_step_program():
    """The program the roofline diagnoses: the step-marked one with the
    most FLOPs (the registrar's MFU-feed rule), else the largest
    program seen at all."""
    with _lock:
        progs = list(_programs.values())
    if not progs:
        return None
    step = [p for p in progs if p['step']]
    pool = step or progs
    return max(pool, key=lambda p: (p.get('analysis', {}).get('flops')
                                    or p['flops_total']))


# ---------------------------------------------------------------------------
# profiler trace -> measured per-layer timings
# ---------------------------------------------------------------------------

def load_trace_events(path):
    """Chrome-trace events from a ``jax.profiler`` capture. ``path`` is
    the capture directory (``plugins/profile/<run>/*.trace.json.gz`` is
    searched recursively) or a ``.trace.json``/``.json.gz`` file.
    Returns the raw event dicts (empty list when nothing parses)."""
    files = []
    if os.path.isdir(path):
        for root, _dirs, names in os.walk(path):
            for n in sorted(names):
                if n.endswith(('.trace.json', '.trace.json.gz')) or \
                        n in ('trace.json', 'trace.json.gz'):
                    files.append(os.path.join(root, n))
    elif os.path.isfile(path):
        files = [path]
    events = []
    for f in files:
        opener = gzip.open if f.endswith('.gz') else open
        try:
            with opener(f, 'rt') as fh:
                data = json.load(fh)
        except Exception as e:  # noqa: BLE001 — a bad capture is skipped
            logging.debug('roofline: cannot parse trace %s: %s', f, e)
            continue
        evs = data.get('traceEvents', data) if isinstance(data, dict) \
            else data
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
    return events


def _union(ivals):
    """Merge (start, end) intervals; returns the disjoint sorted list."""
    out = []
    for s, e in sorted(ivals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _intersection_us(a, b):
    """Total overlap between two disjoint sorted interval lists."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _join_trace(prog, events):
    """Key trace events back to layers through the HLO instruction
    names (fall back to op_name scope extraction from the event args).
    Returns None when nothing matched — the caller then models instead
    of pretending to have measured."""
    per_layer_us = {}
    per_instr_count = {}
    comm_us = 0.0
    comm_ivals, compute_ivals = [], []
    instr_layer = prog['instr_layer']
    comm_instrs = prog['comm_instrs']
    for ev in events:
        if ev.get('ph') != 'X':
            continue
        try:
            dur = float(ev.get('dur') or 0.0)
            ts = float(ev.get('ts') or 0.0)
        except (TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        nm = str(ev.get('name', '')).lstrip('%')
        args = ev.get('args') or {}
        layer = instr_layer.get(nm)
        is_comm = nm in comm_instrs or \
            any(nm.startswith(c) for c in COMM_OPS)
        if layer is None and not is_comm:
            for key in ('name', 'long_name', 'tf_op', 'op_name'):
                v = args.get(key)
                if not v:
                    continue
                cand = str(v).lstrip('%').split(' ', 1)[0]
                layer = instr_layer.get(cand) \
                    or _layer_from_op_name(str(v))
                if layer is not None:
                    break
        if is_comm:
            comm_us += dur
            comm_ivals.append((ts, ts + dur))
        elif layer is not None:
            per_layer_us[layer] = per_layer_us.get(layer, 0.0) + dur
            per_instr_count[nm] = per_instr_count.get(nm, 0) + 1
            compute_ivals.append((ts, ts + dur))
    if not per_layer_us and not comm_us:
        return None
    # the capture usually spans several steps: every instruction fires
    # once per dispatch, so the modal per-instruction event count IS
    # the number of steps captured
    counts = sorted(per_instr_count.values())
    steps = counts[len(counts) // 2] if counts else 1
    overlap_us = _intersection_us(_union(comm_ivals),
                                  _union(compute_ivals))
    return {'per_layer_us': per_layer_us, 'comm_us': comm_us,
            'overlap_us': overlap_us, 'steps': max(1, steps)}


def _default_trace_path():
    from ..config import flags
    try:
        p = flags.get('MXTPU_ROOFLINE_TRACE')
    except Exception:  # noqa: BLE001
        p = ''
    if p:
        return os.path.expanduser(p)
    try:
        d = flags.get('MXTPU_XPROF_DIR')
    except Exception:  # noqa: BLE001
        d = ''
    d = os.path.expanduser(d or 'xprof_trace')
    return d if os.path.isdir(d) else None


# ---------------------------------------------------------------------------
# the join: classification + communication accounting
# ---------------------------------------------------------------------------

def _registry_step_ms(reg):
    """Best per-step milliseconds from the registry (the modeled path's
    denominator): fused window dispatch p50 / W, else the per-batch
    dispatch p50, else the bench dispatch p50 normalized by bench's
    steps-per-dispatch (one bench.dispatch span covers STEPS_PER_CALL
    steps — fit.steps counts them per dispatch)."""
    h = reg.get('fused_fit.dispatch')
    if h is not None and h.count:
        p50 = h.percentile(50)
        w = reg.get('fused_fit.steps_per_call')
        if p50 and w is not None and w.value:
            return float(p50) / float(w.value)
    h = reg.get('fit.dispatch')
    if h is not None and h.count:
        p50 = h.percentile(50)
        if p50:
            return float(p50)
    h = reg.get('bench.dispatch')
    if h is not None and h.count:
        p50 = h.percentile(50)
        if p50:
            steps_c = reg.get('fit.steps')
            if steps_c is not None and steps_c.value:
                per_dispatch = float(steps_c.value) / h.count
                if per_dispatch >= 1.0:
                    return float(p50) / per_dispatch
            return float(p50)
    return None


def _classify(flops, nbytes, time_ms, peaks, measured):
    """(class, roof_ms, roof_pct) for one layer against the peaks."""
    if peaks['flops'] <= 0 or peaks['hbm_bytes_s'] <= 0:
        return CLASS_UNKNOWN, None, None
    if flops <= 0 and nbytes <= 0:
        return CLASS_OVERHEAD, 0.0, 0.0
    ft = flops / peaks['flops']
    bt = nbytes / peaks['hbm_bytes_s']
    roof_ms = max(ft, bt) * 1e3
    cls = CLASS_COMPUTE if ft >= bt else CLASS_MEMORY
    roof_pct = None
    if time_ms and time_ms > 0:
        roof_pct = min(100.0, 100.0 * roof_ms / time_ms)
        if measured and roof_pct < OVERHEAD_UTIL_PCT:
            # far below BOTH ceilings: the time went to something the
            # roofline cannot see (launch gaps, transposes, small-op
            # scheduling) — overhead, not math
            cls = CLASS_OVERHEAD
    return cls, roof_ms, roof_pct


def analyze(step_time_ms=None, events=None, trace_path=None,
            device=None, warn_unknown=True):
    """Compute the roofline analysis dict (no publication — see
    :func:`summarize`). Returns None when roofline is off or no
    program has been ingested.

    ``step_time_ms`` overrides the registry-derived per-step time;
    ``events`` injects pre-parsed trace events (tests), else
    ``trace_path`` / the MXTPU_ROOFLINE_TRACE / MXTPU_XPROF_DIR capture
    is loaded when one exists. ``warn_unknown=False`` makes the call
    truly read-only (the unknown-device peak lookup neither warns nor
    writes the ``roofline.peaks_unknown`` gauge — the scrape path)."""
    if not enabled():
        return None
    prog = _pick_step_program()
    if prog is None:
        return None
    from . import xla
    peaks = xla.device_peaks(device, warn=warn_unknown)
    if events is None:
        path = trace_path or _default_trace_path()
        events = load_trace_events(path) if path else []
    joined = _join_trace(prog, events) if events else None
    measured = joined is not None and bool(joined['per_layer_us'])

    analysis = prog.get('analysis') or {}
    # calibrate the parsed split against XLA's own whole-program totals
    # so per-layer numbers sum to what cost_analysis reported
    scale_f = scale_b = 1.0
    if analysis.get('flops') and prog['flops_total'] > 0:
        scale_f = float(analysis['flops']) / prog['flops_total']
    if analysis.get('bytes_accessed') and prog['bytes_total'] > 0:
        scale_b = float(analysis['bytes_accessed']) / prog['bytes_total']

    reg = _tele().registry
    if step_time_ms is None:
        step_time_ms = _registry_step_ms(reg)

    trace_steps = joined['steps'] if joined else None
    rows = []
    roof_total_ms = 0.0
    layer_items = sorted(prog['layers'].items())
    for layer, c in layer_items:
        flops = c['flops'] * scale_f
        nbytes = c['bytes'] * scale_b
        if peaks['flops'] > 0 and peaks['hbm_bytes_s'] > 0:
            roof_total_ms += max(flops / peaks['flops'],
                                 nbytes / peaks['hbm_bytes_s']) * 1e3
        rows.append([layer, flops, nbytes])

    if measured:
        source = 'measured'
        layer_ms = {l: joined['per_layer_us'][l] / joined['steps'] / 1e3
                    for l in joined['per_layer_us']}
    else:
        source = 'modeled'
        # distribute the measured step time across layers in proportion
        # to each one's roofline-minimum time (perfect execution would
        # land exactly there); with no step time either, assume the
        # roofline itself
        layer_ms = {}
        for layer, flops, nbytes in rows:
            if peaks['flops'] > 0 and peaks['hbm_bytes_s'] > 0:
                roof = max(flops / peaks['flops'],
                           nbytes / peaks['hbm_bytes_s']) * 1e3
            else:
                roof = 0.0
            if step_time_ms and roof_total_ms > 0:
                layer_ms[layer] = step_time_ms * roof / roof_total_ms
            else:
                layer_ms[layer] = roof

    out_rows = []
    for layer, flops, nbytes in rows:
        t_ms = layer_ms.get(layer, 0.0)
        cls, roof_ms, roof_pct = _classify(flops, nbytes, t_ms, peaks,
                                           measured)
        row = {'layer': layer, 'class': cls,
               'flops': round(flops, 1), 'bytes': round(nbytes, 1),
               'time_ms': round(t_ms, 4),
               'ai': round(flops / nbytes, 3) if nbytes > 0 else None,
               'achieved_flops_s': round(flops / (t_ms / 1e3), 1)
               if t_ms > 0 else None,
               'achieved_bytes_s': round(nbytes / (t_ms / 1e3), 1)
               if t_ms > 0 else None,
               'roof_pct': round(roof_pct, 1)
               if roof_pct is not None else None,
               'headroom_ms': round(max(0.0, t_ms - roof_ms), 4)
               if roof_ms is not None else None}
        out_rows.append(row)
    out_rows.sort(key=lambda r: (-(r['headroom_ms'] or 0.0),
                                 -r['time_ms'], r['layer']))

    # communication accounting (bytes are per step by the scan-body
    # convention; time measured from the capture, else modeled at the
    # HBM ceiling — a deliberate lower bound, labeled as such)
    comm_bytes = prog['comm_bytes']
    comm = None
    if comm_bytes > 0 or (joined and joined['comm_us'] > 0):
        if joined and joined['comm_us'] > 0:
            comm_ms = joined['comm_us'] / joined['steps'] / 1e3
            overlap_pct = round(100.0 * joined['overlap_us']
                                / joined['comm_us'], 1)
            comm_src = 'measured'
        else:
            comm_ms = (comm_bytes / peaks['hbm_bytes_s'] * 1e3) \
                if peaks['hbm_bytes_s'] > 0 else None
            overlap_pct = None
            comm_src = 'modeled'
        comm = {'bytes': round(comm_bytes, 1),
                'time_ms': round(comm_ms, 4)
                if comm_ms is not None else None,
                'overlap_pct': overlap_pct,
                'pct_of_step': round(100.0 * comm_ms / step_time_ms, 1)
                if comm_ms and step_time_ms else None,
                'ops': {k: round(v, 1)
                        for k, v in sorted(prog['comm_ops'].items())},
                'source': comm_src}

    return {
        'program': prog['name'],
        'source': source,
        'device': peaks['kind'],
        'peaks': peaks['source'],
        'peak_tflops': round(peaks['flops'] / 1e12, 3)
        if peaks['flops'] else None,
        'peak_hbm_gbs': round(peaks['hbm_bytes_s'] / 1e9, 3)
        if peaks['hbm_bytes_s'] else None,
        'step_time_ms': round(step_time_ms, 4)
        if step_time_ms is not None else None,
        'trace_steps': trace_steps,
        'layers': out_rows,
        'worst_action': suggest_action(out_rows[0]['class'])
        if out_rows else None,
        'comm': comm,
    }


def comm_bytes_by_op(name_prefix=None):
    """{collective opcode: per-step bytes} summed over every ingested
    program (optionally filtered to names starting with
    ``name_prefix``), or {} when roofline is off / nothing matched.
    The per-opcode view of the communication accounting: the sharded
    weight update's reduce-scatter + all-gather traffic reads straight
    off it (bench.py's ``update_comm_bytes``)."""
    if not enabled():
        return {}
    with _lock:
        progs = [p for n, p in _programs.items()
                 if name_prefix is None or str(n).startswith(name_prefix)]
    out = {}
    for p in progs:
        for op, b in (p.get('comm_ops') or {}).items():
            out[op] = out.get(op, 0.0) + float(b)
    return out


def comm_share():
    """``(pct, source)`` — the collective share of the step (%) with
    its provenance attached: ``'measured'`` when the number comes from
    a joined device trace, ``'modeled'`` when it is the HBM-ceiling
    lower bound, ``(None, None)`` when there is nothing to report.
    The provenance travels with the number everywhere it is consumed
    (cluster records, /metrics, the goodput comm bucket) so a model is
    never laundered into a measurement. Uses the last published
    analysis when one carries comm numbers; otherwise a live sync round
    computes the MODELED share directly from the program's collective
    bytes and the HBM ceiling — the same arithmetic as analyze()'s
    modeled comm path, without rebuilding the per-layer analysis every
    sync round (the common no-collective program exits on the bytes
    check)."""
    with _lock:
        last = _last
    if last is not None and last.get('comm'):
        comm = last['comm']
        return (comm.get('pct_of_step'),
                comm.get('source') or last.get('source') or 'modeled')
    if not enabled():
        return None, None
    prog = _pick_step_program()
    if prog is None or prog['comm_bytes'] <= 0:
        return None, None
    from . import xla
    peaks = xla.device_peaks()
    if peaks['hbm_bytes_s'] <= 0:
        return None, None
    step_ms = _registry_step_ms(_tele().registry)
    if not step_ms:
        return None, None
    comm_ms = prog['comm_bytes'] / peaks['hbm_bytes_s'] * 1e3
    return round(100.0 * comm_ms / step_ms, 1), 'modeled'


def comm_pct_of_step():
    """The collective share of the step (%), or None — the provenance-
    free convenience over :func:`comm_share` (callers feeding records
    or /metrics should use comm_share and carry the source along)."""
    return comm_share()[0]


def summarize(step_time_ms=None):
    """Run :func:`analyze`, publish ``roofline.*`` gauges + the
    ``roofline`` JSONL record, and return the analysis dict (None when
    off/empty). Called from telemetry.write_summary.

    A measured ``step_time_ms`` (bench feeds its wall-clock mean) is
    remembered: a later summarize() with none — the atexit
    write_summary after a bench run — reuses it instead of falling
    back to the registry-derived time, so the run's roofline records
    never disagree about the step-time denominator."""
    global _last, _explicit_step_ms
    if step_time_ms is not None:
        _explicit_step_ms = step_time_ms
    elif _explicit_step_ms is not None:
        step_time_ms = _explicit_step_ms
    d = analyze(step_time_ms=step_time_ms)
    if d is None:
        return None
    st = _tele()
    _publish_gauges(d, st.registry)
    if st.sink is not None:
        rec = {'type': 'roofline'}
        rec.update(d)
        st.sink.emit(rec)
    with _lock:
        _last = d
    return d


def _publish_gauges(d, reg):
    """One analysis dict -> the roofline.* gauge family (shared by
    :func:`summarize` and the cluster-cadence :func:`republish`)."""
    reg.gauge('roofline.layers').set(len(d['layers']))
    if d['layers']:
        worst = d['layers'][0]
        reg.gauge('roofline.worst_layer').set(worst['layer'])
        reg.gauge('roofline.worst_class').set(worst['class'])
        # unconditionally, so an 'unknown'-class round ('' action)
        # never leaves a previous round's lever string stale next to
        # the updated worst_layer/worst_class pair
        reg.gauge('roofline.worst_action').set(
            d.get('worst_action') or '')
        if worst['roof_pct'] is not None:
            reg.gauge('roofline.worst_roof_pct').set(worst['roof_pct'])
        if worst['headroom_ms'] is not None:
            reg.gauge('roofline.worst_headroom_ms').set(
                worst['headroom_ms'])
    comm = d.get('comm')
    if comm:
        reg.gauge('roofline.comm_bytes').set(comm['bytes'])
        if comm['time_ms'] is not None:
            reg.gauge('roofline.comm_time_ms').set(comm['time_ms'])
        if comm['overlap_pct'] is not None:
            reg.gauge('roofline.comm_overlap_pct').set(
                comm['overlap_pct'])
        if comm['pct_of_step'] is not None:
            reg.gauge('roofline.comm_pct_of_step').set(
                comm['pct_of_step'])


def republish():
    """Cluster-sync-cadence hook (telemetry/cluster.py): refresh the
    ``roofline.*`` gauges from a read-only MODELED analysis so a
    mid-run ``/metrics`` scrape sees live roofline state, not just the
    values frozen at the last summarize()/write_summary(). No JSONL
    record is emitted and no profiler capture is loaded from disk — a
    sync round must stay cheap. Returns the analysis dict, or None
    while the flag is off / nothing is ingested yet."""
    global _last
    if not enabled():
        return None
    d = analyze(step_time_ms=_explicit_step_ms, events=[],
                warn_unknown=False)
    if d is None:
        return None
    _publish_gauges(d, _tele().registry)
    with _lock:
        _last = d
    return d


def snapshot_roofline():
    """The last published analysis dict (the /summary payload's and
    read-only summary()'s input), or None."""
    with _lock:
        return _last


def _reset_for_tests():
    global _decided, _last, _explicit_step_ms
    with _lock:
        _programs.clear()
        _last = None
    _decided = None
    _explicit_step_ms = None
    from . import xla
    xla._reset_peaks_warned_for_tests()
