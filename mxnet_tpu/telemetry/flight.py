"""Incident flight recorder: a bounded in-memory black box.

The JSONL sink records forward-only — diagnosing an incident after the
fact means either full telemetry export was already running (and a
week-long log to grep) or the evidence is gone. This module is the
bounded alternative: a fixed-size in-memory ring
(``MXTPU_FLIGHT_RECORDER`` slots, default 2048, on whenever telemetry
is on) retains the most recent telemetry records — spans, request
traces, health/anomaly events, cluster rounds — at negligible cost
(one deque append per record, no I/O, no thread).

Every incident path dumps the ring to ``flight-<reason>.jsonl`` next
to the telemetry log the moment the incident is on record:

- ``flight-hang.jsonl``       — watchdog stall (telemetry/watchdog.py);
- ``flight-nonfinite.jsonl``  — non-finite incident (telemetry/health.py);
- ``flight-oom.jsonl``        — RESOURCE_EXHAUSTED report
  (telemetry/programs.py);
- ``flight-slo-burn.jsonl``   — SLO error-budget burn (telemetry/slo.py);
- ``flight-restart.jsonl``    — a supervised restart
  (health.note_restart — the restart drivers' observation of an
  unclean exit).

The dump carries a ``flight`` header record (reason, ring size, wall
time) followed by the retained records oldest-first — the seconds
BEFORE the incident, which the forward-only log only has if export
was verbose enough. ``tools/trace_report.py`` renders a dump offline.

Feeding: :func:`note` is called from the JSONL sink's emit chokepoint,
so everything that would reach the log (including records a size-capped
sink drops) enters the ring too. Dumps are bounded per reason
(:data:`_MAX_DUMPS_PER_REASON`, newest wins) so an incident loop
cannot fill a disk.

Gating: ``MXTPU_TELEMETRY=1`` and ``MXTPU_FLIGHT_RECORDER > 0``
(the default). Off = no ring is ever allocated and every entry point
is one cached-bool check — the zero-overhead contract; nothing here
touches a compiled program either way.
"""
import collections
import json
import logging
import os
import threading
import time

__all__ = ['enabled', 'note', 'dump', 'snapshot_flight']

_MAX_DUMPS_PER_REASON = 5


class _FState:
    __slots__ = ('decided', 'active', 'size', 'ring', 'dumps', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.size = 0
        self.ring = None
        self.dumps = {}       # reason -> dump count
        self.lock = threading.Lock()


_state = _FState()
_decide_lock = threading.Lock()


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    # decide telemetry BEFORE taking our lock: the telemetry decide
    # emits the 'start' record through the sink, whose emit chokepoint
    # re-enters flight.note()/_decide() on this same thread — a
    # non-reentrant lock held across it would deadlock the process at
    # first telemetry use
    tele_on = _tele().active
    with _decide_lock:
        if _state.decided:
            return _state.active
        size = 0
        if tele_on:
            from ..config import flags
            try:
                flags.reload('MXTPU_FLIGHT_RECORDER')
                size = int(flags.get('MXTPU_FLIGHT_RECORDER'))
            except Exception:  # noqa: BLE001 — stripped builds w/o flag
                size = 0
        _state.size = size
        if size > 0:
            _state.ring = collections.deque(maxlen=size)
        _state.active = size > 0
        _state.decided = True
    return _state.active


def enabled():
    """Whether the recorder is on: MXTPU_TELEMETRY=1 and
    MXTPU_FLIGHT_RECORDER > 0, decided once. One attribute check after
    the first call — the emit chokepoint's gate."""
    if _state.decided:
        return _state.active
    return _decide()


def note(record):
    """Retain one telemetry record (a plain dict, already t/host
    stamped by the sink). Off = one cached-bool check; on = one
    (uncontended) lock + deque append — the lock is what lets a
    concurrent dump() snapshot the ring without a mutated-during-
    iteration RuntimeError voiding the incident's one recording."""
    if not enabled():
        return
    with _state.lock:
        _state.ring.append(record)


def snapshot_flight():
    """The ring's current contents, oldest first (tests/tools)."""
    if not enabled():
        return []
    with _state.lock:
        return list(_state.ring)


def _dump_path(reason):
    """flight-<reason>.jsonl next to the telemetry log (its directory
    is the run's one place artifacts land)."""
    from ..config import flags
    try:
        base = os.path.expanduser(flags.get('MXTPU_TELEMETRY_PATH')
                                  or 'telemetry.jsonl')
    except Exception:  # noqa: BLE001
        base = 'telemetry.jsonl'
    return os.path.join(os.path.dirname(base) or '.',
                        'flight-%s.jsonl' % reason)


def dump(reason, extra=None):
    """Write the ring to ``flight-<reason>.jsonl`` (overwriting a
    previous dump for the same reason — the newest incident's context
    wins; at most :data:`_MAX_DUMPS_PER_REASON` writes per reason).
    ``extra`` merges into the header record. Best-effort by contract:
    an incident path must never die of its own forensics. Returns the
    path, or None when off/bounded/failed."""
    if not enabled():
        return None
    with _state.lock:
        n = _state.dumps.get(reason, 0)
        if n >= _MAX_DUMPS_PER_REASON:
            return None
        _state.dumps[reason] = n + 1
        records = list(_state.ring)
    path = _dump_path(reason)
    head = {'type': 'flight', 'reason': reason, 't': time.time(),
            'records': len(records), 'ring_size': _state.size}
    if extra:
        head.update(extra)
    try:
        with open(path, 'w') as f:
            f.write(json.dumps(head) + '\n')
            for rec in records:
                try:
                    f.write(json.dumps(rec) + '\n')
                except (TypeError, ValueError):
                    continue   # a non-JSON-safe record must not void
                               # the rest of the recording
    except OSError as e:
        logging.warning('flight recorder: cannot write %s (%s)', path, e)
        return None
    logging.warning('flight recorder: dumped %d record(s) to %s '
                    '(reason: %s)', len(records), path, reason)
    return path


def _reset_for_tests():
    global _state
    _state = _FState()
