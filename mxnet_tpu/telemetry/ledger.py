"""Run ledger: a manifest + bounded scalar timeseries per run, with a
dependency-free native TensorBoard (tfevents) writer.

Two runs happened — which one is better, and why? Answering that needs
three things no other plane records:

- a **manifest** (`manifest` JSONL record, once per run): the resolved
  MXTPU_* flag values, jax version, device kind/platform, mesh
  descriptor and git sha — so "what was different about run B" is a
  dict diff, not archaeology;
- a **scalar timeseries** (`scalars` JSONL records, every
  ``MXTPU_SCALARS_EVERY`` trained steps): loss, learning rate,
  throughput, global + worst-layer gradient statistics
  (telemetry/dynamics.py), MFU and eval metrics — the bounded
  per-step ledger ``tools/run_compare.py`` diffs across runs;
- a **tfevents mirror** (``MXTPU_TFEVENTS_DIR``): every scalar also
  lands as a native TensorBoard event through
  :class:`TfEventsWriter` — a hand-rolled TFRecord/Event protobuf
  encoder (golden-bytes tested, CRC32C included) so
  ``tensorboard --logdir`` works on any run without tensorboardX or
  torch installed. :func:`read_tfevents` is the matching decoder
  (tests, and anything that wants the series back without TensorBoard).

Gating: ``MXTPU_TELEMETRY=1``; scalar records additionally need
``MXTPU_SCALARS_EVERY > 0`` (default 25). Off = the usual cached-bool
no-op.
"""
import json
import logging
import os
import struct
import threading
import time
import collections

__all__ = ['enabled', 'ensure_manifest', 'begin_run', 'note_train_step',
           'note_eval',
           'snapshot_ledger', 'final_loss', 'time_to_loss',
           'progress_target', 'TfEventsWriter', 'read_tfevents',
           'crc32c', 'masked_crc', 'MANIFEST_KEYS']

# the manifest fields rolled up by snapshot_ledger, the crashed-run
# reconstruction (tools/telemetry_report.py) and the run-compare
# config diff (tools/run_compare.py) — one list so the three views
# can't drift when a field is added
MANIFEST_KEYS = ('jax_version', 'platform', 'device_kind',
                 'device_count', 'mesh', 'git_sha', 'symbol')

_RECENT_KEEP = 512      # in-memory (step, t, loss) ring for snapshots
_SNAPSHOT_RECENT = 32   # points exposed to /summary & the watch sparkline


# ---------------------------------------------------------------------------
# tfevents: TFRecord framing + Event proto encoding, no dependencies
# ---------------------------------------------------------------------------

def _crc32c_table():
    poly = 0x82F63B78          # Castagnoli, reflected
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _crc32c_table()


def crc32c(data):
    """CRC-32C (Castagnoli) of ``data`` — the checksum TFRecord framing
    uses; zlib.crc32 is the WRONG polynomial, hence the table here."""
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc(data):
    """TFRecord's masked CRC: rotate right by 15 and add the magic
    constant (tensorflow/core/lib/hash/crc32c.h)."""
    crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def _varint(n):
    out = bytearray()
    n &= 0xFFFFFFFFFFFFFFFF     # proto int64 wire form of a negative step
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field, wire):
    return _varint((field << 3) | wire)


def _pb_double(field, v):
    return _key(field, 1) + struct.pack('<d', v)


def _pb_float(field, v):
    return _key(field, 5) + struct.pack('<f', v)


def _pb_varint(field, v):
    return _key(field, 0) + _varint(int(v))


def _pb_bytes(field, data):
    if isinstance(data, str):
        data = data.encode('utf-8')
    return _key(field, 2) + _varint(len(data)) + data


def encode_event(wall_time, step=None, file_version=None, scalars=None):
    """One tensorflow.Event message as bytes. ``scalars`` is a
    {tag: float} dict encoded as Summary/Value simple_values — exactly
    the subset ``tensorboard --logdir`` needs for scalar charts."""
    body = _pb_double(1, float(wall_time))
    if step is not None:
        body += _pb_varint(2, int(step))
    if file_version is not None:
        body += _pb_bytes(3, file_version)
    if scalars:
        summary = b''
        for tag in sorted(scalars):
            value = _pb_bytes(1, tag) + _pb_float(2, float(scalars[tag]))
            summary += _pb_bytes(1, value)
        body += _pb_bytes(5, summary)
    return body


def encode_record(payload):
    """TFRecord framing: u64 length, masked CRC of the length bytes,
    payload, masked CRC of the payload."""
    header = struct.pack('<Q', len(payload))
    return (header + struct.pack('<I', masked_crc(header))
            + payload + struct.pack('<I', masked_crc(payload)))


class TfEventsWriter:
    """Append-only tfevents file writer (``events.out.tfevents.*`` in
    ``logdir``), dependency-free. The first record is the standard
    ``brain.Event:2`` version header; :meth:`add_scalar` appends one
    Event per call. Also usable standalone —
    ``contrib/tensorboard.py``'s LogMetricsCallback falls back to it
    when tensorboardX/torch are absent."""

    _seq = 0
    _seq_lock = threading.Lock()

    def __init__(self, logdir, filename_suffix=''):
        os.makedirs(logdir, exist_ok=True)
        import socket
        # pid + per-process sequence uniquify the name (the
        # tensorboardX convention): two writers born in the same
        # second — the ledger's and the contrib callback's, or two
        # gang workers sharing a logdir — must never append-interleave
        # into one file
        with TfEventsWriter._seq_lock:
            seq = TfEventsWriter._seq
            TfEventsWriter._seq += 1
        name = 'events.out.tfevents.%010d.%s.%d.%d%s' % (
            int(time.time()), socket.gethostname(), os.getpid(), seq,
            filename_suffix)
        self.path = os.path.join(logdir, name)
        self._lock = threading.Lock()
        self._f = open(self.path, 'ab')
        self._write(encode_event(time.time(),
                                 file_version='brain.Event:2'))

    def _write(self, payload):
        with self._lock:
            if self._f is None:
                return
            self._f.write(encode_record(payload))
            self._f.flush()

    def add_scalar(self, tag, value, step):
        """One scalar point (the tensorboardX SummaryWriter method the
        contrib callback calls)."""
        self._write(encode_event(time.time(), step=step,
                                 scalars={str(tag): float(value)}))

    def add_scalars(self, scalars, step, wall_time=None):
        """Several tags at one step in ONE event record."""
        self._write(encode_event(
            wall_time if wall_time is not None else time.time(),
            step=step, scalars=scalars))

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


# -- reader (tests + offline tooling) ---------------------------------------

def _read_varint(buf, i):
    shift, out = 0, 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _decode_summary(buf):
    scalars = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:           # repeated Value
            n, i = _read_varint(buf, i)
            val = buf[i:i + n]
            i += n
            tag, simple = None, None
            j = 0
            while j < len(val):
                vkey, j = _read_varint(val, j)
                vfield, vwire = vkey >> 3, vkey & 7
                if vfield == 1 and vwire == 2:
                    vn, j = _read_varint(val, j)
                    tag = val[j:j + vn].decode('utf-8')
                    j += vn
                elif vfield == 2 and vwire == 5:
                    simple = struct.unpack('<f', val[j:j + 4])[0]
                    j += 4
                else:
                    j = _skip_field(val, j, vwire)
            if tag is not None and simple is not None:
                scalars[tag] = simple
        else:
            i = _skip_field(buf, i, wire)
    return scalars


def _skip_field(buf, i, wire):
    if wire == 0:
        _, i = _read_varint(buf, i)
    elif wire == 1:
        i += 8
    elif wire == 2:
        n, i = _read_varint(buf, i)
        i += n
    elif wire == 5:
        i += 4
    else:
        raise ValueError('unsupported wire type %d' % wire)
    return i


def decode_event(payload):
    """One Event payload -> {'wall_time', 'step', 'file_version',
    'scalars'} (absent fields omitted, scalars {} when none)."""
    out = {'scalars': {}}
    i = 0
    while i < len(payload):
        key, i = _read_varint(payload, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 1:
            out['wall_time'] = struct.unpack('<d', payload[i:i + 8])[0]
            i += 8
        elif field == 2 and wire == 0:
            out['step'], i = _read_varint(payload, i)
        elif field == 3 and wire == 2:
            n, i = _read_varint(payload, i)
            out['file_version'] = payload[i:i + n].decode('utf-8')
            i += n
        elif field == 5 and wire == 2:
            n, i = _read_varint(payload, i)
            out['scalars'] = _decode_summary(payload[i:i + n])
            i += n
        else:
            i = _skip_field(payload, i, wire)
    return out


def read_tfevents(path, verify_crc=True):
    """Decode a tfevents file into a list of event dicts (the
    :func:`decode_event` shape). With ``verify_crc`` a corrupt record
    raises ValueError — the round-trip test's teeth."""
    events = []
    with open(path, 'rb') as f:
        data = f.read()
    i = 0
    while i + 12 <= len(data):
        header = data[i:i + 8]
        (length,) = struct.unpack('<Q', header)
        (hcrc,) = struct.unpack('<I', data[i + 8:i + 12])
        if verify_crc and hcrc != masked_crc(header):
            raise ValueError('tfevents: bad length CRC at offset %d' % i)
        start = i + 12
        if start + length + 4 > len(data):
            break   # truncated tail (a live writer mid-record —
            #         possibly inside the trailing CRC itself)
        payload = data[start:start + length]
        (pcrc,) = struct.unpack('<I',
                                data[start + length:start + length + 4])
        if verify_crc and pcrc != masked_crc(payload):
            raise ValueError('tfevents: bad payload CRC at offset %d'
                             % start)
        events.append(decode_event(payload))
        i = start + length + 4
    return events


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

class _LState:
    __slots__ = ('decided', 'active', 'every', 'step', 'records',
                 'manifest', 'manifest_emitted', 'run_seq', 'writer',
                 'writer_failed', 'last_emit_t', 'last_emit_step', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.every = 0
        self.step = 0
        self.records = collections.deque(maxlen=_RECENT_KEEP)
        self.manifest = None
        self.manifest_emitted = False
        self.run_seq = 0
        self.writer = None
        self.writer_failed = False
        self.last_emit_t = None
        self.last_emit_step = None
        self.lock = threading.Lock()


_state = _LState()
_decide_lock = threading.Lock()


def _tele():
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        tele_on = _tele().active
        ev = 0
        if tele_on:
            from ..config import flags
            try:
                flags.reload('MXTPU_SCALARS_EVERY')
                ev = int(flags.get('MXTPU_SCALARS_EVERY'))
            except Exception:  # noqa: BLE001 — stripped builds w/o the flag
                ev = 0
        _state.every = ev
        _state.active = tele_on and ev > 0
        _state.decided = True
    return _state.active


def enabled():
    """Whether the scalar ledger is on: MXTPU_TELEMETRY=1 and
    MXTPU_SCALARS_EVERY > 0, decided once."""
    if _state.decided:
        return _state.active
    return _decide()


def _emit(rec):
    st = _tele()
    if st.active and st.sink is not None:
        st.sink.emit(rec)


def _tfevents_dir():
    from ..config import flags
    try:
        flags.reload('MXTPU_TFEVENTS_DIR')
        return flags.get('MXTPU_TFEVENTS_DIR') or ''
    except Exception:  # noqa: BLE001
        return ''


def _writer():
    """The lazy tfevents writer (None when MXTPU_TFEVENTS_DIR unset or
    the open failed — warn once, never crash the fit loop)."""
    if _state.writer is not None or _state.writer_failed:
        return _state.writer
    path = _tfevents_dir()
    if not path:
        _state.writer_failed = True
        return None
    try:
        _state.writer = TfEventsWriter(os.path.expanduser(path))
    except OSError as e:
        _state.writer_failed = True
        logging.warning('ledger: cannot open tfevents dir %s (%s) — '
                        'scalars stay JSONL-only', path, e)
    return _state.writer


# -- manifest ----------------------------------------------------------------

def _git_sha():
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(['git', 'rev-parse', '--short', 'HEAD'],
                             cwd=repo, capture_output=True, text=True,
                             timeout=5)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:  # noqa: BLE001 — no git / not a checkout
        return None


def _resolved_flags():
    """{name: resolved value} for every declared MXTPU_* flag — the
    run's effective configuration (unparseable values render as their
    raw string so the manifest never raises)."""
    from ..config import flags
    out = {}
    for f in flags:
        try:
            out[f.name] = flags.get(f.name)
        except Exception:  # noqa: BLE001 — a bad env value
            out[f.name] = os.environ.get(f.name)
    return out


def build_manifest(module=None):
    """The run-manifest dict (pure; does not emit)."""
    man = {'pid': os.getpid(), 'argv': list(__import__('sys').argv)}
    try:
        import jax
        man['jax_version'] = jax.__version__
        devs = jax.devices()
        if devs:
            man['platform'] = devs[0].platform
            man['device_kind'] = getattr(devs[0], 'device_kind', None)
            man['device_count'] = len(devs)
    except Exception:  # noqa: BLE001 — backend init can fail; manifest not
        pass
    try:
        from ..parallel import multihost
        man['mesh'] = multihost.mesh_descriptor()
    except Exception:  # noqa: BLE001
        pass
    if module is not None:
        mesh = getattr(getattr(module, '_exec_group', None), 'mesh', None)
        if mesh is not None:
            try:
                man['mesh'] = dict(mesh.shape)
            except Exception:  # noqa: BLE001
                pass
        sym = getattr(module, '_symbol', None)
        if sym is not None:
            man['symbol'] = getattr(sym, 'name', None)
    sha = _git_sha()
    if sha:
        man['git_sha'] = sha
    man['flags'] = _resolved_flags()
    man['env_set'] = sorted(k for k in os.environ
                            if k.startswith('MXTPU_'))
    return man


def ensure_manifest(module=None):
    """Build + emit the `manifest` JSONL record once per process
    (whenever telemetry is on — the manifest is worth one record even
    with the scalar cadence off). Fit boundaries call
    :func:`begin_run` instead, which re-emits per run."""
    st = _tele()
    if not st.active:
        return None
    with _state.lock:
        if _state.manifest_emitted:
            return _state.manifest
        _state.manifest_emitted = True
        _state.run_seq += 1
        seq = _state.run_seq
    return _emit_manifest(module, seq)


def begin_run(module=None):
    """Build + emit a fresh `manifest` record for a new fit() run —
    every in-process fit (and every resilient_fit attempt) gets its
    own, tagged with a monotonically increasing ``run_seq`` so
    tools/run_compare.py and the offline report key on the LATEST
    configuration instead of the process's first. Flags may legally
    change between fits (tests and sweeps flip MXTPU_* between calls),
    so the re-emit is what keeps the ledger honest."""
    st = _tele()
    if not st.active:
        return None
    with _state.lock:
        _state.manifest_emitted = True
        _state.run_seq += 1
        seq = _state.run_seq
    return _emit_manifest(module, seq)


def _emit_manifest(module, seq):
    man = build_manifest(module)
    man['run_seq'] = int(seq)
    _state.manifest = man
    rec = {'type': 'manifest'}
    rec.update(man)
    _emit(rec)
    return man


# -- scalars -----------------------------------------------------------------

def _gauge(name):
    reg = _tele().registry
    g = reg.get(name)
    return g.value if g is not None else None


def _build_record(step, now, loss, lr, extra=None):
    rec = {'type': 'scalars', 'step': int(step)}
    if loss is not None:
        rec['loss'] = round(float(loss), 6)
    if lr is not None:
        rec['lr'] = round(float(lr), 8)
    if _state.last_emit_t is not None and now > _state.last_emit_t \
            and _state.last_emit_step is not None:
        rec['steps_per_sec'] = round(
            (step - _state.last_emit_step) / (now - _state.last_emit_t), 3)
    for field, gauge in (('grad_norm', 'health.grad_norm'),
                         ('mfu', 'xla.mfu'),
                         ('samples_per_sec',
                          'speedometer.samples_per_sec')):
        v = _gauge(gauge)
        if v is not None:
            rec[field] = v
    from . import dynamics as _dyn
    if _dyn.enabled():
        dsnap = _dyn.snapshot_dynamics()
        if dsnap:
            if dsnap.get('worst_layer') is not None:
                rec['worst_layer'] = dsnap['worst_layer']
                rec['worst_update_ratio'] = dsnap['worst_update_ratio']
            if dsnap.get('dead_frac_max') is not None:
                rec['dead_frac_max'] = dsnap['dead_frac_max']
    if extra:
        rec.update(extra)
    return rec


def _mirror_tfevents(scalars, step, now):
    """Best-effort tfevents mirror of one scalar dict — shared by the
    train-step and eval paths so the two record streams can't drift."""
    w = _writer()
    if w is None or not scalars:
        return
    try:
        w.add_scalars(scalars, step, wall_time=now)
    except Exception as e:  # noqa: BLE001 — never kill the loop
        logging.debug('ledger: tfevents write failed: %s', e)


def _emit_scalars(rec, now):
    # stamp the CALLER's timestamp: bench's feed() banks post-barrier
    # with amortized per-step times, and run_compare's step_time /
    # time_to_loss read the record's 't' — the sink's emit-time default
    # would bunch every fed point at one instant
    rec['t'] = now
    _emit(rec)
    _mirror_tfevents({k: float(v) for k, v in rec.items()
                      if k not in ('type', 'step', 't', 'host',
                                   'worst_layer', 'event', 'epoch')
                      and isinstance(v, (int, float))},
                     rec['step'], now)
    with _state.lock:
        _state.records.append((rec['step'], now, rec.get('loss')))
        _state.last_emit_t = now
        _state.last_emit_step = rec['step']


def note_train_step(loss=None, lr=None, metric=None, t=None):
    """Count one trained step; at every MXTPU_SCALARS_EVERY-th step
    emit a `scalars` record (and its tfevents mirror). ``loss`` is the
    step's loss when the loop knows it (the fused stats path's
    in-graph CrossEntropy); ``metric`` is the running EvalMetric —
    its values land as ``metric_<name>`` fields, and a cross-entropy
    value doubles as the loss when none was given. ``lr`` may be a
    callable (evaluated only on due steps — the per-batch loop's
    scheduler sample must not cost the 24 of 25 non-due steps).
    ``t`` is an explicit wall stamp for callers that process steps in
    a burst after one fetch (the fused window amortizes its steps over
    the window's wall time — emit-time clocks would bunch them)."""
    if not enabled():
        return
    with _state.lock:
        _state.step += 1
        step = _state.step
        due = (step % _state.every) == 0
    if not due:
        return
    if callable(lr):
        lr = lr()
    extra = {}
    if metric is not None:
        try:
            for name, value in metric.get_name_value():
                if value == value:  # skip nan (empty metric)
                    extra['metric_%s' % name] = round(float(value), 6)
                    if loss is None and 'entropy' in name:
                        loss = value
        except Exception:  # noqa: BLE001 — custom metric surprises
            pass
    now = time.time() if t is None else float(t)
    _emit_scalars(_build_record(step, now, loss, lr, extra), now)


def note_eval(name_values, epoch=None):
    """Bank an eval pass's metric values as a `scalars` record
    (``event=eval``, fields ``eval_<name>``) + tfevents ``eval/<name>``
    tags — run_compare's eval-metric column."""
    if not enabled():
        return
    extra = {'event': 'eval'}
    if epoch is not None:
        extra['epoch'] = int(epoch)
    for name, value in name_values:
        if value == value:
            extra['eval_%s' % name] = round(float(value), 6)
    now = time.time()
    with _state.lock:
        step = _state.step
    rec = {'type': 'scalars', 'step': int(step)}
    rec.update(extra)
    _emit(rec)
    _mirror_tfevents({'eval/%s' % k[len('eval_'):]: float(v)
                      for k, v in extra.items()
                      if k.startswith('eval_')}, step, now)


def feed(step, loss, t=None):
    """Direct feed for drivers that own their loop (bench.py): bank one
    (step, loss) point with an explicit timestamp — emitted as a
    `scalars` record and entered into the in-memory series
    final_loss/time_to_loss read."""
    if not enabled():
        return
    now = time.time() if t is None else float(t)
    with _state.lock:
        _state.step = max(_state.step, int(step))
    _emit_scalars(_build_record(int(step), now, loss, None), now)


# -- derived metrics (bench + run_compare) -----------------------------------

def _series():
    with _state.lock:
        return list(_state.records)


def final_loss():
    """The last banked loss, or None."""
    for _, _, loss in reversed(_series()):
        if loss is not None:
            return loss
    return None


def progress_target(frac=0.9):
    """The loss value ``frac`` of the way from the first banked loss to
    the best one — a self-scaling time-to-loss target comparable across
    re-runs of the same job."""
    losses = [l for _, _, l in _series() if l is not None]
    if len(losses) < 2:
        return None
    first, best = losses[0], min(losses)
    if best >= first:
        return None     # never improved: no meaningful target
    return first - frac * (first - best)


def time_to_loss(target):
    """Seconds from the first banked point to the first point at or
    below ``target`` loss — None when the run never got there."""
    if target is None:
        return None
    pts = _series()
    t0 = pts[0][1] if pts else None
    for _, t, loss in pts:
        if loss is not None and loss <= target:
            return round(t - t0, 3)
    return None


def snapshot_ledger():
    """Point-in-time ledger dict for /summary, the summary record and
    the watch sparkline: the manifest (minus the bulky flag dump), the
    last scalar point and a short recent-loss series. None while
    telemetry is off and nothing was recorded."""
    st = _tele()
    if not st.active:
        return None
    with _state.lock:
        man = _state.manifest
        recent = list(_state.records)[-_SNAPSHOT_RECENT:]
        steps = _state.step
        wpath = _state.writer.path if _state.writer is not None else None
    if man is None and not recent and not steps:
        return None
    out = {'steps': int(steps), 'every': int(_state.every)}
    if man is not None:
        out['manifest'] = {k: man.get(k) for k in MANIFEST_KEYS
                           if man.get(k) is not None}
        out['manifest']['env_set'] = man.get('env_set')
        # which in-process fit this manifest belongs to (run_seq stays
        # out of MANIFEST_KEYS: it is identity, not configuration, and
        # run_compare's config diff must not flag it)
        if man.get('run_seq') is not None:
            out['manifest']['run_seq'] = int(man['run_seq'])
    if recent:
        out['recent'] = [{'step': s, 'loss': l} for s, _, l in recent]
        out['last'] = {'step': recent[-1][0], 'loss': recent[-1][2]}
        fl = final_loss()
        if fl is not None:
            out['final_loss'] = fl
    if wpath:
        out['tfevents'] = wpath
    return out


def _reset_for_tests():
    global _state
    if _state.writer is not None:
        try:
            _state.writer.close()
        except Exception:  # noqa: BLE001
            pass
    _state = _LState()
