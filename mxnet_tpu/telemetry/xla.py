"""XLA-side telemetry: compile events, device memory, retraces, MFU.

Compile observability comes from jax.monitoring: XLA emits
``/jax/core/compile/backend_compile_duration`` once per backend
compile, which feeds the ``xla.compiles`` counter, the accumulated
``xla.compile_secs``, and a per-compile JSONL record. With the
persistent compilation cache on (MXTPU_COMPILE_CACHE), the cache's
``cache_hits`` / ``compile_time_saved_sec`` events feed
``xla.cache_hits`` and ``xla.cache_saved_secs`` — how many compiles a
warm start was served from disk, and the seconds it refunded. The
listeners are registered once per process and are no-ops while
telemetry is off, so they can stay installed across test resets.

Retrace detection is framework-side: the sites that BUILD compiled
programs (Executor construction, the fused-fit window builder) call
:func:`note_retrace` with a value key identifying the graph; the same
key arriving more than ``MXTPU_TELEMETRY_RETRACE_WARN`` times is the
classic retrace storm (a shape/attr leaking into the program key every
batch — the 49.8 img/s pathology of docs/perf.md) and logs one loud
warning plus a ``retrace_storm`` JSONL record.

Memory gauges read ``device.memory_stats()`` (live/peak bytes on TPU;
None on CPU — sampled best-effort, with ONE process-wide warning the
first time no device reports stats so empty gauges are explained). The
MFU estimate needs the step FLOPs: the program registrar
(:mod:`.programs`) feeds :func:`note_step_flops` automatically from
whichever train-step program the fit loop compiles (bench.py feeds the
same way through ``note_program``), and the summary divides observed
step rate * FLOPs by the device's peak.
"""
import logging
import threading
import time

__all__ = ['install', 'note_retrace', 'note_step_flops', 'sample_memory',
           'device_peak_flops', 'device_peaks', 'mfu_estimate']

_COMPILE_EVENT_SUFFIX = 'backend_compile_duration'
# persistent-compilation-cache events (MXTPU_COMPILE_CACHE): a hit
# means a compile request was served from disk instead of XLA
_CACHE_HIT_EVENT = '/jax/compilation_cache/cache_hits'
_CACHE_SAVED_SUFFIX = 'compile_time_saved_sec'

# Per-chip hardware ceilings, by device_kind substring (order matters:
# 'v5p' must match before 'v5'). Columns: peak dense bf16 FLOP/s and
# peak HBM bytes/s — the two roofline denominators (telemetry/roofline
# classifies each layer by which ceiling bounds it). The MFU estimate
# uses only the FLOP/s column.
_PEAK_TABLE = [
    ('v6', 918e12, 1640e9), ('v5p', 459e12, 2765e9), ('v5', 197e12, 819e9),
    ('v4', 275e12, 1228e9), ('v3', 123e12, 900e9), ('v2', 45e12, 700e9),
]
# CPU fallback: NOMINAL host ceilings (order-of-magnitude: one modern
# core's FMA throughput and stream bandwidth) so a CPU run still gets a
# best-effort roofline classification. Marked nominal — the MFU
# estimate ignores nominal peaks (a "29% MFU" against a guessed CPU
# peak would be noise presented as signal).
_NOMINAL_CPU_PEAKS = (1e11, 5e10)

_installed = False
_install_lock = threading.Lock()


def _state():
    from . import enabled
    enabled()   # decide from the flag if nothing else has yet
    from . import _state as st
    return st


def install():
    """Register the jax.monitoring compile listener (once per process)."""
    global _installed
    with _install_lock:
        if _installed:
            return
        try:
            import jax.monitoring as _mon
            _mon.register_event_duration_secs_listener(_on_duration)
            _mon.register_event_listener(_on_event)
            _installed = True
        except Exception as e:  # noqa: BLE001 — observability must not kill
            logging.debug('telemetry: jax.monitoring unavailable: %s', e)


def _on_duration(event, duration, **kwargs):
    st = _state()
    if not st.active:
        return
    if event.endswith(_COMPILE_EVENT_SUFFIX):
        st.registry.counter('xla.compiles').inc()
        st.registry.counter('xla.compile_secs').inc(float(duration))
        if st.sink is not None:
            st.sink.emit({'type': 'compile', 't': time.time(),
                          'dur_s': round(float(duration), 4)})
    elif event.endswith(_CACHE_SAVED_SUFFIX):
        # compile seconds the persistent cache refunded this process
        st.registry.counter('xla.cache_saved_secs').inc(float(duration))


def _on_event(event, **kwargs):
    st = _state()
    if not st.active:
        return
    if event == _CACHE_HIT_EVENT:
        st.registry.counter('xla.cache_hits').inc()
        if st.sink is not None:
            st.sink.emit({'type': 'cache_hit', 't': time.time()})


def _retrace_threshold():
    from ..config import flags
    try:
        return flags.get('MXTPU_TELEMETRY_RETRACE_WARN')
    except Exception:  # noqa: BLE001 — undeclared in stripped builds
        return 5


def note_retrace(key):
    """A compiled program for graph ``key`` was (re)built. The first
    build is free; every further build of the SAME key counts as a
    retrace, and crossing the warn threshold logs the storm once."""
    st = _state()
    if not st.active:
        return
    with st.lock:
        n = st.retraces[key] = st.retraces.get(key, 0) + 1
    if n > 1:
        st.registry.counter('xla.retraces').inc()
    thresh = _retrace_threshold()
    if n == thresh + 1:
        logging.warning(
            'telemetry: retrace storm — the same graph was compiled %d '
            'times (key=%s). A shape/dtype/attr is leaking into the '
            'program cache key every batch; throughput is bounded by '
            'compile time until it stops.', n, _short(key))
        if st.sink is not None:
            st.sink.emit({'type': 'retrace_storm', 'key': _short(key),
                          'count': n})


def _short(key, limit=200):
    s = str(key)
    return s if len(s) <= limit else s[:limit] + '...'


def note_step_flops(flops):
    """Record the per-training-step model FLOPs (enables the MFU
    estimate). Fed automatically by telemetry.programs when a
    step-marked program (executor fwd+bwd, fused fit window) compiles;
    bench.py feeds XLA's own cost analysis the same way."""
    st = _state()
    if st.active and flops:
        st.registry.gauge('xla.step_flops').set(float(flops))


_memory_stats_warned = False


def _warn_memory_unavailable(reason):
    """Once per process at WARNING (debug thereafter): a user on an
    unsupported backend must learn WHY the memory gauges stay empty —
    a forever-debug message buries the explanation."""
    global _memory_stats_warned
    if _memory_stats_warned:
        logging.debug('telemetry: memory_stats still unavailable: %s',
                      reason)
        return
    _memory_stats_warned = True
    logging.warning(
        'telemetry: device memory_stats() unavailable (%s) — the '
        'xla.bytes_in_use / xla.peak_bytes_in_use gauges and the OOM '
        'device totals stay empty on this backend', reason)


def sample_memory(device=None):
    """Update live/peak device-byte gauges from ``memory_stats()``.
    Best-effort: CPU backends return None and are skipped (warned once
    per process so empty gauges are explained)."""
    st = _state()
    if not st.active:
        return None
    try:
        if device is None:
            import jax
            devices = jax.local_devices()
        else:
            devices = [device]
        for d in devices:
            stats = d.memory_stats()
            if not stats:
                continue
            live = stats.get('bytes_in_use')
            peak = stats.get('peak_bytes_in_use')
            if live is not None:
                st.registry.gauge('xla.bytes_in_use').set(int(live))
            if peak is not None:
                st.registry.gauge('xla.peak_bytes_in_use').set(int(peak))
            return stats
        _warn_memory_unavailable(
            'no local device reports memory statistics — platform %r'
            % (getattr(devices[0], 'platform', '?') if devices else '?'))
    except Exception as e:  # noqa: BLE001 — observability must not kill
        _warn_memory_unavailable(e)
    return None


_peaks_unknown_warned = False


def _peak_overrides():
    """(flops, hbm_bytes_s) from MXTPU_PEAK_TFLOPS / MXTPU_PEAK_HBM_GBS
    (human units: TFLOP/s, GB/s); 0.0 = no override."""
    from ..config import flags
    try:
        f = float(flags.get('MXTPU_PEAK_TFLOPS')) * 1e12
        b = float(flags.get('MXTPU_PEAK_HBM_GBS')) * 1e9
        return f, b
    except Exception:  # noqa: BLE001 — undeclared in stripped builds
        return 0.0, 0.0


def _warn_peaks_unknown(kind):
    """An unknown device kind must not SILENTLY lose MFU and the
    roofline: warn once per process and publish roofline.peaks_unknown
    so the gap is visible in /metrics and the summary."""
    global _peaks_unknown_warned
    st = _state()
    if st.active:
        st.registry.gauge('roofline.peaks_unknown').set(1)
    if _peaks_unknown_warned:
        logging.debug('telemetry: no peak table entry for device kind %r',
                      kind)
        return
    _peaks_unknown_warned = True
    logging.warning(
        'telemetry: device kind %r has no peak table entry — the MFU '
        'estimate and the roofline achieved-vs-peak placement are '
        'skipped for this run (roofline.peaks_unknown=1). Set '
        'MXTPU_PEAK_TFLOPS / MXTPU_PEAK_HBM_GBS to this chip\'s peak '
        'dense bf16 TFLOP/s and HBM GB/s to restore them.', kind)


def device_peaks(device=None, warn=True):
    """The roofline denominators for ``device`` (default: devices()[0])
    as a dict: ``flops`` (peak dense bf16 FLOP/s), ``hbm_bytes_s``
    (peak HBM bytes/s), ``kind``, and per-component
    ``flops_source``/``hbm_source`` — 'table' (a known chip),
    'override' (MXTPU_PEAK_TFLOPS/MXTPU_PEAK_HBM_GBS), 'nominal' (the
    best-effort CPU guess), or 'unknown' (no entry: zero, warned once,
    ``roofline.peaks_unknown`` published). ``source`` is the combined
    label ('a+b' when the components disagree). ``warn=False``
    suppresses the unknown-kind warn + gauge write — the read-only
    scrape path's contract (a /summary request must not write the
    registry)."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        kind = (getattr(device, 'device_kind', '') or '').lower()
    except Exception:  # noqa: BLE001
        kind = ''
    flops = hbm = 0.0
    flops_src = hbm_src = 'unknown'
    for sub, f, b in _PEAK_TABLE:
        if sub in kind:
            flops, hbm = f, b
            flops_src = hbm_src = 'table'
            break
    if flops_src == 'unknown' and (not kind or 'cpu' in kind):
        flops, hbm = _NOMINAL_CPU_PEAKS
        flops_src = hbm_src = 'nominal'
    # Overrides replace only the component they set — a lone
    # MXTPU_PEAK_HBM_GBS must not promote a nominal/unknown FLOP/s
    # value to trusted-for-MFU status (device_peak_flops keys on the
    # FLOP/s component's source alone).
    ov_f, ov_b = _peak_overrides()
    if ov_f:
        flops, flops_src = ov_f, 'override'
    if ov_b:
        hbm, hbm_src = ov_b, 'override'
    if warn and 'unknown' in (flops_src, hbm_src):
        _warn_peaks_unknown(kind)
    source = (flops_src if flops_src == hbm_src
              else flops_src + '+' + hbm_src)
    return {'flops': flops, 'hbm_bytes_s': hbm, 'kind': kind,
            'source': source, 'flops_source': flops_src,
            'hbm_source': hbm_src}


def device_peak_flops(device=None):
    """(peak_bf16_flops, device_kind) for the MFU denominator. Nominal
    (guessed-CPU) peaks report 0.0 here — MFU against a guessed peak
    would be noise — while the roofline keeps them via
    :func:`device_peaks`. Unknown kinds also report 0.0, after the
    warn-once + ``roofline.peaks_unknown`` publication."""
    p = device_peaks(device)
    if p['flops_source'] in ('table', 'override'):
        return p['flops'], p['kind']
    return 0.0, p['kind']


def _reset_peaks_warned_for_tests():
    global _peaks_unknown_warned
    _peaks_unknown_warned = False


def mfu_estimate():
    """step_flops * observed steps / elapsed / peak — or None when any
    ingredient (FLOPs, a step count, a known chip) is missing. Reads
    metrics with registry.get (never create-on-read: a missing
    fit.steps must not plant a zero counter in the summary)."""
    st = _state()
    if not st.active:
        return None
    flops_g = st.registry.get('xla.step_flops')
    steps_c = st.registry.get('fit.steps')
    flops = flops_g.value if flops_g is not None else None
    steps = steps_c.value if steps_c is not None else 0
    elapsed = time.time() - st.t_start
    if not flops or not steps or elapsed <= 0:
        return None
    peak, _ = device_peak_flops()
    if not peak:
        return None
    return flops * steps / elapsed / peak
