"""Goodput accounting: where every second of the run's wall-clock went.

The other telemetry planes explain *rates* (spans, MFU, roofline) and
*failures* (health, watchdog); this one answers the operator's top-line
question — of the N hours this job ran, how many were productive
training? Every second of measured wall-clock is classified into a
named bucket:

- ``step``        productive train-step compute (dispatch + stats fetch)
- ``compile``     XLA compilation (the ``xla.compile_secs`` counter)
- ``input_wait``  the host waiting on / preparing input (draw + put)
- ``checkpoint``  checkpoint capture + save time
- ``eval``        evaluation / inference loops
- ``comm``        the collective share carved out of step time, labeled
                  with its provenance (measured trace vs roofline model)
- ``rework``      steps re-trained between ``last_good`` and a crash
                  (fed by module/resilient_fit.py restart hooks)
- ``overhead``    everything unattributed: wall minus the sum above

The invariant that makes the accounting trustworthy: buckets + overhead
sum to measured wall-clock EXACTLY (overhead is the unclamped
remainder, so over-attribution shows up as negative overhead instead of
silently vanishing — attribution that doesn't sum is a graph, not an
accounting). tests/unittest/test_goodput.py pins the sum property and
bounds the over-count at 5% on an instrumented CPU fit.

Inputs are the EXISTING span/mark sites — histogram sums and counters
already in the registry — so the plane adds no device syncs and no new
instrumentation to the hot loops. Only LEAF spans feed the buckets:
parents (``fit.batch``) and nested spans (``io.prefetch_wait`` inside
draw) stay out, because a span counted twice breaks the sum invariant
this plane exists for.

Across supervised relaunches, tools/train_supervisor.py and
tools/gang_supervisor.py stamp the cumulative lost-work seconds of
every dead attempt into ``MXTPU_GOODPUT_LOST_S``; the relaunched
process reports it as ``prior_lost_s`` plus the derived ``job_wall_s``
/ ``job_goodput_pct`` — separate fields, so the per-process buckets
still sum to the per-process wall.

Gating: ``MXTPU_GOODPUT`` (default on) *and* ``MXTPU_TELEMETRY=1``.
Telemetry off = true no-op: no registry writes, no I/O, one cached-bool
check per entry point, and the compiled programs are untouched (this
module never reaches a trace path).
"""
import threading
import time

__all__ = ['BUCKETS', 'enabled', 'compute', 'note_rework', 'current',
           'summarize', 'snapshot_goodput', 'local_stats']

# bucket order is the contract: the cluster sync vector encodes the
# top-badput bucket as this tuple's index, and the summary block and
# JSONL record render in this order
BUCKETS = ('step', 'compile', 'input_wait', 'checkpoint', 'eval', 'comm',
           'rework', 'overhead')

# leaf span families feeding each raw bucket (histogram sums are
# milliseconds). fused_fit.build is where the fused window's compiles
# block, so compile seconds landing there are not double-counted.
STEP_SPANS = ('fit.dispatch', 'fused_fit.dispatch', 'fused_fit.fetch')
INPUT_SPANS = ('fit.draw', 'fused_fit.draw', 'fused_fit.put')
EVAL_SPANS = ('eval.dispatch', 'eval.metric', 'eval.fetch',
              'fused_eval.draw', 'fused_eval.put', 'fused_eval.dispatch',
              'fused_eval.fetch')
CKPT_SPANS = ('ckpt.save', 'ckpt.capture')
BUILD_SPANS = ('fused_fit.build',)


class _GState:
    __slots__ = ('decided', 'active', 'rework_steps', 'prior_lost_s',
                 'last', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.rework_steps = 0
        self.prior_lost_s = 0.0
        self.last = None
        self.lock = threading.Lock()


_state = _GState()
_decide_lock = threading.Lock()


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        tele_on = _tele().active
        on = False
        prior = 0.0
        if tele_on:
            from ..config import flags
            try:
                flags.reload('MXTPU_GOODPUT')
                flags.reload('MXTPU_GOODPUT_LOST_S')
                on = bool(flags.get('MXTPU_GOODPUT'))
                prior = float(flags.get('MXTPU_GOODPUT_LOST_S'))
            except Exception:  # noqa: BLE001 — stripped builds w/o the flag
                on, prior = False, 0.0
        _state.active = on
        _state.prior_lost_s = max(0.0, prior)
        _state.decided = True
    return _state.active


def enabled():
    return _state.active if _state.decided else _decide()


def _emit(rec):
    st = _tele()
    if st.active and st.sink is not None:
        st.sink.emit(rec)


# ---------------------------------------------------------------------------
# the pure attribution arithmetic (shared with tools/telemetry_report.py's
# offline reconstruction — a run that died mid-epoch accounts its badput
# from raw records through this same function)
# ---------------------------------------------------------------------------

def _span_sum_s(hists, names):
    total = 0.0
    for name in names:
        h = hists.get(name)
        if h:
            total += float(h.get('sum') or 0.0)
    return total / 1e3


def compute(snapshot, elapsed_s, rework_steps=0, total_steps=None,
            comm_pct=None, comm_source=None, prior_lost_s=0.0):
    """Classify ``elapsed_s`` wall-clock seconds into the named buckets,
    from a registry snapshot (live ``Registry.snapshot()`` or the
    offline reconstruction — both carry histogram ``sum`` values).

    Pure: no registry access, no flag reads — callable with telemetry
    off (telemetry_report reconstructs crashed runs through it).

    - ``comm_pct``/``comm_source`` carve the collective share out of
      the step bucket, provenance attached (measured vs modeled —
      never confuse the two);
    - ``rework_steps`` re-prices that many steps at the run's mean
      per-step cost and moves them from ``step`` (productive) to
      ``rework`` (badput);
    - ``overhead`` is the UNCLAMPED remainder, so buckets + overhead
      always sum to ``elapsed_s`` exactly.
    """
    elapsed_s = max(0.0, float(elapsed_s or 0.0))
    hists = snapshot.get('histograms') or {}
    counters = snapshot.get('counters') or {}
    step_s = _span_sum_s(hists, STEP_SPANS)
    input_s = _span_sum_s(hists, INPUT_SPANS)
    eval_s = _span_sum_s(hists, EVAL_SPANS)
    ckpt_s = _span_sum_s(hists, CKPT_SPANS)
    build_s = _span_sum_s(hists, BUILD_SPANS)
    compile_s = float(counters.get('xla.compile_secs') or 0.0)
    # compile overlap: fused-window compiles block inside
    # fused_fit.build (its own span, not otherwise bucketed); per-batch
    # compiles block inside the first fit.dispatch. Compile seconds not
    # covered by build must come out of the step bucket or they'd be
    # counted twice.
    in_build = min(compile_s, build_s)
    step_s = max(0.0, step_s - min(compile_s - in_build, step_s))
    comm_s = 0.0
    if comm_pct is not None and comm_pct > 0.0:
        comm_s = step_s * min(100.0, float(comm_pct)) / 100.0
        step_s -= comm_s
    rework_s = 0.0
    rework_steps = max(0, int(rework_steps or 0))
    if rework_steps and total_steps:
        per_step = step_s / max(1, int(total_steps))
        rework_s = min(step_s, per_step * rework_steps)
        step_s -= rework_s
    buckets = {
        'step': step_s,
        'compile': compile_s,
        'input_wait': input_s,
        'checkpoint': ckpt_s,
        'eval': eval_s,
        'comm': comm_s,
        'rework': rework_s,
    }
    attributed = sum(buckets.values())
    buckets['overhead'] = elapsed_s - attributed
    badput = [(v, k) for k, v in buckets.items()
              if k != 'step' and v > 0.0]
    out = {
        'wall_s': round(elapsed_s, 3),
        'buckets': {k: round(buckets[k], 3) for k in BUCKETS},
        'goodput_pct': round(100.0 * step_s / elapsed_s, 2)
        if elapsed_s > 0.0 else 0.0,
        'badput_top': max(badput)[1] if badput else None,
        'rework_steps': rework_steps,
    }
    if comm_pct is not None:
        out['comm_source'] = comm_source or 'modeled'
    prior_lost_s = max(0.0, float(prior_lost_s or 0.0))
    if prior_lost_s > 0.0:
        job_wall = elapsed_s + prior_lost_s
        out['prior_lost_s'] = round(prior_lost_s, 3)
        out['job_wall_s'] = round(job_wall, 3)
        out['job_goodput_pct'] = round(100.0 * step_s / job_wall, 2) \
            if job_wall > 0.0 else 0.0
    return out


# ---------------------------------------------------------------------------
# live feeds
# ---------------------------------------------------------------------------

def note_rework(steps):
    """Record ``steps`` re-trained steps (restart rework badput): the
    span between the restored ``last_good`` checkpoint and the step the
    crashed attempt had reached. Fed by module/resilient_fit.py at each
    restart; the re-priced seconds land in the ``rework`` bucket."""
    if not enabled():
        return
    steps = max(0, int(steps))
    if not steps:
        return
    with _state.lock:
        _state.rework_steps += steps
        total = _state.rework_steps
    _tele().registry.gauge('goodput.rework_steps').set(total)


def current(comm_pct=None, comm_source=None):
    """The goodput dict computed from the live registry right now
    (no gauges published, no record emitted), or None while off.
    When the caller has no comm share at hand the roofline's
    provenance-labeled one is used."""
    if not enabled():
        return None
    st = _tele()
    if comm_pct is None:
        from . import roofline
        comm_pct, comm_source = roofline.comm_share()
    snap = st.registry.snapshot()
    with _state.lock:
        rework = _state.rework_steps
    total_steps = int((snap.get('counters') or {}).get('fit.steps') or 0)
    return compute(snap, time.time() - st.t_start,
                   rework_steps=rework, total_steps=total_steps,
                   comm_pct=comm_pct, comm_source=comm_source,
                   prior_lost_s=_state.prior_lost_s)


def local_stats():
    """This host's contribution to the cluster sync vector:
    ``(goodput_pct, badput_top_index)`` with NaN for unavailable —
    the fleet aggregation (telemetry/cluster.py) reports fleet goodput
    as the slowest host's, with its top badput bucket named."""
    nan = float('nan')
    if not enabled():
        return nan, nan
    g = current()
    if g is None or not g['wall_s']:
        return nan, nan
    top = g.get('badput_top')
    return (float(g['goodput_pct']),
            float(BUCKETS.index(top)) if top in BUCKETS else nan)


def summarize(elapsed_s=None):
    """End-of-run hook (telemetry.write_summary): compute the
    attribution, publish the ``goodput.*`` gauges and the ``goodput``
    JSONL record, and return the dict for the summary table / summary
    record (None while off)."""
    if not enabled():
        return None
    st = _tele()
    if elapsed_s is None:
        elapsed_s = time.time() - st.t_start
    from . import roofline
    comm_pct, comm_source = roofline.comm_share()
    snap = st.registry.snapshot()
    with _state.lock:
        rework = _state.rework_steps
    total_steps = int((snap.get('counters') or {}).get('fit.steps') or 0)
    out = compute(snap, elapsed_s, rework_steps=rework,
                  total_steps=total_steps, comm_pct=comm_pct,
                  comm_source=comm_source,
                  prior_lost_s=_state.prior_lost_s)
    reg = st.registry
    reg.gauge('goodput.goodput_pct').set(out['goodput_pct'])
    for name in BUCKETS:
        reg.gauge('goodput.%s_s' % name).set(out['buckets'][name])
    if out.get('badput_top'):
        reg.gauge('goodput.badput_top').set(out['badput_top'])
    if out.get('comm_source'):
        reg.gauge('goodput.comm_source').set(out['comm_source'])
    if rework:
        reg.gauge('goodput.rework_steps').set(rework)
    if out.get('prior_lost_s'):
        reg.gauge('goodput.prior_lost_s').set(out['prior_lost_s'])
        reg.gauge('goodput.job_goodput_pct').set(out['job_goodput_pct'])
    rec = {'type': 'goodput'}
    rec.update(out)
    _emit(rec)
    with _state.lock:
        _state.last = out
    return out


def snapshot_goodput():
    """The last summarize() result (JSON-serializable), or None — the
    summary record's ``goodput`` key and /summary's input."""
    with _state.lock:
        return dict(_state.last) if _state.last else None


def _reset_for_tests():
    global _state
    _state = _GState()
