"""Per-program cost attribution: the compiled-program registrar.

Everything XLA runs for this framework is built at a handful of compile
sites (the executor's fwd / fwd+bwd programs, the fused fit/eval window
programs, bench.py's raw train step). PR 1's telemetry could time those
dispatches but the programs themselves stayed anonymous blobs — FLOPs
for the MFU gauge were hand-computed in bench.py and memory gauges were
whole-device totals. This module makes every compiled program
self-describing, following the compiler-stack practice of making
per-program cost a first-class primitive (TVM, arXiv:1802.04799; the
compiled-program boundary as the natural instrumentation unit,
Julia->TPU arXiv:1810.09868):

- :func:`analyze_compiled` — pure: XLA's own ``cost_analysis()`` /
  ``memory_analysis()`` of a compiled executable as a plain dict
  (FLOPs, bytes accessed, temp/argument/output/generated-code bytes).
  Works with telemetry off — bench.py computes its headline numbers
  through it either way;
- :func:`note_program` — publish one program's analysis: ``program.*``
  gauges in the registry, a ``program`` JSONL record, a row in the
  end-of-run per-program summary table, and (for programs marked as
  the train step) :func:`telemetry.xla.note_step_flops`, so the MFU
  estimate is framework-computed instead of bench-only;
- :func:`register` — the compile-site interceptor. Wraps a
  ``jax.jit``-ed callable so its lazy compile becomes an explicit
  ``lower().compile()`` whose executable this module can analyze; the
  wrapper then dispatches through the AOT executable (ONE compile
  total, same numerics). With telemetry off it returns the jitted
  callable unchanged — the zero-overhead no-op contract;
- :func:`maybe_oom_report` — on a ``RESOURCE_EXHAUSTED`` error, dump
  the per-program memory breakdown alongside ``memory_stats()`` so an
  OOM stops being a one-line crash: the report says which programs
  were resident and what XLA planned to allocate for each.
"""
import logging
import threading
import time

__all__ = ['analyze_compiled', 'note_program', 'register',
           'snapshot_programs', 'maybe_oom_report']

_lock = threading.Lock()
_programs = {}          # name -> record dict (see note_program)
_step_flops_seen = {}   # name -> max flops across its recompiles
_oom_reported = False

_ANALYSIS_FIELDS = ('flops', 'bytes_accessed', 'temp_bytes',
                    'argument_bytes', 'output_bytes',
                    'generated_code_bytes', 'alias_bytes', 'live_bytes')


def _state():
    from . import enabled
    enabled()   # decide from the flag if nothing else has yet
    from . import _state as st
    return st


def _empty_analysis():
    return {'flops': 0.0, 'bytes_accessed': 0.0, 'temp_bytes': 0,
            'argument_bytes': 0, 'output_bytes': 0,
            'generated_code_bytes': 0, 'alias_bytes': 0, 'live_bytes': 0}


def analyze_compiled(compiled):
    """XLA's own cost + memory analysis of a compiled executable, as a
    plain dict (zeros where a backend doesn't report). Pure — no
    registry writes, no I/O — so callers that need the numbers with
    telemetry off (bench.py's MFU math) can use it directly."""
    rec = _empty_analysis()
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        rec['flops'] = float(cost.get('flops', 0.0) or 0.0)
        rec['bytes_accessed'] = float(cost.get('bytes accessed', 0.0) or 0.0)
    except Exception as e:  # noqa: BLE001 — observability must not kill
        logging.debug('telemetry: cost_analysis unavailable: %s', e)
    try:
        ma = compiled.memory_analysis()
        if isinstance(ma, (list, tuple)):
            ma = ma[0]
        for field, attr in (('temp_bytes', 'temp_size_in_bytes'),
                            ('argument_bytes', 'argument_size_in_bytes'),
                            ('output_bytes', 'output_size_in_bytes'),
                            ('generated_code_bytes',
                             'generated_code_size_in_bytes'),
                            ('alias_bytes', 'alias_size_in_bytes')):
            rec[field] = int(getattr(ma, attr, 0) or 0)
        # steady-state footprint of one dispatch: args + temps + outputs
        # minus the donated-input bytes the outputs alias in place. The
        # donation ledger: aliasing a carry moves its output bytes into
        # alias_bytes, so live_bytes is what a window actually makes
        # XLA hold beyond the buffers the caller already owns.
        rec['live_bytes'] = max(0, rec['argument_bytes']
                                + rec['temp_bytes'] + rec['output_bytes']
                                - rec['alias_bytes'])
    except Exception as e:  # noqa: BLE001
        logging.debug('telemetry: memory_analysis unavailable: %s', e)
    return rec


def note_program(name, compiled=None, analysis=None, step_flops=False,
                 compile_s=None):
    """Record one compiled program under ``name``. Returns the analysis
    dict (computed from ``compiled`` when not given) whether or not
    telemetry is on; publication — ``program.*`` gauges, the JSONL
    ``program`` record, the summary-table row, the automatic
    :func:`~.xla.note_step_flops` feed for ``step_flops=True``
    programs — only happens while telemetry is active."""
    if analysis is None:
        analysis = analyze_compiled(compiled) if compiled is not None \
            else _empty_analysis()
    st = _state()
    if not st.active:
        return analysis
    if compiled is not None:
        # roofline attribution (MXTPU_ROOFLINE): parse the program's
        # HLO into per-layer costs while the executable is in hand —
        # one cached-bool check when the flag is off
        from . import roofline
        if roofline.enabled():
            roofline.note_compiled(name, compiled, analysis=analysis,
                                   step_flops=step_flops)
        # memory attribution (MXTPU_MEMORY): same contract — parse the
        # HLO into per-layer buffer bytes while the executable is in
        # hand, one cached-bool check when the flag is off
        from . import memory
        if memory.enabled():
            memory.note_compiled(name, compiled, analysis=analysis)
    with _lock:
        rec = _programs.get(name)
        if rec is None:
            rec = _programs[name] = {'name': name, 'compiles': 0,
                                     'dispatches': 0}
            rec.update(_empty_analysis())
        for f in _ANALYSIS_FIELDS:
            # a name can cover several compiled variants (shape
            # variants, train/eval forms): keep the LARGEST value per
            # field — the conservative bound the OOM report and MFU
            # want, instead of whichever variant compiled last.
            # .get(): hand-crafted analysis dicts (tests, older
            # callers) may predate the alias/live fields
            rec[f] = max(rec[f], analysis.get(f, 0))
        merged = {f: rec[f] for f in _ANALYSIS_FIELDS}
        rec['compiles'] += 1
    reg = st.registry
    reg.counter('program.compiles').inc()
    # gauges mirror the MERGED record so the two views never disagree
    reg.gauge('program.%s.flops' % name).set(merged['flops'])
    reg.gauge('program.%s.bytes_accessed' % name).set(
        merged['bytes_accessed'])
    reg.gauge('program.%s.temp_bytes' % name).set(merged['temp_bytes'])
    reg.gauge('program.%s.alias_bytes' % name).set(merged['alias_bytes'])
    reg.gauge('program.%s.live_bytes' % name).set(merged['live_bytes'])
    if step_flops and analysis['flops']:
        # the train-step program: its FLOPs feed the MFU estimate. XLA
        # counts a scan (while-loop) body ONCE regardless of trip
        # count, so a W-step fused window reports per-step FLOPs
        # already — exactly what note_step_flops wants. Feed the MAX
        # across ALL step-marked programs so far: neither a tail-batch
        # shape variant nor the tail's executor.fwd_bwd (compiled after
        # the fused window, without the update math) may shrink the
        # per-step FLOPs the whole run's MFU is computed from.
        with _lock:
            _step_flops_seen[name] = max(_step_flops_seen.get(name, 0.0),
                                         analysis['flops'])
            fed = max(_step_flops_seen.values())
        from . import xla
        xla.note_step_flops(fed)
    if st.sink is not None:
        out = {'type': 'program', 'name': name}
        out.update({f: analysis.get(f, 0) for f in _ANALYSIS_FIELDS})
        if compile_s is not None:
            out['compile_s'] = round(float(compile_s), 3)
        st.sink.emit(out)
    return analysis


def note_dispatch(name):
    """Count one dispatch of a registered program (wrapper-internal)."""
    with _lock:
        rec = _programs.get(name)
        if rec is not None:
            rec['dispatches'] += 1


def snapshot_programs():
    """Point-in-time {name: record} copy — the summary table's input."""
    with _lock:
        return {n: dict(r) for n, r in _programs.items()}


# -- the compile-site interceptor -------------------------------------------

class _RegisteredProgram:
    """AOT wrapper around a jitted callable: the first call per
    argument signature runs ``lower().compile()`` explicitly (one
    compile total — the lazy path would have compiled here anyway),
    hands the executable to :func:`note_program`, then dispatches
    through it. Any lower/compile/dispatch surprise falls back to the
    wrapped lazy jit for that signature — attribution is best-effort,
    execution is not."""

    __slots__ = ('name', 'jitted', 'static_argnums', 'step_flops',
                 '_compiled')

    def __init__(self, name, jitted, static_argnums, step_flops):
        self.name = name
        self.jitted = jitted
        self.static_argnums = tuple(static_argnums)
        self.step_flops = step_flops
        self._compiled = {}

    def lower(self, *args, **kwargs):
        return self.jitted.lower(*args, **kwargs)

    def _signature(self, args):
        import jax
        sig = []
        for i, arg in enumerate(args):
            flat, treedef = jax.tree_util.tree_flatten(arg)
            static = i in self.static_argnums
            leaves = []
            for leaf in flat:
                if hasattr(leaf, 'shape') and hasattr(leaf, 'dtype'):
                    leaves.append((tuple(leaf.shape), str(leaf.dtype),
                                   getattr(leaf, 'sharding', None)))
                elif static:
                    # static args select programs by VALUE, exactly as
                    # the jax.jit declaration does
                    leaves.append(('static', leaf))
                else:
                    # a traced python scalar: jit specializes on its
                    # TYPE (weak dtype), never its value — keying by
                    # value would compile per distinct value where the
                    # lazy jit compiles once
                    leaves.append(('scalar', type(leaf)))
            sig.append((treedef, tuple(leaves)))
        return tuple(sig)

    def _compile(self, args, key):
        t0 = time.time()
        try:
            compiled = self.jitted.lower(*args).compile()
        except Exception as e:  # noqa: BLE001 — fall back, never kill
            logging.debug('telemetry: AOT compile of %s failed (%s); '
                          'using lazy jit for this signature',
                          self.name, e)
            self._compiled[key] = False
            return False
        note_program(self.name, compiled=compiled,
                     step_flops=self.step_flops,
                     compile_s=time.time() - t0)
        self._compiled[key] = compiled
        return compiled

    def __call__(self, *args):
        try:
            key = self._signature(args)
            entry = self._compiled.get(key)
        except Exception:  # noqa: BLE001 — unhashable leaf etc.
            return self.jitted(*args)
        if entry is None:
            entry = self._compile(args, key)
        if entry is False:
            return self.jitted(*args)
        if self.static_argnums:
            dyn = [a for i, a in enumerate(args)
                   if i not in self.static_argnums]
        else:
            dyn = args
        try:
            out = entry(*dyn)
        except (TypeError, ValueError) as e:
            # an argument layout/device surprise the signature key
            # missed: the lazy jit handles it (argument checks raise
            # before any buffer is donated, so args are still alive).
            # Runtime errors (a genuine OOM mid-execution) re-raise —
            # retrying after donation would only mask the real failure.
            logging.debug('telemetry: AOT dispatch of %s failed (%s); '
                          'retrying via lazy jit', self.name, e)
            return self.jitted(*args)
        note_dispatch(self.name)
        return out


def register(name, jitted, static_argnums=(), step_flops=False):
    """Intercept a compile site. With telemetry on, returns a wrapper
    that compiles via ``lower().compile()``, analyzes the executable
    (:func:`note_program`), and dispatches through it; with telemetry
    off, returns ``jitted`` unchanged (zero overhead — the hot path
    sees the very same object it constructed).

    ``static_argnums`` must mirror the ``jax.jit`` declaration (AOT
    executables take only the dynamic arguments). ``step_flops=True``
    marks the program whose FLOPs define a training step — it feeds
    the framework-computed MFU estimate."""
    from . import enabled
    if not enabled():
        return jitted
    return _RegisteredProgram(name, jitted, static_argnums, step_flops)


def scope_name(name):
    """Sanitize a symbol/layer name for ``jax.named_scope`` / HLO
    metadata (scopes join with '/', so strip everything exotic)."""
    import re
    return re.sub(r'[^A-Za-z0-9_.\-]', '_', str(name)) or '_'


# -- OOM diagnostics ---------------------------------------------------------

def _looks_like_oom(msg):
    low = msg.lower()
    return 'resource_exhausted' in low or 'resource exhausted' in low


def maybe_oom_report(exc):
    """If ``exc`` is an XLA RESOURCE_EXHAUSTED error (and telemetry is
    on), log the per-program memory breakdown next to the device's
    ``memory_stats()`` and append an ``oom`` JSONL record — once per
    process, so a crash-loop cannot spam the log. Returns True when a
    report was (or already had been) written for an OOM error."""
    st = _state()
    if not st.active:
        return False
    msg = str(exc)
    if not _looks_like_oom(msg):
        return False
    global _oom_reported
    with _lock:
        if _oom_reported:
            return True
        _oom_reported = True
        progs = {n: dict(r) for n, r in _programs.items()}
    from . import xla
    stats = xla.sample_memory()
    lines = ['device OOM (RESOURCE_EXHAUSTED) — per-program memory '
             'breakdown (XLA memory_analysis, bytes XLA planned to '
             'allocate per program):']
    for name in sorted(progs):
        r = progs[name]
        lines.append(
            '  %-44s temp=%8.1f MiB  args=%8.1f MiB  out=%8.1f MiB  '
            'dispatches=%d' % (name, r['temp_bytes'] / 2**20,
                               r['argument_bytes'] / 2**20,
                               r['output_bytes'] / 2**20,
                               r['dispatches']))
    if not progs:
        lines.append('  (no programs registered — the failing compile '
                     'itself may have exhausted memory)')
    if stats:
        keep = ('bytes_in_use', 'peak_bytes_in_use', 'bytes_limit',
                'largest_free_block_bytes')
        lines.append('  device memory_stats: %s' %
                     ', '.join('%s=%s' % (k, stats[k])
                               for k in keep if k in stats))
    else:
        lines.append('  device memory_stats() unavailable on this backend')
    logging.error('%s', '\n'.join(lines))
    if st.sink is not None:
        clean_stats = {k: v for k, v in (stats or {}).items()
                       if isinstance(v, (int, float, str, bool))}
        rec = {'type': 'oom', 'error': msg[:500],
               'programs': progs, 'memory_stats': clean_stats}
        # cross-link what the MXTPU_MEMORY forecaster last said before
        # the allocator died — the post-mortem's "was this predicted?"
        try:
            from . import memory
            fc = memory.last_forecast()
            if fc:
                rec['last_forecast'] = {k: v for k, v in fc.items()
                                        if k != 'type'}
        except Exception:  # noqa: BLE001 — forensics must not add a crash
            pass
        st.sink.emit(rec)
        st.sink.flush()
    # flight recorder: what the process was doing in the records
    # before the allocation failed
    try:
        from . import flight
        flight.dump('oom')
    except Exception:  # noqa: BLE001 — forensics must not add a crash
        pass
    return True


def _reset_for_tests():
    global _oom_reported
    with _lock:
        _programs.clear()
        _step_flops_seen.clear()
        _oom_reported = False
