"""SLO plane: latency/error objectives, burn rate, degraded /healthz.

The health sentinels catch runs computing wrong numbers and the
watchdog catches runs computing nothing; neither says whether the
SERVING plane is meeting its contract. This module tracks configurable
service-level objectives over the request stream:

- ``MXTPU_SLO_LATENCY_MS`` — a request slower than this counts as bad,
  exactly like a server-side error;
- ``MXTPU_SLO_ERROR_PCT`` — the error budget: the allowed share (%) of
  bad requests. With only the latency objective set the budget
  defaults to :data:`_DEFAULT_BUDGET_PCT` (1%).

Every completed request feeds :func:`note_request`. Over a rolling
window of the last ``MXTPU_SLO_WINDOW`` requests the module derives
the **burn rate** (bad share / budget: 1.0 = burning the budget
exactly as fast as allowed) and publishes the ``slo.*`` gauge family
on ``/metrics``:

``slo.latency_objective_ms``, ``slo.error_budget_pct``,
``slo.bad_pct`` (rolling), ``slo.burn_rate`` (rolling),
``slo.budget_remaining_pct`` (cumulative since start),
``slo.window_requests``, ``slo.degraded`` (0/1).

Sustained burn — ``burn_rate >= 1`` with at least :data:`_MIN_REQUESTS`
requests in the window — flips ``/healthz`` to the ``slo_degraded``
state (503, distinct from ``hung`` and the non-finite ``degraded``),
which the gang/train supervisors and any load balancer can probe; the
state clears automatically once fresh traffic meets the objectives
again. Each degraded transition emits an ``slo`` JSONL record and
dumps the flight recorder (``flight-slo-burn.jsonl``) so the requests
*before* the burn are on disk for the postmortem.

Gating: ``MXTPU_TELEMETRY=1`` *and* at least one objective set. Off =
one cached-bool check per request, no state, no gauges.

Client-side rejects (malformed bodies, 400s) do NOT burn the budget —
the objective measures the service, not its callers; only server-side
failures (dispatch/fetch errors, 5xx) and objective-breaking latencies
count.
"""
import collections
import logging
import threading
import time

__all__ = ['enabled', 'note_request', 'degraded', 'snapshot_slo']

_DEFAULT_BUDGET_PCT = 1.0   # budget when only the latency objective set
_MIN_REQUESTS = 16          # window floor before a degraded verdict
_DEGRADE_BURN = 1.0         # burn rate at/above which the state flips
_STALE_S = 120.0            # degraded + this long with NO requests =
                            # self-clear: a load balancer that pulls a
                            # 503 replica starves it of the fresh
                            # traffic recovery needs, so a frozen bad
                            # window must not pin the state forever


class _SState:
    __slots__ = ('decided', 'active', 'latency_ms', 'budget_pct',
                 'window', 'ring', 'total', 'total_bad', 'degraded',
                 'last_note', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.latency_ms = 0.0
        self.budget_pct = 0.0
        self.window = 0
        self.ring = None          # deque of per-request bad bools
        self.total = 0
        self.total_bad = 0
        self.degraded = False
        self.last_note = None     # monotonic stamp of the last request
        self.lock = threading.Lock()


_state = _SState()
_decide_lock = threading.Lock()


def _tele():
    """The telemetry package state (deciding it from the flag first)."""
    from . import enabled as _tele_enabled, _state as st
    _tele_enabled()
    return st


def _decide():
    # decide telemetry before taking our lock (the telemetry decide
    # runs sink/flight side effects — same re-entrancy discipline as
    # flight._decide)
    tele_on = _tele().active
    with _decide_lock:
        if _state.decided:
            return _state.active
        lat = err = 0.0
        window = 128
        if tele_on:
            from ..config import flags
            try:
                flags.reload('MXTPU_SLO_LATENCY_MS')
                flags.reload('MXTPU_SLO_ERROR_PCT')
                flags.reload('MXTPU_SLO_WINDOW')
                lat = float(flags.get('MXTPU_SLO_LATENCY_MS'))
                err = float(flags.get('MXTPU_SLO_ERROR_PCT'))
                window = int(flags.get('MXTPU_SLO_WINDOW'))
            except Exception:  # noqa: BLE001 — stripped builds w/o flags
                lat = err = 0.0
        on = lat > 0.0 or err > 0.0
        _state.latency_ms = lat
        _state.budget_pct = err if err > 0.0 else \
            (_DEFAULT_BUDGET_PCT if lat > 0.0 else 0.0)
        _state.window = window
        if on:
            _state.ring = collections.deque(maxlen=window)
            reg = _tele().registry
            if lat > 0.0:
                reg.gauge('slo.latency_objective_ms').set(lat)
            reg.gauge('slo.error_budget_pct').set(_state.budget_pct)
        _state.active = on
        _state.decided = True
    return _state.active


def enabled():
    """Whether the SLO plane is armed: MXTPU_TELEMETRY=1 and at least
    one of MXTPU_SLO_LATENCY_MS / MXTPU_SLO_ERROR_PCT set, decided
    once. One attribute check after the first call — the serving
    loop's gate."""
    if _state.decided:
        return _state.active
    return _decide()


def note_request(latency_ms, error=False):
    """Feed one completed request: its latency (ms) and whether it
    failed server-side. Updates the rolling window, the ``slo.*``
    gauges and the degraded state; emits the transition record + the
    flight dump on a flip. Off = one cached-bool check."""
    if not enabled():
        return None
    st = _state
    bad = bool(error) or (st.latency_ms > 0.0
                          and float(latency_ms) > st.latency_ms)
    flipped = None
    with st.lock:
        st.last_note = time.monotonic()
        st.ring.append(bad)
        st.total += 1
        st.total_bad += int(bad)
        n = len(st.ring)
        n_bad = sum(st.ring)
        bad_pct = 100.0 * n_bad / n
        burn = bad_pct / st.budget_pct if st.budget_pct else 0.0
        # cumulative budget remaining: how much of the allowed bad
        # share the run has consumed since start (floored at 0)
        allowed = st.total * st.budget_pct / 100.0
        remaining = max(0.0, 1.0 - (st.total_bad / allowed)) * 100.0 \
            if allowed > 0 else 100.0
        want_degraded = n >= _MIN_REQUESTS and burn >= _DEGRADE_BURN
        if want_degraded != st.degraded:
            st.degraded = want_degraded
            flipped = want_degraded
    reg = _tele().registry
    reg.gauge('slo.bad_pct').set(round(bad_pct, 2))
    reg.gauge('slo.burn_rate').set(round(burn, 3))
    reg.gauge('slo.budget_remaining_pct').set(round(remaining, 2))
    reg.gauge('slo.window_requests').set(n)
    reg.gauge('slo.degraded').set(int(st.degraded))
    if flipped is not None:
        _transition(flipped, bad_pct, burn)
    return bad


def _transition(now_degraded, bad_pct, burn):
    """One degraded/recovered flip: JSONL record, log line, and (on
    the way DOWN) the flight dump — the window before the burn is
    exactly what the postmortem wants. Guarded throughout: this runs
    inside note_request, which the batcher's failure path calls while
    resolving per-request futures — a forensics error here must never
    strand a caller."""
    try:
        st = _tele()
        rec = {'type': 'slo',
               'event': 'degraded' if now_degraded else 'recovered',
               'bad_pct': round(bad_pct, 2),
               'burn_rate': round(burn, 3),
               'latency_objective_ms': _state.latency_ms or None,
               'error_budget_pct': _state.budget_pct}
        if st.sink is not None:
            st.sink.emit(rec)
            st.sink.flush()
        if now_degraded:
            logging.warning(
                'slo: error budget burning at %.1fx (%.1f%% bad '
                'requests against a %.1f%% budget) — /healthz now '
                'answers slo_degraded', burn, bad_pct,
                _state.budget_pct)
            from . import flight
            flight.dump('slo-burn')
        else:
            logging.warning('slo: burn recovered (%.1f%% bad, burn '
                            '%.2fx) — /healthz back to ok', bad_pct,
                            burn)
    except Exception as e:  # noqa: BLE001 — see docstring
        logging.debug('slo: transition reporting failed: %s', e)


def degraded():
    """The active SLO-degraded digest (burn >= 1 sustained over the
    rolling window), or None. telemetry/serve.py answers /healthz 503
    with status ``slo_degraded`` on it — distinct from ``hung``
    (watchdog) and ``degraded`` (non-finite incidents).

    Staleness decay: a degraded replica a load balancer pulled on the
    503 receives no fresh traffic, and the frozen bad window would
    otherwise pin it out of service forever; after :data:`_STALE_S`
    seconds with zero requests the state (and the stale window)
    self-clears so the replica can rejoin and be re-judged on live
    traffic."""
    if not enabled() or not _state.degraded:
        return None
    st = _state
    cleared = False
    with st.lock:
        if st.degraded and st.last_note is not None and \
                time.monotonic() - st.last_note > _STALE_S:
            st.degraded = False
            st.ring.clear()
            cleared = True
    if cleared:
        _tele().registry.gauge('slo.degraded').set(0)
        logging.warning('slo: degraded state stale (%.0fs with no '
                        'requests) — clearing so the replica can '
                        'rejoin and be re-judged', _STALE_S)
        return None
    return snapshot_slo()


def snapshot_slo():
    """Point-in-time SLO dict (JSON-safe) for /healthz, /summary and
    the watch CLI; None while the plane is off."""
    if not enabled():
        return None
    st = _state
    with st.lock:
        n = len(st.ring)
        n_bad = sum(st.ring)
        bad_pct = 100.0 * n_bad / n if n else 0.0
        burn = bad_pct / st.budget_pct if st.budget_pct else 0.0
        allowed = st.total * st.budget_pct / 100.0
        remaining = max(0.0, 1.0 - (st.total_bad / allowed)) * 100.0 \
            if allowed > 0 else 100.0
        return {'latency_objective_ms': st.latency_ms or None,
                'error_budget_pct': st.budget_pct,
                'window_requests': n,
                'bad_pct': round(bad_pct, 2),
                'burn_rate': round(burn, 3),
                'budget_remaining_pct': round(remaining, 2),
                'degraded': bool(st.degraded)}


def _reset_for_tests():
    global _state
    _state = _SState()
