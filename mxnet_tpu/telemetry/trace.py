"""Request-level tracing: one id per serving request, end to end.

The serving plane's aggregate metrics (``serve.request_latency`` p99,
queue depth, pad fraction) say *that* tail latency moved, never *which*
request was slow or *where* in the pipeline its time went. This module
is the per-request half: a trace id is minted at the HTTP frontend (or
accepted from the client via ``X-Request-Id`` / W3C ``traceparent``),
rides the request through :class:`~mxnet_tpu.serving.batcher.
DynamicBatcher` into the engine dispatch, and lands as one ``trace``
JSONL record carrying the stage breakdown::

    {"type": "trace", "trace_id": "...", "dispatch_span": "...",
     "rows": 2, "status": "ok", "total_ms": 7.31,
     "stages": {"queue_wait_ms": 4.8, "coalesce_ms": 0.02,
                "pad_ms": 0.05, "dispatch_ms": 0.7, "fetch_ms": 1.6,
                "split_ms": 0.03}}

The batcher's shared-dispatch structure is preserved: N coalesced
requests emit N trace records that all carry the SAME ``dispatch_span``
id (the batch-level pad/dispatch/fetch stages are shared; queue_wait
and split are per-request), so a dump groups back into one dispatch
with N passengers. When the chrome-trace profiler is running, each
finished trace also lands on the profiler timeline (one request span +
its stage sub-events), merging with the engine's own ``serve.dispatch``
span rows.

The ``serve.request_latency`` histogram gains the trace id as an
exemplar, so a scraped p99 on ``/metrics`` links to a concrete trace id
greppable in the JSONL log / flight recording
(``tools/trace_report.py`` renders either).

Gating: tracing rides ``MXTPU_TELEMETRY`` — with telemetry off no
trace object is ever allocated and no id is minted (one cached-bool
check at the submit site; the compiled programs are untouched either
way — tracing is pure host-side bookkeeping).
"""
import os
import re
import time

__all__ = ['enabled', 'new_trace_id', 'new_span_id', 'from_headers',
           'start', 'RequestTrace', 'STAGES']

# the stage vocabulary, in pipeline order — shared with
# tools/trace_report.py so the offline renderer and the emitter can
# never disagree on the breakdown's columns
STAGES = ('queue_wait', 'coalesce', 'pad', 'dispatch', 'fetch', 'split')

_TRACEPARENT_RE = re.compile(
    r'^[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}$')
_ID_SAFE_RE = re.compile(r'[^A-Za-z0-9_.\-]')
_MAX_ID_LEN = 64


def enabled():
    """Whether request tracing is on — exactly the telemetry switch."""
    from . import enabled as _tele_enabled
    return _tele_enabled()


def new_trace_id():
    """A fresh 16-hex-char trace id."""
    return os.urandom(8).hex()


def new_span_id():
    """A fresh 8-hex-char span id (the shared dispatch span)."""
    return os.urandom(4).hex()


def from_headers(headers):
    """The client-supplied trace id out of an HTTP header mapping, or
    None. ``X-Request-Id`` wins (sanitized, bounded); else the
    trace-id field of a well-formed W3C ``traceparent``."""
    if headers is None:
        return None
    rid = headers.get('X-Request-Id')
    if rid:
        rid = _ID_SAFE_RE.sub('_', rid.strip())[:_MAX_ID_LEN]
        if rid:
            return rid
    tp = headers.get('traceparent')
    if tp:
        m = _TRACEPARENT_RE.match(tp.strip().lower())
        if m:
            return m.group(1)
    return None


class RequestTrace:
    """One request's accumulating span breakdown (host-side only)."""

    __slots__ = ('trace_id', 'dispatch_span', 'rows', 'status',
                 't0_wall', 't0', 'stages', '_done')

    def __init__(self, trace_id, rows=None):
        self.trace_id = trace_id or new_trace_id()
        self.dispatch_span = None
        self.rows = rows
        self.status = 'ok'
        self.t0_wall = time.time()
        self.t0 = time.monotonic()
        self.stages = {}
        self._done = False

    def add(self, stage, ms):
        """Accumulate ``ms`` under ``stage`` (chunked dispatches add
        per chunk)."""
        self.stages[stage] = self.stages.get(stage, 0.0) + float(ms)

    def add_shared(self, dispatch_span, timings):
        """Absorb one dispatch's batch-level stage timings ({'pad_ms':
        ..}-style dict from the engine) plus the shared dispatch span
        id all passengers point at."""
        self.dispatch_span = dispatch_span
        for stage in STAGES:
            v = timings.get(stage + '_ms')
            if v is not None:
                self.add(stage, v)

    def finish(self, status='ok'):
        """Seal the trace: emit the ``trace`` JSONL record (which also
        enters the flight-recorder ring) and, when the chrome-trace
        profiler is running, the request's timeline events. Idempotent
        — the error path and the completion path can race."""
        if self._done:
            return None
        self._done = True
        self.status = status
        total_ms = (time.monotonic() - self.t0) * 1e3
        rec = {'type': 'trace', 'trace_id': self.trace_id,
               'dispatch_span': self.dispatch_span,
               'rows': self.rows, 'status': status,
               't': self.t0_wall, 'total_ms': round(total_ms, 4),
               'stages': {s + '_ms': round(v, 4)
                          for s, v in self.stages.items()}}
        from . import _state as st
        if st.active and st.sink is not None:
            st.sink.emit(dict(rec))
        from .. import profiler as _profiler
        if _profiler.is_running():
            t0_us = int(self.t0_wall * 1e6)
            _profiler.record_event('serve.request[%s]' % self.trace_id,
                                   t0_us, t0_us + int(total_ms * 1e3),
                                   'serve')
            # stage sub-events laid out cumulatively in pipeline order:
            # the host measured durations, not absolute stamps, so the
            # reconstruction is sequential by construction
            off = 0.0
            for stage in STAGES:
                v = self.stages.get(stage)
                if not v:
                    continue
                _profiler.record_event(
                    'serve.req.%s' % stage, t0_us + int(off * 1e3),
                    t0_us + int((off + v) * 1e3), 'serve')
                off += v
        return rec


def start(trace_id=None, rows=None):
    """A live :class:`RequestTrace` when tracing is on, else None (the
    batcher's one cached-bool check per submit)."""
    if not enabled():
        return None
    return RequestTrace(trace_id, rows=rows)
