"""Metrics registry: counters, gauges, histograms.

The process-wide instrumentation substrate the hot paths (fit loop,
fused window, executor, io, kvstore) report through. Three metric
kinds, all thread-safe (prefetch iterators and the jax.monitoring
compile listener report off the main thread):

- ``Counter``: monotonically increasing float (batches served, bytes
  pushed, compile seconds accumulated).
- ``Gauge``: last-write-wins value (steps per device call, samples/sec,
  live device bytes).
- ``Histogram``: streaming count/sum/min/max over ALL observations plus
  p50/p95 over a bounded ring of the most recent observations — a
  recent-window percentile, which is what a perf investigation wants
  (an old warmup outlier must not pin p95 forever).

Every site gets its metric via ``registry.counter(name)`` etc. —
create-once by name, like the reference's dmlc registry pattern.
Distinct kinds may not share a name (that is a bug at the call site).
"""
import collections
import threading

__all__ = ['Counter', 'Gauge', 'Histogram', 'Registry',
           'NULL_COUNTER', 'NULL_GAUGE', 'NULL_HISTOGRAM']

_HIST_WINDOW = 8192   # ring capacity backing the percentile estimates
_EXEMPLARS_KEPT = 8   # recent exemplar-carrying observations retained


class Counter:
    """Monotonic accumulator (float increments allowed: compile secs)."""

    __slots__ = ('name', '_value', '_lock')

    def __init__(self, name):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins sample."""

    __slots__ = ('name', '_value')

    def __init__(self, name):
        self.name = name
        self._value = None

    def set(self, v):
        self._value = v

    @property
    def value(self):
        return self._value


class Histogram:
    """count/sum/min/max over everything; p50/p95/max over the recent
    ring (last ``_HIST_WINDOW`` observations). Observations may carry
    an exemplar — a small label dict (e.g. ``{'trace_id': ...}``)
    linking the sample to a concrete artifact; the most recent few are
    retained and the highest-valued one rides the snapshot, so a
    scraped p99 names a trace an operator can actually pull up."""

    __slots__ = ('name', '_count', '_sum', '_min', '_max', '_ring',
                 '_ring_pos', '_exemplars', '_lock')

    def __init__(self, name):
        self.name = name
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._ring = []
        self._ring_pos = 0
        self._exemplars = collections.deque(maxlen=_EXEMPLARS_KEPT)
        self._lock = threading.Lock()

    def observe(self, v, exemplar=None):
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            if len(self._ring) < _HIST_WINDOW:
                self._ring.append(v)
            else:
                self._ring[self._ring_pos] = v
                self._ring_pos = (self._ring_pos + 1) % _HIST_WINDOW
            if exemplar:
                self._exemplars.append((v, dict(exemplar)))

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    @property
    def mean(self):
        return self._sum / self._count if self._count else None

    @property
    def min(self):
        return self._min

    @property
    def max(self):
        return self._max

    def percentile(self, p):
        """p in [0, 100]; nearest-rank over the recent ring."""
        with self._lock:
            vals = sorted(self._ring)
        if not vals:
            return None
        idx = max(0, min(len(vals) - 1,
                         int(round(p / 100.0 * (len(vals) - 1)))))
        return vals[idx]

    def exemplar(self):
        """The highest-valued recent exemplar as {'value': v,
        'labels': {...}}, or None — the tail sample the /metrics
        quantile line links to."""
        with self._lock:
            if not self._exemplars:
                return None
            v, labels = max(self._exemplars, key=lambda e: e[0])
        return {'value': v, 'labels': dict(labels)}

    def stats(self):
        out = {'count': self._count, 'sum': self._sum, 'mean': self.mean,
               'min': self._min, 'max': self._max,
               'p50': self.percentile(50), 'p95': self.percentile(95)}
        ex = self.exemplar()
        if ex is not None:
            out['exemplar'] = ex
        return out


class Registry:
    """Name -> metric, create-once, kind-checked."""

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get(self, name, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name)
            elif not isinstance(m, cls):
                raise TypeError('metric %r is a %s, requested as %s'
                                % (name, type(m).__name__, cls.__name__))
            return m

    def counter(self, name):
        return self._get(name, Counter)

    def gauge(self, name):
        return self._get(name, Gauge)

    def histogram(self, name):
        return self._get(name, Histogram)

    def get(self, name):
        """The metric registered under ``name``, or None."""
        return self._metrics.get(name)

    def names(self):
        return sorted(self._metrics)

    def snapshot(self):
        """Point-in-time {'counters': {...}, 'gauges': {...},
        'histograms': {name: stats-dict}} — the exporter's input."""
        with self._lock:
            items = list(self._metrics.items())
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for name, m in items:
            if isinstance(m, Counter):
                out['counters'][name] = m.value
            elif isinstance(m, Gauge):
                if m.value is not None:
                    out['gauges'][name] = m.value
            else:
                out['histograms'][name] = m.stats()
        return out

    def reset(self):
        with self._lock:
            self._metrics.clear()


class _NullCounter:
    """Shared do-nothing metric: the disabled-telemetry fast path hands
    these out so hot sites never branch beyond one enabled() check."""

    __slots__ = ()
    name = '<null>'
    value = 0.0

    def inc(self, n=1):
        pass


class _NullGauge:
    __slots__ = ()
    name = '<null>'
    value = None

    def set(self, v):
        pass


class _NullHistogram:
    __slots__ = ()
    name = '<null>'
    count = 0
    sum = 0.0
    mean = None
    min = None
    max = None

    def observe(self, v, exemplar=None):
        pass

    def percentile(self, p):
        return None

    def exemplar(self):
        return None

    def stats(self):
        return {'count': 0, 'sum': 0.0, 'mean': None, 'min': None,
                'max': None, 'p50': None, 'p95': None}


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()
