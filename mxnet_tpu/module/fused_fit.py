"""Fused multi-step fast path for Module.fit.

Reference: python/mxnet/module/base_module.py:376 runs one
forward_backward + update + update_metric per batch. On a TPU behind a
tunneled runtime each of those is a separate dispatch with ms-scale
RTT, which caps throughput regardless of chip speed (measured in
docs/perf.md: spc=1 1596 img/s vs spc=32 2552 img/s on the same
graph). This module compiles a WINDOW of W training steps into ONE
XLA computation via lax.scan — the standard in-graph-train-loop TPU
pattern — behind the unchanged Module.fit API:

- numerics are identical to the per-batch path: the same _GraphProgram
  runner, the same jax.vjp with all-ones head gradients, and the same
  registered fused update ops with the same attrs. Every optimizer
  whose update() is a single registered op is supported — SGD/ccSGD
  (incl. fp16 master weights), NAG, Adam, RMSProp (both forms), Ftrl —
  via a per-optimizer plan that mirrors its op choice, static attrs,
  state<->op-input order, and host-side lr transform (Adam's bias
  correction);
- metrics: Accuracy / TopKAccuracy / CrossEntropy (and composites of
  them) are computed from in-graph sufficient statistics — per-step
  sums packed into one vector, fetched once per window. ANY other
  metric takes the host-fallback mode: the window returns the stacked
  per-step outputs (one fetch per window) and eval_metric.update runs
  per batch on the host exactly as the reference loop would. Either
  way metric values and batch_end_callback cadence match the
  reference loop exactly (callbacks fire in a burst after each window
  — the one observable difference);
- the learning rate enters the compiled program as a traced (W, n)
  array sampled per batch on the host (no recompile when a scheduler
  moves it), so scheduler boundaries are EXACT even mid-window, and
  Adam's per-update-count bias correction is exact. Bookkeeping
  (num_update) advances per-batch as in the reference;
- grad_req='add' carries the gradient accumulators through the scan
  and writes them back, matching the reference loop's accumulate-
  without-clear semantics.

Eligibility (build() returns None → fit falls back to the reference
loop): plain Module, one executor (single context or SPMD group),
non-staged graph, grad_req 'write'/'add', an optimizer with a plan
(above; multi-precision only for SGD), and a single-process kvstore
(None/'local'/'device' — dist kvstores need per-batch push/pull).

Toggles: MXTPU_FUSED_FIT=0 disables; MXTPU_FIT_STEPS_PER_CALL sets W
(default 32 on TPU, 4 elsewhere).
"""
import logging
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import faults as _faults
from .. import metric as metric_mod
from .. import optimizer as opt_mod
from .. import profiler as _profiler
from .. import telemetry as _tele
from ..optimizer import _as_clip
from ..executor import mirror_wrap
from ..kvstore import _updater_key
from ..ndarray.ndarray import from_jax
from ..ops import registry as _reg
from .window_pipeline import (WindowPipeline, dynamics_sentinel,
                              health_sentinel, host_wrap,
                              registered_jit, window_bisect, window_size)
from .window_pipeline import plan_metric as _metric_plan

__all__ = ['FusedFitLoop']


def _window_size():
    return window_size('MXTPU_FIT_STEPS_PER_CALL')


def _shard_update_enabled():
    from ..config import flags
    flags.reload('MXTPU_SHARDED_UPDATE')
    return flags.get('MXTPU_SHARDED_UPDATE')


def _shard_update_requested():
    """True only when MXTPU_SHARDED_UPDATE is EXPLICITLY set truthy in
    the environment. The flag defaults on, so the flag-honesty warning
    below must not fire on every unconfigured single-device run — only
    when someone asked for the sharded update and is not getting it."""
    import os
    return os.environ.get('MXTPU_SHARDED_UPDATE') is not None \
        and _shard_update_enabled()


_replicated_warned = set()


def note_replicated_update(reason, site='fused_fit'):
    """Flag-honesty warning, once per (site, reason) per process:
    MXTPU_SHARDED_UPDATE was explicitly requested but the update about
    to run is REPLICATED — full optimizer state on every device. The
    sharded path engages only on the SPMD fused-fit window with dp > 1
    and the module not opted out (docs/env_vars.md)."""
    key = (site, reason)
    if key in _replicated_warned:
        return
    _replicated_warned.add(key)
    logging.warning(
        'MXTPU_SHARDED_UPDATE is set but the %s update runs REPLICATED '
        '(%s): every device materializes the full optimizer state. The '
        'sharded update (arXiv:2004.13336) engages only inside the SPMD '
        'fused-fit window with dp > 1 — see MXTPU_SHARDED_UPDATE in '
        'docs/env_vars.md', site, reason)


_compress_off_warned = set()


def _warn_compress_off(reason):
    """Flag-honesty warning, once per reason per process:
    MXTPU_GRAD_COMPRESS was set but the gradients about to move are
    UNCOMPRESSED. Quantization rides the ZeRO sharded-update path
    (the flat, dp-sharded leaf is the block layout) — see
    MXTPU_GRAD_COMPRESS in docs/env_vars.md."""
    if reason in _compress_off_warned:
        return
    _compress_off_warned.add(reason)
    logging.warning(
        'MXTPU_GRAD_COMPRESS is set but gradients run UNCOMPRESSED: '
        '%s — see MXTPU_GRAD_COMPRESS in docs/env_vars.md', reason)


def flush_sharded_states(module):
    """Materialize any optimizer-state leaves the module's cached fused
    loop holds in the ZeRO update-phase layout (flat, padded,
    dp-sharded) back to their canonical shapes. Safe no-op when there
    is no cached loop or the sharded update never engaged — callers
    (save/load_optimizer_states, checkpoint restore, the tail path)
    need the canonical layout without caring how training ran."""
    cached = module.__dict__.get('_fused_fit_cache')
    if cached is not None:
        cached[1].flush_zero_states()


def zero_shape_probe(module):
    """``probe(state_wrapper) -> canonical shape | None`` for the
    module's cached fused loop, or None when no loop holds ZeRO-layout
    state. module/checkpointing.py calls the probe on every state
    wrapper it walks: a non-None answer means the wrapper's array is
    currently in the update-phase form (flat, padded, dp-sharded) and
    the checkpoint must record the canonical shape next to it so a
    restore — possibly onto a different dp — can reshape it back."""
    cached = module.__dict__.get('_fused_fit_cache')
    if cached is None:
        return None
    loop = cached[1]
    if loop._zero is None:
        return None
    # snapshot the wrapper->shape map NOW, from the live wrappers the
    # caller is about to walk (id() keys are only valid against these
    # exact objects — see zero_wrapper_shapes)
    shapes = loop.zero_wrapper_shapes()
    if not shapes:
        return None

    def probe(wrapper):
        return shapes.get(id(wrapper))
    # the canonical NamedSharding of the layout: jit outputs carry an
    # equivalent GSPMDSharding that orbax cannot serialize (it warns
    # per leaf per save) — the checkpoint walk relabels onto this
    probe.row = loop._zero['row']
    return probe


def _compress_flag():
    from ..config import flags
    flags.reload('MXTPU_GRAD_COMPRESS')
    return flags.get('MXTPU_GRAD_COMPRESS')


def _compress_block():
    from ..config import flags
    flags.reload('MXTPU_GRAD_COMPRESS_BLOCK')
    return int(flags.get('MXTPU_GRAD_COMPRESS_BLOCK'))


def _mirror_flag():
    from ..config import flags
    flags.reload('MXTPU_BACKWARD_DO_MIRROR')
    return flags.get('MXTPU_BACKWARD_DO_MIRROR')


def _donate_flag():
    from ..config import flags
    flags.reload('MXTPU_FUSED_DONATE')
    return flags.get('MXTPU_FUSED_DONATE')


def _remat_policy():
    from ..config import flags
    flags.reload('MXTPU_REMAT_POLICY')
    return flags.get('MXTPU_REMAT_POLICY')


def _bn_onepass_flag():
    from ..ops.nn import _bn_onepass
    return bool(_bn_onepass())


def _remat_wrap(f):
    """Per-step remat for the window body: MXTPU_REMAT_POLICY
    (none/dots/full) is the roofline block's memory-bound lever,
    scoped to the fused window; empty defers to the process-wide
    MXTPU_BACKWARD_DO_MIRROR via executor.mirror_wrap exactly as
    before (so existing mirror configurations lower unchanged)."""
    policy = _remat_policy()
    if policy == '':
        return mirror_wrap(f)
    if policy == 'none':
        return f
    if policy == 'dots':
        return jax.checkpoint(
            f,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    return jax.checkpoint(f)


def _install_donate_filter():
    """The window deliberately donates its input/label stacks for their
    LIFETIME (freed at last in-program use, so window k's and k+1's
    stacks are never both live under the prefetch pipeline) even though
    no output aliases them — jax warns 'Some donated buffers were not
    usable' for exactly that shape of donation, once per compile.
    Filter that one message; every other donation diagnostic stays.
    Installed at every donated window BUILD (not once per process):
    test harnesses save/restore the warnings filter list around each
    case, and a once-guard would leave later builds unfiltered. The
    presence check keeps a long-lived process that rebuilds windows
    many times from growing warnings.filters unboundedly."""
    import warnings
    msg = 'Some donated buffers were not usable'
    for f in warnings.filters:
        if f[0] == 'ignore' and getattr(f[1], 'pattern', None) == msg:
            return
    warnings.filterwarnings('ignore', message=msg)


def _is_half(dt):
    return str(dt) in ('float16', 'bfloat16')


def updater_obj(module):
    """The updater that holds this module's optimizer state (the
    kvstore's when update_on_kvstore, the module's local one
    otherwise)."""
    return module._kvstore._updater if module._update_on_kvstore \
        else module._updater


def updater_keys(module, grad_names):
    """The key each param updates under, matching the unfused path:
    update_on_kvstore pushes by NAME (kvstore._updater keys); the
    local updater uses integer position (model._update_params)."""
    if module._update_on_kvstore:
        return {n: _updater_key(n) for n in grad_names}
    pnames = module._exec_group.param_names
    return {n: pnames.index(n) for n in grad_names}


def _walk_state_wrappers(st):
    """The NDArray state wrappers inside one optimizer-state entry, in
    the same traversal order module/checkpointing._walk_opt uses."""
    if st is None:
        return []
    if isinstance(st, tuple):
        out = []
        for s in st:
            out.extend(_walk_state_wrappers(s))
        return out
    return [st]


def ensure_opt_states(module, grad_names, upd_keys, arg_dict):
    """Pre-create optimizer states through the optimizer's own
    create_state path (the lazy per-batch loop only builds them at the
    first update) so every caller — the fused window, checkpointing,
    save/load_optimizer_states — sees the same structure. Returns the
    updater."""
    upd = updater_obj(module)
    for n in grad_names:
        key = upd_keys[n]
        if key not in upd.states:
            upd.states[key] = \
                module._optimizer.create_state_multi_precision(
                    key, arg_dict[n])
            upd.states_synced[key] = True
    return upd


# ---------------------------------------------------------------------------
# optimizer plans: one registered fused update op per optimizer
# ---------------------------------------------------------------------------

class _OptPlan:
    """Expresses one optimizer's update() as its registered fused op
    inside the scan body, mirroring the NDArray path exactly: op
    choice, static attrs, host-side lr transform (e.g. Adam's bias
    correction), and the state<->op-input-order mapping. All fused
    update ops return (new_weight, *new_states) with states in input
    order, so application in the scan body is generic."""

    supports_mp = False

    def __init__(self, opt):
        self.opt = opt

    _clip = staticmethod(_as_clip)   # None → -1.0 sentinel, shared
    # with the imperative updaters so the convention lives in one place

    def lr_wd(self, index):
        """(lr, wd) the updater would use for the CURRENT update count
        of `index` (call right after _update_count, like update())."""
        return self.opt._get_lr(index), self.opt._get_wd(index)

    def state_arrays(self, st):
        """Optimizer state -> jax arrays in the op's input order."""
        if st is None:
            return []
        if isinstance(st, tuple):
            return [s._data for s in st]
        return [st._data]

    def writeback_state(self, st, arrays):
        if st is None:
            return
        if isinstance(st, tuple):
            for s, a in zip(st, arrays):
                s._data = a
        else:
            st._data = arrays[0]


class _SGDPlan(_OptPlan):
    supports_mp = True

    def mode(self, weight_dtype):
        """Mirrors SGD.update_multi_precision's op choice."""
        mp = self.opt.multi_precision and _is_half(weight_dtype)
        mom = self.opt.momentum != 0.0
        return ('mp_' if mp else '') + ('sgd_mom_update' if mom
                                        else 'sgd_update')

    def static_attrs(self):
        o = self.opt
        return {'momentum': o.momentum, 'rescale_grad': o.rescale_grad,
                'clip_gradient': self._clip(o.clip_gradient)}

    def state_arrays(self, st):
        if isinstance(st, tuple):           # multi-precision (w32, mom)
            w32, mom = st
            if mom is None:
                return [w32._data]          # mp_sgd_update(..., weight32)
            return [mom._data, w32._data]   # mp_sgd_mom_update(.., mom, w32)
        return [st._data] if st is not None else []

    def writeback_state(self, st, arrays):
        if isinstance(st, tuple):
            w32, mom = st
            if mom is None:
                w32._data = arrays[0]
            else:
                mom._data = arrays[0]
                w32._data = arrays[1]
        elif st is not None:
            st._data = arrays[0]


class _NAGPlan(_SGDPlan):
    supports_mp = False

    def mode(self, weight_dtype):
        return ('nag_mom_update' if self.opt.momentum != 0.0
                else 'sgd_update')


class _AdamPlan(_OptPlan):
    def mode(self, weight_dtype):
        return 'adam_update'

    def static_attrs(self):
        o = self.opt
        return {'beta1': o.beta1, 'beta2': o.beta2, 'epsilon': o.epsilon,
                'rescale_grad': o.rescale_grad,
                'clip_gradient': self._clip(o.clip_gradient)}

    def lr_wd(self, index):
        """Adam.update's per-update-count bias correction, folded into
        the per-batch lr row on the host."""
        import math
        o = self.opt
        lr, wd = o._get_lr(index), o._get_wd(index)
        t = o._index_update_count[index]
        lr *= math.sqrt(1. - o.beta2 ** t) / (1. - o.beta1 ** t)
        return lr, wd


class _RMSPropPlan(_OptPlan):
    def mode(self, weight_dtype):
        return ('rmspropalex_update' if self.opt.centered
                else 'rmsprop_update')

    def static_attrs(self):
        o = self.opt
        attrs = {'gamma1': o.gamma1, 'epsilon': o.epsilon,
                 'rescale_grad': o.rescale_grad,
                 'clip_gradient': self._clip(o.clip_gradient),
                 'clip_weights': self._clip(o.clip_weights)}
        if o.centered:
            attrs['gamma2'] = o.gamma2
        return attrs


class _FtrlPlan(_OptPlan):
    def mode(self, weight_dtype):
        return 'ftrl_update'

    def static_attrs(self):
        o = self.opt
        return {'lamda1': o.lamda1, 'beta': o.beta,
                'rescale_grad': o.rescale_grad,
                'clip_gradient': self._clip(o.clip_gradient)}


def _opt_plan(opt):
    """Plan for this optimizer type, or None (→ reference loop).
    Exact-type dispatch: a user subclass with an overridden update()
    must not silently take the base class's fused form."""
    table = {opt_mod.SGD: _SGDPlan, opt_mod.ccSGD: _SGDPlan,
             opt_mod.NAG: _NAGPlan, opt_mod.Adam: _AdamPlan,
             opt_mod.RMSProp: _RMSPropPlan, opt_mod.Ftrl: _FtrlPlan}
    cls = table.get(type(opt))
    return cls(opt) if cls is not None else None


# metric plans (in-graph sufficient statistics) live in
# window_pipeline.plan_metric — shared with the fused eval loop.


class FusedFitLoop:
    """One compiled W-step train window driving Module's state."""

    def __init__(self, module, children, stat_fns, window, oplan):
        self.module = module
        self.children = children
        self.stat_fns = stat_fns
        self.window = window
        self._programs = {}
        import weakref
        self._defer_fns = weakref.WeakKeyDictionary()

        e = module._exec_group.execs[0]
        self._exec = e
        self._run = e._run_eager
        # program-registrar name for this module's compiled windows
        from ..telemetry.programs import scope_name
        self._prog_name = 'fused_fit.window[%s]' % scope_name(
            getattr(module._symbol, 'name', None) or 'graph')
        self._arg_names = list(e._prog.arg_names)
        self._aux_names = list(e._prog.aux_names)
        self._grad_names = list(e._grad_names)
        io_names = set(module._data_names) | set(module._label_names)
        self._carry_names = [n for n in self._arg_names if n not in io_names]
        self._carry_pos = {n: i for i, n in enumerate(self._carry_names)}
        self._optimizer = module._optimizer
        self._plan = oplan  # the instance build() validated eligibility on
        self._accum = (module._grad_req == 'add')
        # SPMD group: every carried array must live replicated on the
        # mesh and batch stacks sharded over dp, or jit rejects the
        # mixed-device argument set
        from .executor_group import SPMDExecutorGroup
        self._mesh = module._exec_group.mesh \
            if isinstance(module._exec_group, SPMDExecutorGroup) else None
        # the shared draw/stack/upload machinery (module/window_pipeline)
        self._pipe = WindowPipeline(window,
                                    device_fn=lambda: e._ctx.jax_device(),
                                    mesh=self._mesh,
                                    span_prefix='fused_fit',
                                    donate=bool(_donate_flag()))
        # training-health sentinels: captured at loop build (build_cached
        # keys reuse on the flag) — None keeps the traced window
        # byte-identical to the plain form
        self._health_fn = health_sentinel()
        # per-layer training dynamics (telemetry/dynamics): same
        # contract — captured at build, traced into the window, rides
        # the existing single fetch; None = byte-identical program
        self._dyn_fn = dynamics_sentinel()
        self._out_names = list(module._symbol.list_outputs())
        self._last_lr = None   # last sampled lr (run-ledger scalars)
        self._upd_keys = updater_keys(module, self._grad_names)
        self._ensure_states()
        # ZeRO-style sharded weight update (arXiv:2004.13336): on an
        # SPMD group with dp > 1, optimizer state lives in the
        # update-phase form — every leaf flat, zero-padded to a
        # multiple of dp, row-sharded over the dp axis — persistently
        # across windows (donated in place through the scan carry), so
        # per-device optimizer/master-param memory drops by ~dp x.
        # Inside the window body: reduce-scatter(grads) -> shard-local
        # update -> all-gather(params). self._zero is None on the
        # documented fallback (flag off, dp == 1, no mesh, or the
        # module opted out via `module.sharded_update = False`) — the
        # replicated update then lowers byte-identically to the
        # pre-sharding program.
        self._zero = None
        self._update_gauged = False
        dp = int(self._mesh.shape['dp']) if self._mesh is not None else 1
        if _shard_update_enabled() and getattr(module, 'sharded_update',
                                               True) and dp > 1:
            from .executor_group import SPMDExecutorGroup
            self._zero = {'dp': dp,
                          'row': SPMDExecutorGroup.update_sharding(
                              self._mesh)}
            # canonical (pre-flatten) shape/dtype per state leaf, in
            # state_arrays (op-input) order — the snapshot/flush paths
            # and the per-device-bytes gauge key on it
            self._zero_shapes = {
                n: [(tuple(a.shape), a.dtype)
                    for a in self._state_arrays(n)]
                for n in self._grad_names}
            # ...and in raw-tuple WALK order (differs from the op-input
            # order for multi-precision plans): the checkpoint walk
            # traverses the raw state tuples and maps canonical shapes
            # per wrapper (zero_wrapper_shapes) — keyed name+position
            # so it survives wrapper replacement (set_states /
            # load_optimizer_states)
            upd = self._updater_obj()
            self._zero_walk_shapes = {
                n: [tuple(w._data.shape) for w in _walk_state_wrappers(
                    upd.states[self._upd_keys[n]])]
                for n in self._grad_names}
        elif _shard_update_requested():
            note_replicated_update(
                'module opted out (sharded_update=False)'
                if self._mesh is not None and dp > 1
                else 'no SPMD mesh / dp axis is 1')
        # Quantized gradient collectives (MXTPU_GRAD_COMPRESS): the
        # error-feedback residuals live here between windows — one flat
        # leaf per grad in the ZeRO update-phase layout, donated
        # through the scan carry like opt-state leaves. Loop-local on
        # purpose: a restart resets the residual to zero, which costs
        # one step of quantization error and nothing else (documented
        # in docs/perf.md), so the checkpoint format is untouched.
        self._resid = None
        self._resid_meta = None
        # per-run flip bookkeeping: last window's resolved mode + wall
        # ms, and whether the one-shot 'compression' record fired
        self._cstate = {'mode': None, 'ms': None, 'emitted': False,
                        'windows': 0}
        if _compress_flag() != 'off' and self._zero is None:
            _warn_compress_off(
                'no ZeRO sharded update engaged (the flat dp-sharded '
                'leaf form is the quantization block layout)')

    # -- reuse across fit() calls ------------------------------------------
    @staticmethod
    def _metric_sig(eval_metric):
        """Value signature of the metric configuration (class + every
        distinguishing kwarg: axis/top_k/eps/... all flow through
        EvalMetric._kwargs into get_config). None = unsignable, never
        reuse."""
        if isinstance(eval_metric, metric_mod.CompositeEvalMetric):
            leaves = list(eval_metric.metrics)
        else:
            leaves = [eval_metric]
        try:
            return repr([sorted(m.get_config().items(), key=str)
                         for m in leaves])
        except Exception:  # noqa: BLE001 — custom metric w/o get_config
            return None

    def _rebind_metric(self, eval_metric):
        from .window_pipeline import rebind_children
        self.children = rebind_children(eval_metric, self.children)

    @classmethod
    def build_cached(cls, module, eval_metric, logger=logging):
        """build(), but reuse the previous fit() call's loop — with its
        compiled window programs — when everything the traced window
        depends on is unchanged: same bound executor, same optimizer
        instance, grad_req, kvstore mode, window size, remat/sharding
        flags, and an equal-config metric.

        An epoch-at-a-time driver (fit(begin_epoch=e, num_epoch=e+1)
        in a loop — the resume / eval-between-epochs pattern) otherwise
        pays a full retrace + XLA recompile of the window EVERY call:
        measured ~20-40 s per compile on the tunneled chip vs ~2 s of
        compute per 64-batch ImageNet epoch, the 49.8 img/s pathology
        of docs/tpu_artifacts/fed_modulefit_20260802T061223Z."""
        from ..config import flags
        flags.reload('MXTPU_FUSED_FIT')
        if not flags.get('MXTPU_FUSED_FIT'):
            # a discarded loop may hold ZeRO-layout optimizer state —
            # materialize it before the reference loop reads it
            flush_sharded_states(module)
            module.__dict__.pop('_fused_fit_cache', None)
            return None
        eg = getattr(module, '_exec_group', None)
        execs = getattr(eg, 'execs', None) or []
        sig = None
        if len(execs) == 1 and execs[0]._monitor is None \
                and not execs[0]._use_staged():
            # a monitor installed (or staging forced) between fit()
            # calls must invalidate reuse the same way build() rejects
            # it — the per-batch reference loop is the one that honors
            # monitor callbacks
            msig = cls._metric_sig(eval_metric)
            if msig is not None:
                sig = (id(execs[0]), id(module._optimizer),
                       module._grad_req,
                       bool(module._update_on_kvstore),
                       getattr(module._kvstore, 'type', None),
                       _window_size(), bool(_shard_update_enabled()),
                       bool(getattr(module, 'sharded_update', True)),
                       # the compression FLAG + block (not the auto-
                       # resolved mode: an auto flip mid-run is handled
                       # by the per-window program key, not a rebuild)
                       str(_compress_flag()), _compress_block(),
                       str(_mirror_flag()), str(_remat_policy()),
                       bool(_donate_flag()),
                       # BatchNorm's stats form is traced INTO the
                       # window — flipping MXTPU_BN_ONEPASS between
                       # fit() calls must rebuild the loop (a cached
                       # program would silently keep the old math)
                       _bn_onepass_flag(), msig,
                       # the health sentinels are traced INTO the window
                       # program — flipping MXTPU_HEALTH between fit()
                       # calls must rebuild the loop
                       bool(_tele.health.enabled()),
                       # ...and so is the per-layer dynamics matrix
                       bool(_tele.dynamics.enabled()))
        cached = module.__dict__.get('_fused_fit_cache')
        if cached is not None and sig is not None and cached[0] == sig:
            loop = cached[1]
            loop._rebind_metric(eval_metric)
            return loop
        loop = cls.build(module, eval_metric, logger=logger)
        if loop is None:
            # falling back to the reference per-batch loop: it updates
            # against the canonical state layout
            flush_sharded_states(module)
        if loop is not None and sig is not None:
            module.__dict__['_fused_fit_cache'] = (sig, loop)
        else:
            module.__dict__.pop('_fused_fit_cache', None)
        return loop

    # -- eligibility -------------------------------------------------------
    @staticmethod
    def build(module, eval_metric, logger=logging):
        from ..config import flags
        flags.reload('MXTPU_FUSED_FIT')
        if not flags.get('MXTPU_FUSED_FIT'):
            return None
        from .module import Module
        if type(module) is not Module:
            return None
        eg = module._exec_group
        if len(getattr(eg, 'execs', ())) != 1:
            return None
        e = eg.execs[0]
        if e._use_staged() or e._monitor is not None:
            return None
        if module._grad_req not in ('write', 'add') \
                or module.inputs_need_grad:
            return None
        opt = module._optimizer
        oplan = _opt_plan(opt)
        if oplan is None:
            return None
        if not oplan.supports_mp and opt.multi_precision and any(
                _is_half(e.arg_dict[n]._data.dtype) for n in e._grad_names):
            return None  # mp master-weight form only planned for SGD
        kv = module._kvstore
        if kv is not None and kv.type not in ('local', 'device'):
            return None
        shapes = {d.name: d.shape for d in
                  list(module.data_shapes) + list(module.label_shapes or [])}
        try:
            _, out_shapes, _ = module._symbol.infer_shape(**shapes)
        except Exception:  # noqa: BLE001 — undecidable shapes: fall back
            return None
        if out_shapes is None:
            return None
        window = _window_size()
        # plan_metric also enforces the stat fns' output/label geometry;
        # other geometries use the host-fallback mode below
        plan = _metric_plan(eval_metric, out_shapes, module._label_names)
        if plan is not None:
            children, fns = plan
        else:
            # host-fallback metric mode: the window ships the stacked
            # per-step outputs (one fetch per window) and the metric's
            # own update() runs per batch on the host. Bounded: W
            # stacked fp32 outputs must stay under a device-memory cap.
            est = 4 * window * sum(
                int(np.prod(s)) for s in out_shapes if s)
            if est > 256 * 1024 * 1024:
                return None
            children, fns = None, None
        # a previously-cached loop (about to be replaced) may hold the
        # optimizer state in the ZeRO layout: the new loop must read
        # CANONICAL shapes at construction
        flush_sharded_states(module)
        loop = FusedFitLoop(module, children, fns, window, oplan)
        logger.info('fused fit fast path active: %d steps/device-call%s',
                    loop.window,
                    '' if fns is not None else ' (host-metric mode)')
        return loop

    # -- optimizer state ---------------------------------------------------
    def _updater_obj(self):
        return updater_obj(self.module)

    def _ensure_states(self):
        ensure_opt_states(self.module, self._grad_names, self._upd_keys,
                          self._exec.arg_dict)

    def _state_arrays(self, n):
        st = self._updater_obj().states[self._upd_keys[n]]
        return self._plan.state_arrays(st)

    def _writeback_state(self, n, arrays):
        st = self._updater_obj().states[self._upd_keys[n]]
        self._plan.writeback_state(st, arrays)

    # -- program -----------------------------------------------------------
    def _static_attrs(self):
        """Optimizer-wide attrs that never change across windows (lr/wd
        are dynamic: they enter the compiled program as traced arrays
        so a per-update lr scheduler never forces a recompile)."""
        return self._plan.static_attrs()

    def _sample_window_lr(self):
        """Advance the optimizer's update bookkeeping batch-by-batch
        (exactly as the reference loop's per-batch update() calls
        would) and return (W, n_params) lr/wd arrays holding the value
        the updater would use for EACH batch of the window — scheduler
        boundaries and per-update-count transforms (Adam) are exact
        even mid-window."""
        o = self._optimizer
        n = len(self._grad_names)
        lr = np.empty((self.window, n), np.float32)
        wd = np.empty((self.window, n), np.float32)
        for w in range(self.window):
            for j, name in enumerate(self._grad_names):
                idx = self._upd_keys[name]
                o._update_count(idx)
                lr[w, j], wd[w, j] = self._plan.lr_wd(idx)
        if n:
            self._last_lr = float(lr[-1, 0])
        return lr, wd

    def _mode(self, n):
        """Update-op choice per param, delegated to the optimizer plan."""
        return self._plan.mode(self._exec.arg_dict[n]._data.dtype)

    def _cmode(self):
        """Resolved gradient-compression mode for the NEXT window:
        'off'/'int8'/'bf16'. 'auto' resolves against the cluster
        verdict state (parallel/compression.py), so a sync round that
        classifies the run communication_bound flips this mid-run —
        the mode is part of the per-window program key, so the flip
        rebuilds the window program at the next dispatch. Pinned to
        'off' (warn-once) when the ZeRO update path is not engaged:
        the flat dp-sharded leaf IS the quantization block layout."""
        from ..parallel import compression
        mode = compression.resolved_mode()
        if mode != 'off' and self._zero is None:
            _warn_compress_off(
                'no ZeRO sharded update engaged (the flat dp-sharded '
                'leaf form is the quantization block layout)')
            return 'off'
        return mode

    def _build_program(self, static_attrs, shapes_key, cmode=None):
        run = self._run
        arg_pos = {n: i for i, n in enumerate(self._arg_names)}
        data_names = list(self.module._data_names)
        label_names = list(self.module._label_names)
        carry_names = self._carry_names
        grad_names = self._grad_names
        grad_carry_idx = [self._carry_pos[n] for n in grad_names]
        modes = {n: self._mode(n) for n in grad_names}
        ops = {mode: _reg.get(mode) for mode in set(modes.values())}
        stat_fns = self.stat_fns
        health_fn = self._health_fn
        dyn_fn = self._dyn_fn
        accum = self._accum
        W = self.window
        mesh = self._mesh
        defer_fn = self._defer_fn   # traced INTO the program (or None)
        donate = _donate_flag()
        rep_pin = None
        if mesh is not None:
            # tiny whole-mesh operands (the s32 step-index vector, the
            # per-step lr/wd rows) get an explicit replicated pin: left
            # unannotated, GSPMD re-derives their placement per use and
            # prints an '[spmd] Involuntary full rematerialization'
            # stderr warning for each (the PR 9 known residue)
            from .executor_group import SPMDExecutorGroup
            rep_pin = SPMDExecutorGroup.replicate_sharding(mesh)
        shard_update = self._zero is not None
        cmode = self._cmode() if cmode is None else cmode
        compress = shard_update and cmode != 'off'
        if compress:
            # error-feedback quantization of the update-form gradient
            # (parallel/compression.py): the numerics of the EQuARX
            # recipe, applied inside the jitted window; the residual
            # rides the scan carry next to the opt-state leaves
            from ..parallel import compression as _compr
            cblock = _compress_block()
        if shard_update:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..parallel.sharding import zero_flatten, zero_unflatten
            dp = self._zero['dp']
            row = self._zero['row']
            rep = NamedSharding(mesh, P())

            def to_update_form(t):
                """Weight/grad -> the update-phase form: flat, zero-
                padded to a multiple of dp, row-sharded (every leaf
                divides, whatever its shape — the per-leaf padding of
                arXiv:2004.13336). Constraining the GRADIENT here turns
                its all-reduce into a reduce-scatter: each replica
                receives — and updates — only its 1/dp slice."""
                return jax.lax.with_sharding_constraint(
                    zero_flatten(t, dp), row)

            def from_update_form(t, shape):
                """Fresh weight -> canonical shape, replicated: the
                all-gather that hands the next forward a whole param."""
                return jax.lax.with_sharding_constraint(
                    zero_unflatten(t, shape), rep)

            def pin_state(t):
                # optimizer states arrive AND leave in the update-phase
                # form: pinning both body entry and exit keeps the scan
                # carry's sharding in equilibrium (no per-iteration
                # reshard) and the jit outputs dp-sharded — the ZeRO
                # layout the loop holds between windows
                return jax.lax.with_sharding_constraint(t, row)

        def make_body(key):
            def body(carry, xs):
                if compress:
                    params, states, aux, gaccs, resids = carry
                    new_resids = list(resids)
                else:
                    params, states, aux, gaccs = carry
                step_i, datas, labels, lr_row, wd_row = xs
                k = jax.random.fold_in(key, step_i)
                if defer_fn is not None:
                    # deferred device-augment: raw uint8 batch -> the
                    # graph's float input, inside THIS program (zero
                    # per-batch dispatches; iterator's eager mode runs
                    # the identical math per batch)
                    ka = jax.random.fold_in(k, 0x41554721)
                    datas = (defer_fn(datas[0], ka),) + tuple(datas[1:])

                def f(wrt):
                    full = [None] * len(arg_pos)
                    for n, v in zip(carry_names, params):
                        full[arg_pos[n]] = v
                    for n, v in zip(data_names, datas):
                        full[arg_pos[n]] = v
                    for n, v in zip(label_names, labels):
                        full[arg_pos[n]] = v
                    for n, v in zip(grad_names, wrt):
                        full[arg_pos[n]] = v
                    return run(tuple(full), aux, k, True)

                wrt = tuple(params[i] for i in grad_carry_idx)
                (outs, new_aux), vjp = jax.vjp(_remat_wrap(f), wrt)
                heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
                zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
                (grads,) = vjp((heads, zero_aux))
                if accum:
                    # grad_req='add': the reference loop accumulates
                    # into grad buffers and never clears them
                    grads = tuple(ga + g for ga, g in zip(gaccs, grads))
                    gaccs = grads

                new_params = list(params)
                new_states = list(states)
                for j, n in enumerate(grad_names):
                    ci = grad_carry_idx[j]
                    attrs = dict(static_attrs)
                    attrs['lr'] = lr_row[j]   # traced: scheduler-safe
                    attrs['wd'] = wd_row[j]
                    w, g = params[ci], grads[j]
                    st = states[j]
                    if shard_update:
                        w_shape = w.shape
                        w, g = to_update_form(w), to_update_form(g)
                        st = tuple(pin_state(s) for s in st)
                    if compress:
                        # quantize -> dequantize the reduced gradient
                        # with error feedback: the dropped precision of
                        # this step re-enters at the next via the
                        # carried residual (convergence gated by the
                        # chaos-lane run_compare e2e, never assumed)
                        g, nr = _compr.ef_roundtrip(g, resids[j], cmode,
                                                    cblock)
                        g = pin_state(g)
                        new_resids[j] = pin_state(nr)
                    # every fused update op returns (w, *states) with
                    # states in input order — application is generic
                    res = ops[modes[n]].fn(attrs, w, g, *st)
                    if not isinstance(res, tuple):
                        res = (res,)
                    # the traced lr/wd scalars are strong f32 where the
                    # imperative path feeds weak python floats: without
                    # this cast a bf16 weight/state promotes to f32 in
                    # the update and the scan carry rejects the dtype
                    # drift (found by the bf16 BN parity tests)
                    ins = (w,) + tuple(st)
                    res = tuple(r.astype(i.dtype)
                                if r.dtype != i.dtype else r
                                for r, i in zip(res, ins))
                    if shard_update:
                        # only the WEIGHT re-gathers (the next forward
                        # needs it whole); optimizer states stay flat +
                        # dp-sharded through the scan carry and out of
                        # the program — the ZeRO layout
                        res = (from_update_form(res[0], w_shape),) + \
                            tuple(pin_state(s) for s in res[1:])
                    new_params[ci] = res[0]
                    if len(res) > 1:
                        new_states[j] = tuple(res[1:])
                if stat_fns is not None:
                    # all metric stats packed into ONE vector per step
                    # so the host needs a single fetch per window (each
                    # fetch through a tunneled runtime costs a full RTT)
                    ys = jnp.stack([v for fn in stat_fns
                                    for v in fn(outs, labels)])
                else:
                    # host-fallback metric: ship the raw outputs; scan
                    # stacks them into (W, ...) per output
                    ys = outs
                extras = []
                if health_fn is not None:
                    # per-step sentinel vector rides the scan ys — the
                    # (W, k) stack comes home in the window's existing
                    # fetch, so a mid-window NaN keeps its step index
                    extras.append(health_fn(
                        outs, grads=grads,
                        params=tuple(params[i] for i in grad_carry_idx),
                        new_params=tuple(new_params[i]
                                         for i in grad_carry_idx)))
                if dyn_fn is not None:
                    # per-layer dynamics vector rides the same ys — the
                    # (W, 3n+outs) matrix ships in the SAME single
                    # fetch (no added syncs; counter-asserted in tests)
                    extras.append(dyn_fn(
                        outs, grads=grads,
                        params=tuple(params[i] for i in grad_carry_idx),
                        new_params=tuple(new_params[i]
                                         for i in grad_carry_idx)))
                if extras:
                    ys = (ys, *extras)
                if compress:
                    return (tuple(new_params), tuple(new_states),
                            new_aux, gaccs, tuple(new_resids)), ys
                return (tuple(new_params), tuple(new_states), new_aux,
                        gaccs), ys
            return body

        def make_xs(lr_arr, wd_arr):
            step_idx = jnp.arange(W)
            lr_xs = jnp.asarray(lr_arr)
            wd_xs = jnp.asarray(wd_arr)
            if rep_pin is not None:
                step_idx = jax.lax.with_sharding_constraint(step_idx,
                                                            rep_pin)
                lr_xs = jax.lax.with_sharding_constraint(lr_xs, rep_pin)
                wd_xs = jax.lax.with_sharding_constraint(wd_xs, rep_pin)
            return step_idx, lr_xs, wd_xs

        if compress:
            # the residual tuple is an extra carry member right after
            # gaccs — donated like the other carry leaves, returned in
            # the ZeRO layout for the loop to hold between windows
            def window_fn(params, states, aux, gaccs, resids, data_stack,
                          label_stack, key, lr_arr, wd_arr):
                step_idx, lr_xs, wd_xs = make_xs(lr_arr, wd_arr)
                (p, s, a, g, r), ys = jax.lax.scan(
                    make_body(key), (params, states, aux, gaccs, resids),
                    (step_idx, data_stack, label_stack, lr_xs, wd_xs))
                return p, s, a, g, r, ys
        else:
            def window_fn(params, states, aux, gaccs, data_stack,
                          label_stack, key, lr_arr, wd_arr):
                step_idx, lr_xs, wd_xs = make_xs(lr_arr, wd_arr)
                (p, s, a, g), ys = jax.lax.scan(
                    make_body(key), (params, states, aux, gaccs),
                    (step_idx, data_stack, label_stack, lr_xs, wd_xs))
                return p, s, a, g, ys

        # the train-step program of the fused path: its XLA cost
        # analysis (scan body counted once = per-step FLOPs) feeds the
        # framework-computed MFU through the registrar. Donation
        # (MXTPU_FUSED_DONATE): the param/state/aux/gacc carry aliases
        # in place onto the matching outputs, and the input/label
        # stacks are donated for their lifetime — the runtime frees
        # them at their last in-program use, so the prefetched next
        # window's stacks never coexist with this window's. =0 builds
        # the undonated reference program (bit-exact numerics, parity-
        # tested) for A/B evidence.
        if donate:
            _install_donate_filter()
        if compress:
            donate_idx = (0, 1, 2, 3, 4, 5, 6) if donate else ()
        else:
            donate_idx = (0, 1, 2, 3, 4, 5) if donate else ()
        return registered_jit(
            self._prog_name, window_fn, step_flops=True,
            donate_argnums=donate_idx)

    # -- ZeRO state layout -------------------------------------------------
    def zero_wrapper_shapes(self):
        """{id(state wrapper): canonical shape} for the leaves CURRENTLY
        in the update-phase form, built FRESH from the live updater
        walk on every call: wrapper objects can be replaced under the
        loop (set_states / load_optimizer_states) and CPython recycles
        id() values, so this map must never be cached across calls —
        the checkpoint walk builds it immediately before traversing
        the very same wrappers."""
        if self._zero is None:
            return {}
        from .window_pipeline import is_update_sharded
        row = self._zero['row']
        out = {}
        upd = self._updater_obj()
        for n in self._grad_names:
            ws = _walk_state_wrappers(upd.states[self._upd_keys[n]])
            for w, shape in zip(ws, self._zero_walk_shapes[n]):
                if is_update_sharded(getattr(w, '_data', None), row):
                    out[id(w)] = shape
        return out

    def flush_zero_states(self):
        """Materialize every state leaf held in the ZeRO update-phase
        form back to its canonical shape, replicated on the mesh.
        Runs before anything OUTSIDE the compiled window consumes the
        states — the per-batch tail path, save/load_optimizer_states,
        a checkpoint restore. The next window re-shards lazily
        (place_update_sharded passes converted leaves through), so the
        cost is one gather per excursion, not per window."""
        if self._zero is None:
            return
        from .window_pipeline import is_update_sharded
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.sharding import zero_unflatten
        row = self._zero['row']
        rep = NamedSharding(self._mesh, P())
        for n in self._grad_names:
            arrays = self._state_arrays(n)
            out, changed = [], False
            for a, (shape, _d) in zip(arrays, self._zero_shapes[n]):
                if is_update_sharded(a, row):
                    a = jax.device_put(zero_unflatten(a, shape), rep)
                    changed = True
                out.append(a)
            if changed:
                self._writeback_state(n, out)
        # the gauges must flip AS A PAIR: a flush back to the
        # replicated layout also restores the replicated footprint
        # (a 'replicated' bit next to the 1/dp byte count would be a
        # self-contradictory record)
        _tele.gauge('update.sharded').set(0)
        _tele.gauge('update.opt_state_bytes_per_device').set(int(sum(
            int(np.prod(shape)) * np.dtype(dt).itemsize
            for n in self._grad_names
            for shape, dt in self._zero_shapes[n])))

    def _prepare_tail(self):
        """Restore the per-batch update invariant before tail batches
        run the imperative path: the kvstore machinery keeps its
        update-side arrays (store weights, updater states) on the
        CONTEXT device — its reduce lands merged grads there — while
        the window writeback leaves everything mesh-placed. Only the
        SPMD path needs this; everywhere else the context device IS the
        placement. The next epoch's first window re-shards lazily."""
        if self._mesh is None:
            return
        self.flush_zero_states()
        m = self.module
        if not m._update_on_kvstore:
            # local-updater tail: weights/grads/states all live mesh-
            # replicated (arg_dict pinned at forward, grads from the
            # SPMD backward, states from the window writeback or the
            # flush above) — already co-located
            return
        dev = self._exec._ctx.jax_device()
        upd = self._updater_obj()
        for n in self._grad_names:
            store = m._kvstore._store.get(n)
            if store is not None:
                store._data = jax.device_put(store._data, dev)
            for w in _walk_state_wrappers(upd.states[self._upd_keys[n]]):
                w._data = jax.device_put(w._data, dev)

    def _note_update_gauges(self):
        """Publish the per-device optimizer-state footprint: with the
        sharded update on, the ZeRO layout's exact ceil(n/dp)/device
        bytes; otherwise the full replicated bytes — so a sharded-vs-
        replicated A/B reads the win off one gauge. Published at every
        snapshot (pure shape arithmetic, no device access) so the pair
        of gauges tracks every layout transition — a tail flush zeroes
        them and the next window's re-shard must flip them back."""
        if self._zero is not None:
            from ..parallel.sharding import zero_sharded_bytes
            total = sum(zero_sharded_bytes(shape, dt, self._zero['dp'])
                        for n in self._grad_names
                        for shape, dt in self._zero_shapes[n])
            _tele.gauge('update.sharded').set(1)
            _tele.gauge('update.dp').set(self._zero['dp'])
        elif self._update_gauged:
            return   # replicated layout never transitions
        else:
            total = sum(int(a.nbytes) for n in self._grad_names
                        for a in self._state_arrays(n))
            _tele.gauge('update.sharded').set(0)
        self._update_gauged = True
        _tele.gauge('update.opt_state_bytes_per_device').set(int(total))

    # -- quantized gradient collectives ------------------------------------
    def _resid_specs(self):
        """(name, padded flat length, dtype) per grad leaf in the ZeRO
        update-phase layout — the residual shapes AND the wire-byte
        model's element counts."""
        if self._resid_meta is None:
            from ..parallel.sharding import zero_pad_len
            dp = self._zero['dp']
            meta = []
            for n in self._grad_names:
                a = self._exec.arg_dict[n]._data
                size = int(np.prod(a.shape)) if a.shape else 1
                meta.append((n, zero_pad_len(size, dp), np.dtype(a.dtype)))
            self._resid_meta = meta
        return self._resid_meta

    def _ensure_resids(self):
        """Error-feedback residuals in grad_names order: zeros on first
        use (or after a shape change), row-sharded like the opt-state
        leaves, then carried window to window via the donated call."""
        if self._resid is None:
            self._resid = {}
        row = self._zero['row']
        out = []
        for n, L, dt in self._resid_specs():
            r = self._resid.get(n)
            if r is None or r.shape != (L,):
                r = jax.device_put(np.zeros((L,), dt), row)
            self._resid[n] = r
            out.append(r)
        return tuple(out)

    def _publish_comm_gauges(self, cmode):
        """comm.* gauges for the window just dispatched. The byte count
        is the wire MODEL (comm.bytes_src='modeled'): in global-view
        SPMD the partitioner moves the reduced gradient itself, so the
        gauge is arithmetic over the leaf layout, not a socket counter
        — the kvstore_dist path publishes the measured twin."""
        if not _tele.enabled():
            return
        from ..parallel import compression
        block = _compress_block()
        total = unc = 0
        for _n, L, dt in self._resid_specs():
            total += compression.wire_bytes(L, cmode, block, dt.itemsize)
            unc += compression.wire_bytes(L, 'off', block, dt.itemsize)
        _tele.gauge('comm.bytes_on_wire_per_step').set(int(total))
        _tele.gauge('comm.compression_ratio').set(
            round(unc / max(total, 1), 3))
        _tele.gauge('comm.mode').set(cmode)
        _tele.gauge('comm.bytes_src').set('modeled')

    def _note_compress_window(self, cmode, win_ms):
        """Per-window compression bookkeeping: publish the comm gauges
        and, on the first completed window after a mode flip (the auto
        trigger engaging mid-run), emit the one-shot 'compression'
        JSONL record carrying the before/after per-step wall delta."""
        st = self._cstate
        st['windows'] += 1
        self._publish_comm_gauges(cmode)
        prev, last_ms = st['mode'], st['ms']
        W = self.window
        if (prev is not None and cmode != prev and not st['emitted']
                and st.get('flip') is None and last_ms is not None):
            # the first window in the new mode pays the program
            # rebuild + compile — hold the record until the next
            # (steady-state) window so the after-side is honest
            st['flip'] = {'prev': prev, 'to': cmode,
                          'before_ms': last_ms}
        elif (st.get('flip') is not None and not st['emitted']
                and cmode == st['flip']['to']):
            from ..parallel import compression
            before = st['flip']['before_ms']
            compression.emit_record(
                event='mode_flip', mode=cmode,
                prev_mode=st['flip']['prev'],
                auto=compression.auto_engaged(),
                step=int(st['windows'] * W),
                before_step_ms=round(before / W, 3),
                after_step_ms=round(win_ms / W, 3),
                delta_step_ms=round((win_ms - before) / W, 3))
            st['emitted'] = True
        st['mode'], st['ms'] = cmode, win_ms

    # -- per-epoch drive ---------------------------------------------------
    def _snapshot(self):
        e = self._exec
        params = tuple(e.arg_dict[n]._data for n in self._carry_names)
        states = tuple(tuple(self._state_arrays(n))
                       for n in self._grad_names)
        aux = tuple(e.aux_dict[n]._data for n in self._aux_names)
        gaccs = tuple(e.grad_dict[n]._data for n in self._grad_names) \
            if self._accum else ()
        if self._mesh is not None:
            from .window_pipeline import place_replicated
            if self._zero is not None:
                # optimizer state enters (and stays) in the ZeRO
                # update-phase form; already-converted leaves pass
                # through untouched, so this is free in steady state
                from .window_pipeline import place_update_sharded
                flat = place_update_sharded(self._mesh, [
                    (a, shape)
                    for n, st in zip(self._grad_names, states)
                    for a, (shape, _d) in zip(st, self._zero_shapes[n])])
                regrouped, i = [], 0
                for n in self._grad_names:
                    k = len(self._zero_shapes[n])
                    regrouped.append(tuple(flat[i:i + k]))
                    i += k
                states = tuple(regrouped)
                params, aux, gaccs = place_replicated(
                    self._mesh, params, aux, gaccs)
            else:
                params, states, aux, gaccs = place_replicated(
                    self._mesh, params, states, aux, gaccs)
        self._note_update_gauges()
        return params, states, aux, gaccs

    def _writeback(self, params, states, aux, gaccs):
        e = self._exec
        m = self.module
        for n, v in zip(self._carry_names, params):
            e.arg_dict[n]._data = v
        for n, st in zip(self._grad_names, states):
            self._writeback_state(n, list(st))
            if m._update_on_kvstore:
                # keep the kvstore's canonical copy in sync (pull reads it)
                store = m._kvstore._store.get(n)
                if store is not None:
                    store._data = e.arg_dict[n]._data
        for n, v in zip(self._aux_names, aux):
            e.aux_dict[n]._data = v
        if self._accum:
            for n, v in zip(self._grad_names, gaccs):
                e.grad_dict[n]._data = v
        m._params_dirty = True

    def run_epoch(self, train_data, eval_metric, epoch,
                  batch_end_callback, monitor=None, ckpt=None):
        """Run one epoch; returns the number of batches consumed.
        Tail batches (< window) run through the reference per-batch
        path — state is written back after every window, so the two
        paths interleave safely. ``ckpt`` is fit's TrainCheckpointer
        (module/checkpointing.py), fed once per dispatched window."""
        from ..model import BatchEndParam
        from .base_module import _as_list

        _tele.gauge('fused_fit.steps_per_call').set(self.window)
        # cpu-backed NDArray wrapper for already-host data, so the
        # metric's .asnumpy() calls cost no device round-trip
        host_nd = host_wrap(self._exec._ctx)

        # which metric children carry a per-batch loss: the in-graph
        # CrossEntropy sufficient statistics feed the health plane's
        # rolling loss-spike detector AND the run ledger's per-step
        # loss scalar for free (note_loss no-ops while health is off)
        ce_idx = [j for j, c in enumerate(self.children or ())
                  if type(c) is metric_mod.CrossEntropy] \
            if self.stat_fns is not None and (
                self._health_fn is not None or _tele.ledger.enabled()) \
            else []

        # wall stamp of the previous apply_stats fetch: the ledger's
        # per-step timestamps amortize over the inter-window wall so
        # W steps processed in one burst don't bunch at one instant
        # (which would inflate steps_per_sec and zero run_compare's
        # step_time deltas)
        _stats_t = [None]

        def apply_stats(pieces, labels_w, nbatch, win_snaps=None):
            """One host fetch for the window's results, then exact
            per-batch metric application + callbacks. Stats mode feeds
            the packed sufficient-statistic sums into the metric
            children; host-metric mode replays eval_metric.update with
            each step's outputs against the window's own labels
            (snapshotted at collection time — see below), the way the
            reference loop's update_metric would."""
            hrows = drows = None
            if self._health_fn is not None or self._dyn_fn is not None:
                parts = list(pieces)
                pieces = parts.pop(0)
                if self._health_fn is not None:
                    hrows = parts.pop(0)
                if self._dyn_fn is not None:
                    drows = parts.pop(0)
            with _tele.span('fused_fit.fetch', 'fused_fit'):
                # the window's one device->host fetch (full RTT on a
                # tunneled runtime; everything after is host math) —
                # the (W, k) sentinel AND dynamics matrices ride the
                # same fetch
                if self.stat_fns is not None:
                    host = np.asarray(pieces)      # (W, 2 * n_metrics)
                    steps = host.shape[0]
                else:
                    outs_host = [np.asarray(o) for o in pieces]  # (W, ...)
                    steps = outs_host[0].shape[0]
                if hrows is not None:
                    hmat = np.asarray(hrows)
                if drows is not None:
                    dmat = np.asarray(drows)
            if hrows is not None:
                # mid-window NaN -> exact step attribution + (first
                # incident) staged-path first-bad-layer bisect on the
                # offending batch's draw-time snapshot. raise action
                # surfaces here, before the metric sees garbage.
                _tele.health.note_window(
                    hmat, source='fused_fit', nbatch_base=nbatch,
                    bisect=window_bisect(
                        self._exec, list(self.module._data_names),
                        list(self.module._label_names), win_snaps, True,
                        defer_fn=self._defer_eager)
                    if win_snaps is not None else None)
            if drows is not None:
                # per-layer dynamics: each row keeps its exact step,
                # feeds the per-layer spike detectors and raises a
                # named-layer incident on a non-finite statistic
                _tele.dynamics.note_window(
                    dmat, self._grad_names, self._out_names,
                    nbatch_base=nbatch)
            ledger_on = _tele.ledger.enabled()
            if ledger_on:
                t_apply = time.time()
                t_prev = _stats_t[0]
                _stats_t[0] = t_apply
            for i in range(steps):
                loss_i = None
                if self.stat_fns is not None:
                    for j, child in enumerate(self.children):
                        child.sum_metric += float(host[i, 2 * j])
                        child.num_inst += int(host[i, 2 * j + 1])
                    for j in ce_idx:
                        loss_i = host[i, 2 * j] / max(host[i, 2 * j + 1],
                                                      1.0)
                        _tele.health.note_loss(loss_i)
                else:
                    preds = [host_nd(o[i]) for o in outs_host]
                    eval_metric.update(labels_w[i], preds)
                if ledger_on:
                    # run-ledger scalars (decimated inside): the step's
                    # in-graph CE loss when the stats plan computes one,
                    # the running metric otherwise. Steps spread evenly
                    # across the inter-window wall; the first window has
                    # no baseline so its due steps bunch at ITS fetch
                    # stamp — the same timeline later windows
                    # interpolate on (emission-time clocks would land
                    # PAST the next window's anchor and break
                    # monotonicity)
                    _tele.ledger.note_train_step(
                        loss=loss_i, lr=self._last_lr,
                        metric=None if loss_i is not None
                        else eval_metric,
                        t=t_apply if t_prev is None else
                        t_prev + (t_apply - t_prev) * (i + 1) / steps)
                if batch_end_callback is not None:
                    p = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric,
                                      locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(p)
                nbatch += 1
            return nbatch

        from ..io import DataBatch as _DataBatch
        # deferred device-augment: when the iterator supports it, draw
        # RAW uint8 batches and trace the augmentation inside the
        # window program — each eager per-batch aug dispatch costs
        # ~65-85 ms of tunnel latency (the 221 img/s fed-fit plateau,
        # docs/perf.md round-5)
        defer_switch = getattr(train_data, 'defer_device_aug', None)
        self._defer_fn = None
        self._defer_eager = None
        self._defer_sig = False
        if callable(defer_switch) and defer_switch(True):
            # one pure fn per ITERATOR object (WeakKey: dies with it) —
            # an unsigned iterator would otherwise key a fresh program
            # every epoch through the identity fallback below
            try:
                self._defer_fn = self._defer_fns[train_data]
            except KeyError:
                self._defer_fn = train_data.device_aug_pure()
                self._defer_fns[train_data] = self._defer_fn
            # tail batches (< window) materialize per batch: ONE
            # compiled call each, not the pure fn's ~10 eager ops
            self._defer_eager = jax.jit(self._defer_fn)
            # the aug MATH is baked into the compiled window, so the
            # program key must carry its configuration — a second
            # iterator with equal batch shapes but different
            # mean/std/scale/rand flags must NOT reuse this program.
            # Unsigned fallback keys by the LIVE function object (held
            # by the key itself), never by a recyclable id()
            sig_fn = getattr(train_data, 'device_aug_signature', None)
            self._defer_sig = sig_fn() if callable(sig_fn) \
                else ('defer-unsigned', self._defer_fn)
        else:
            defer_switch = None
        try:
            return self._run_epoch_inner(
                train_data, eval_metric, epoch, batch_end_callback,
                _DataBatch, apply_stats, host_nd, ckpt)
        except Exception as e:
            # RESOURCE_EXHAUSTED anywhere in the window drive (upload,
            # dispatch, stats fetch): dump the per-program memory
            # breakdown before the crash surfaces (no-op otherwise)
            _tele.programs.maybe_oom_report(e)
            raise
        finally:
            if defer_switch is not None:
                defer_switch(False)
                self._defer_fn = None
                self._defer_eager = None
            # the loop now outlives fit() (build_cached): drop the last
            # window's device stack + its strong host refs — the
            # identity cache only ever hits while an epoch is running
            self._pipe.drop_cache()

    def _run_epoch_inner(self, train_data, eval_metric, epoch,
                         batch_end_callback, _DataBatch, apply_stats,
                         host_nd, ckpt=None):
        from ..model import BatchEndParam
        from .base_module import _as_list
        from .. import random as _random
        m = self.module
        # a resumed epoch's first fused batch IS batch r_step of the
        # epoch: counting from the checkpointer's base keeps callback/
        # incident batch indices true (and the failure bound correct)
        nbatch = ckpt.epoch_nbatch_base if ckpt is not None else 0
        pending = None
        it = iter(train_data)
        # MXTPU_FUSED_FIT_TIMING=1: per-epoch host-stage breakdown
        # (draw / stack+put / dispatch / stats-fetch) — the fed-path
        # diagnosis knob; wall beyond these stages is device compute
        # the host successfully hid
        from ..config import flags as _flags
        _timing = bool(_flags.get('MXTPU_FUSED_FIT_TIMING'))
        _tm = {'draw': 0.0, 'put': 0.0, 'dispatch': 0.0, 'fetch': 0.0}
        _clk = time.perf_counter
        _ep_t0 = _clk() if _timing else 0.0
        pipe = self._pipe
        pool = pipe.pool() \
            if _flags.get('MXTPU_FUSED_FIT_PREFETCH') else None

        faults_on = _faults.enabled()

        def collect():
            # draw-time snapshotting lives in the shared pipeline:
            # iterators may legally reuse their DataBatch/NDArray
            # buffers for the next batch; the draw-time jax-array
            # references stay valid while the window is collected and
            # the apply is deferred.
            _t = _clk() if _timing else 0.0
            batches, snaps = pipe.collect(it)
            if faults_on:
                # nan-grad draw seam: training batches counted in step
                # order, the armed one poisoned before stack/upload
                snaps = [_faults.maybe_poison_snap(s) for s in snaps]
            if _timing:
                _tm['draw'] += _clk() - _t
            return batches, snaps

        def start_put(win_snaps):
            # with the prefetch pool, window k+1's stack + put run on
            # the side thread while window k computes on device and
            # k-1's stats fetch waits
            return pipe.start_put(win_snaps, pool)

        health_on = self._health_fn is not None
        cluster_on = _tele.cluster.enabled()
        mem_on = _tele.memory.enabled()
        tl_on = _tele.timeline.enabled()
        _t_win = _clk()   # wall clock per dispatched window (health)
        batches, snaps = collect()
        if not batches:
            if ckpt is not None and ckpt.allow_empty_epoch(epoch):
                # checkpoint-resume landed exactly on this epoch's
                # boundary: the skip consumed every batch — the epoch
                # is already trained
                return 0
            # exhausted before the FIRST batch: the reference loop's
            # unguarded first next() (base_module.py:482) raises here —
            # fail just as loudly instead of silently training a
            # zero-batch epoch (callers must reset() an iterator that a
            # score()/predict pass drained)
            raise StopIteration(
                'training iterator is exhausted at epoch start — '
                'reset() it (a score()/predict pass leaves the '
                'iterator drained, matching the reference fit loop)')
        fut = start_put(snaps) if len(batches) == self.window else None
        try:
            while len(batches) == self.window:
                # one program per (static attrs, shapes); lr/wd enter
                # as traced arrays sampled at each window start, so an
                # lr scheduler never forces a recompile
                static_attrs = self._static_attrs()
                attrs_key = tuple(sorted(static_attrs.items()))
                shapes_key = tuple((tuple(d.shape), str(d.dtype))
                                   for d in snaps[0][0])
                # resolved compression mode is part of the program key:
                # an auto flip (cluster verdict) lands here as a new
                # key and rebuilds the window at this dispatch edge
                cmode = self._cmode()
                prog_key = (attrs_key, shapes_key, self._defer_sig,
                            cmode)
                if prog_key not in self._programs:
                    with _tele.span('fused_fit.build', 'fused_fit'):
                        self._programs[prog_key] = self._build_program(
                            static_attrs, shapes_key, cmode)
                    # same-key rebuilds only happen when the program dict
                    # was torn down; the storm detector keys on the
                    # SHAPES — a shape/attr leaking into attrs_key shows
                    # up as many builds of one shapes_key
                    _tele.xla.note_retrace(('fused_fit.window', shapes_key))
                window_fn = self._programs[prog_key]

                # host-metric mode: keep per-batch label wrappers from
                # the draw-time snapshots for the deferred
                # eval_metric.update. Stats mode needs nothing from the
                # host batches.
                labels_snap = None
                if self.stat_fns is None:
                    labels_snap = [[from_jax(l, self._exec._ctx)
                                    for l in ls] for _, ls, _, _ in snaps]
                if faults_on:
                    # dispatch-exception seam: fire before the window
                    # containing the armed step is dispatched
                    _faults.maybe_raise('dispatch', upcoming=self.window)
                params, states, aux, gaccs = self._snapshot()
                # the optimizer's host tail — W x n_params update-count
                # walks + lr/wd sampling, plus the snapshot above —
                # runs BEFORE the put wait, so it hides under window
                # k+1's side-thread transfer instead of serializing
                # after it (the update/upload overlap; the resolver's
                # hidden_ms below is the evidence)
                lr_arr, wd_arr = self._sample_window_lr()
                _t = _clk() if _timing else 0.0
                with _tele.span('fused_fit.put', 'fused_fit'):
                    data_stack, label_stack = fut()
                if pool is not None:
                    _tele.histogram('fused_fit.overlap_ms').observe(
                        fut.hidden_ms)
                if _timing:
                    _now = _clk()
                    _tm['put'] += _now - _t
                    _t = _now
                with _tele.span('fused_fit.dispatch', 'fused_fit'):
                    self._base_key = _random.next_key()
                    if cmode != 'off':
                        resids = self._ensure_resids()
                        (params, states, aux, gaccs, resids,
                         pieces) = window_fn(
                            params, states, aux, gaccs, resids,
                            data_stack, label_stack,
                            self._base_key, lr_arr, wd_arr)
                        self._resid = dict(zip(self._grad_names, resids))
                    else:
                        params, states, aux, gaccs, pieces = window_fn(
                            params, states, aux, gaccs, data_stack,
                            label_stack, self._base_key, lr_arr, wd_arr)
                    self._writeback(params, states, aux, gaccs)
                _tele.counter('fit.steps').inc(self.window)
                _tele.counter('fused_fit.windows').inc()
                # hang-watchdog progress mark: one whole window
                # dispatched (the dispatch is async, but an enqueued
                # window IS host-side progress; a wedged device shows
                # up at the next put/fetch, which then stops marking)
                _tele.watchdog.note_progress('fused_fit.window')
                if cluster_on:
                    # a whole window of steps advanced in one dispatch;
                    # the sync (if due) piggybacks on the window edge
                    _tele.cluster.note_step(self.window)
                # MXTPU_XPROF step window (quantized to whole windows)
                _profiler.note_step(self.window)
                if _timing:
                    _now = _clk()
                    _tm['dispatch'] += _now - _t
                    _t = _now
                # dispatch is async: while this window computes, draw
                # the NEXT window (its stack + transfer start on the
                # side thread) and fetch the PREVIOUS window's stats —
                # both the transfer and the fetch RTT disappear behind
                # device time (callbacks run one window late; values
                # and cadence are unchanged)
                win_snaps = snaps if health_on else None
                batches, snaps = collect()
                fut = start_put(snaps) \
                    if len(batches) == self.window else None
                if pending is not None:
                    nbatch = apply_stats(pending[0], pending[1], nbatch,
                                         pending[2])
                pending = (pieces, labels_snap, win_snaps)
                # one wall observation per window (window-edge to
                # window-edge): in steady state the loop is device-
                # bound, so wall / W IS the per-step time — health's
                # step-time stream and the compression flip record's
                # before/after delta both read it
                _now = _clk()
                _win_wall = _now - _t_win
                _t_win = _now
                if health_on:
                    _tele.health.note_step_time(_win_wall,
                                                steps=self.window)
                if self._zero is not None:
                    self._note_compress_window(cmode, _win_wall * 1e3)
                if ckpt is not None:
                    lag = self.window
                    if pending is not None and ckpt.save_due(self.window):
                        # a save will initiate for THIS window: flush
                        # the pipelined stats/health rows first so the
                        # capture's eval-metric state covers every step
                        # the checkpoint claims (and a NaN in this
                        # window raises BEFORE a poisoned capture)
                        nbatch = apply_stats(pending[0], pending[1],
                                             nbatch, pending[2])
                        pending = None
                        lag = 0   # health checked through this window
                    # otherwise the health plane has only processed the
                    # PREVIOUS window's rows (the fetch is pipelined one
                    # window late): certification trails by lag=W
                    ckpt.note_steps(self.window, lag=lag)
                if faults_on:
                    _faults.note_steps(self.window)
                if mem_on:
                    # live-bytes timeline (MXTPU_MEMORY): a host-side
                    # allocator query at the scalars cadence, no
                    # device sync
                    _tele.memory.note_step(self.window)
                if tl_on:
                    # pod step timeline (MXTPU_TIMELINE): a whole
                    # window of steps for the phase ledger's per-step
                    # normalization — one clock read
                    _tele.timeline.note_step(self.window)
                if _timing:
                    _tm['fetch'] += _clk() - _t
        finally:
            # drain an in-flight prefetch before run_epoch's cache
            # teardown (or an exception unwind) can race the side thread
            if pool is not None:
                WindowPipeline.drain(fut)
        _t = _clk() if _timing else 0.0
        if pending is not None:
            nbatch = apply_stats(pending[0], pending[1], nbatch,
                                 pending[2])
        if _timing:
            _tm['fetch'] += _clk() - _t
        if snaps:
            # tail batches run the imperative per-batch update: ZeRO
            # leaves materialize to canonical shapes and the kvstore-
            # side arrays return to the context device (the per-batch
            # machinery's placement invariant)
            self._prepare_tail()
        for ds, ls, pad, idx in snaps:
            # tail (< window): reference per-batch path, on a rebuilt
            # batch (the original's buffers may have been overwritten
            # by later draws — pad/index come from the draw-time
            # snapshot for the same reason). Deferred uint8 batches are
            # materialized eagerly here — one aug dispatch per tail
            # batch, exactly the eager mode's cost
            if self._defer_eager is not None:
                ds = (self._defer_eager(ds[0], _random.next_key()),
                      ) + tuple(ds[1:])
            sb = _DataBatch(
                data=[from_jax(d, self._exec._ctx) for d in ds],
                label=[from_jax(l, self._exec._ctx) for l in ls],
                pad=pad, index=idx)
            if health_on or self._dyn_fn is not None:
                # the tail runs the executor path: incidents (health
                # AND dynamics) carry the real batch index through the
                # note_batch context
                _tele.health.note_batch(nbatch)
            m.forward_backward(sb)
            m.update()
            _tele.counter('fit.steps').inc()
            _tele.watchdog.note_progress('fit.step')
            if cluster_on:
                _tele.cluster.note_step()
            if faults_on:
                _faults.note_steps(1)
            if tl_on:
                _tele.timeline.note_step(1)
            _profiler.note_step()
            m.update_metric(eval_metric, sb.label)
            _tele.ledger.note_train_step(lr=self._last_lr,
                                         metric=eval_metric)
            if ckpt is not None:
                # after update_metric, so a save initiated on a tail
                # step captures the metric including this batch; the
                # sentinel check already ran inside backward (lag=0)
                ckpt.note_steps(1)
            if batch_end_callback is not None:
                p = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                  eval_metric=eval_metric,
                                  locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(p)
            nbatch += 1
        if _timing:
            logging.info(
                'fused_fit timing epoch=%d wall=%.3fs draw=%.3fs '
                'put=%.3fs dispatch=%.3fs fetch=%.3fs', epoch,
                _clk() - _ep_t0, _tm['draw'], _tm['put'],
                _tm['dispatch'], _tm['fetch'])
        return nbatch
