"""Fused multi-step fast path for Module.fit.

Reference: python/mxnet/module/base_module.py:376 runs one
forward_backward + update + update_metric per batch. On a TPU behind a
tunneled runtime each of those is a separate dispatch with ms-scale
RTT, which caps throughput regardless of chip speed (measured in
docs/perf.md: spc=1 1596 img/s vs spc=32 2552 img/s on the same
graph). This module compiles a WINDOW of W training steps into ONE
XLA computation via lax.scan — the standard in-graph-train-loop TPU
pattern — behind the unchanged Module.fit API:

- numerics are identical to the per-batch path: the same _GraphProgram
  runner, the same jax.vjp with all-ones head gradients, the same
  registered sgd(_mom)/mp_sgd(_mom) update ops with the same attrs;
- the eval metric is computed from in-graph sufficient statistics
  (per-step correct/count sums), fetched once per window and applied
  per batch on the host, so metric values and batch_end_callback
  cadence match the reference loop exactly (callbacks fire in a burst
  after each window — the one observable difference);
- the learning rate enters the compiled program as a traced scalar
  (no recompile when a scheduler moves it), sampled once per window
  at the value the updater would use for the window's FIRST batch:
  window-aligned scheduler boundaries are exact; a mid-window
  boundary lands up to W-1 updates late. Bookkeeping (num_update)
  advances per-batch as in the reference.

Eligibility is conservative (build() returns None → fit falls back to
the reference loop): plain Module, one executor (single context or
SPMD group), non-staged graph, grad_req='write', type(optimizer) is
SGD, single-process kvstore (None/'local'/'device'), and a metric
composed of Accuracy / TopKAccuracy / CrossEntropy.

Toggles: MXTPU_FUSED_FIT=0 disables; MXTPU_FIT_STEPS_PER_CALL sets W
(default 32 on TPU, 4 elsewhere).
"""
import logging

import numpy as np

import jax
import jax.numpy as jnp

from .. import metric as metric_mod
from .. import optimizer as opt_mod
from ..executor import mirror_wrap
from ..kvstore import _updater_key
from ..ndarray.ndarray import NDArray, from_jax
from ..ops import registry as _reg

__all__ = ['FusedFitLoop']


def _window_size():
    from ..config import flags
    flags.reload('MXTPU_FIT_STEPS_PER_CALL')
    n = flags.get('MXTPU_FIT_STEPS_PER_CALL')
    if n > 0:
        return n
    return 32 if jax.default_backend() == 'tpu' else 4


def _is_half(dt):
    return str(dt) in ('float16', 'bfloat16')


# ---------------------------------------------------------------------------
# metric plans: in-graph sufficient statistics + host-side apply
# ---------------------------------------------------------------------------

def _plan_one(m):
    """(stats_fn(outs, labels) -> (sum, count), apply) for one metric,
    or None if unsupported. Statistics mirror metric.py's numpy math."""
    if type(m) is metric_mod.Accuracy:
        if getattr(m, 'axis', 1) != 1:
            return None     # stats below assume 2-D preds, class axis 1
        def stats(outs, labels):
            pred = outs[0]
            hit = jnp.argmax(pred, axis=-1).astype(jnp.int32) == \
                labels[0].astype(jnp.int32)
            return jnp.sum(hit).astype(jnp.float32), \
                jnp.float32(hit.size)
        return stats
    if type(m) is metric_mod.TopKAccuracy:
        k = m.top_k

        def stats(outs, labels, k=k):
            pred = outs[0]
            _, idx = jax.lax.top_k(pred, k)
            hit = jnp.any(idx.astype(jnp.int32) ==
                          labels[0].astype(jnp.int32)[..., None], axis=-1)
            return jnp.sum(hit).astype(jnp.float32), \
                jnp.float32(hit.size)
        return stats
    if type(m) is metric_mod.CrossEntropy:
        eps = getattr(m, 'eps', 1e-12)

        def stats(outs, labels, eps=eps):
            pred = outs[0]
            lab = labels[0].astype(jnp.int32)
            p = jnp.take_along_axis(pred, lab[:, None], axis=-1)[:, 0]
            return jnp.sum(-jnp.log(p + eps)).astype(jnp.float32), \
                jnp.float32(lab.size)
        return stats
    return None


def _metric_plan(eval_metric):
    """Returns (children, [stats_fn]) where children are the leaf
    EvalMetric objects to update, or None if any leaf is unsupported."""
    if isinstance(eval_metric, metric_mod.CompositeEvalMetric):
        children = list(eval_metric.metrics)
    else:
        children = [eval_metric]
    fns = []
    for m in children:
        fn = _plan_one(m)
        if fn is None:
            return None
        fns.append(fn)
    return children, fns


class FusedFitLoop:
    """One compiled W-step train window driving Module's state."""

    def __init__(self, module, children, stat_fns, window):
        self.module = module
        self.children = children
        self.stat_fns = stat_fns
        self.window = window
        self._programs = {}
        self._dev_cache_key = None
        self._dev_cache = None

        e = module._exec_group.execs[0]
        self._exec = e
        self._run = e._run_eager
        self._arg_names = list(e._prog.arg_names)
        self._aux_names = list(e._prog.aux_names)
        self._grad_names = list(e._grad_names)
        io_names = set(module._data_names) | set(module._label_names)
        self._carry_names = [n for n in self._arg_names if n not in io_names]
        self._carry_pos = {n: i for i, n in enumerate(self._carry_names)}
        self._optimizer = module._optimizer
        # SPMD group: every carried array must live replicated on the
        # mesh and batch stacks sharded over dp, or jit rejects the
        # mixed-device argument set
        from .executor_group import SPMDExecutorGroup
        self._mesh = module._exec_group.mesh \
            if isinstance(module._exec_group, SPMDExecutorGroup) else None
        # the key each param updates under must match the unfused path:
        # update_on_kvstore pushes by NAME (kvstore._updater keys);
        # the local updater uses integer position (model._update_params)
        if module._update_on_kvstore:
            self._upd_keys = {n: _updater_key(n) for n in self._grad_names}
        else:
            pnames = module._exec_group.param_names
            self._upd_keys = {n: pnames.index(n) for n in self._grad_names}
        self._ensure_states()

    # -- eligibility -------------------------------------------------------
    @staticmethod
    def build(module, eval_metric, logger=logging):
        from ..config import flags
        flags.reload('MXTPU_FUSED_FIT')
        if not flags.get('MXTPU_FUSED_FIT'):
            return None
        from .module import Module
        if type(module) is not Module:
            return None
        eg = module._exec_group
        if len(getattr(eg, 'execs', ())) != 1:
            return None
        e = eg.execs[0]
        if e._use_staged() or e._monitor is not None:
            return None
        if module._grad_req != 'write' or module.inputs_need_grad:
            return None
        opt = module._optimizer
        if type(opt) is not opt_mod.SGD:
            return None
        kv = module._kvstore
        if kv is not None and kv.type not in ('local', 'device'):
            return None
        # the metric stat fns assume ONE 2-D (batch, classes) output and
        # one label — the reference loop zips all output/label pairs
        shapes = {d.name: d.shape for d in
                  list(module.data_shapes) + list(module.label_shapes or [])}
        try:
            _, out_shapes, _ = module._symbol.infer_shape(**shapes)
        except Exception:  # noqa: BLE001 — undecidable shapes: fall back
            return None
        if out_shapes is None or len(out_shapes) != 1 \
                or len(out_shapes[0]) != 2:
            return None
        if len(module._label_names) != 1:
            return None
        plan = _metric_plan(eval_metric)
        if plan is None:
            return None
        children, fns = plan
        loop = FusedFitLoop(module, children, fns, _window_size())
        logger.info('fused fit fast path active: %d steps/device-call',
                    loop.window)
        return loop

    # -- optimizer state ---------------------------------------------------
    def _updater_obj(self):
        m = self.module
        return m._kvstore._updater if m._update_on_kvstore else m._updater

    def _ensure_states(self):
        """Pre-create optimizer states through the optimizer's own
        create_state path so save/load_optimizer_states see the same
        structure the unfused loop would build lazily."""
        upd = self._updater_obj()
        e = self._exec
        for n in self._grad_names:
            key = self._upd_keys[n]
            if key not in upd.states:
                upd.states[key] = \
                    self._optimizer.create_state_multi_precision(
                        key, e.arg_dict[n])
                upd.states_synced[key] = True

    def _state_arrays(self, n):
        """Flatten one param's optimizer state into jax arrays in the
        update op's INPUT order: () / (mom,) / (w32,) / (mom, w32)."""
        st = self._updater_obj().states[self._upd_keys[n]]
        if isinstance(st, tuple):           # multi-precision (w32, mom)
            w32, mom = st
            if mom is None:
                return [w32._data]          # mp_sgd_update(..., weight32)
            return [mom._data, w32._data]   # mp_sgd_mom_update(.., mom, w32)
        return [st._data] if st is not None else []

    def _writeback_state(self, n, arrays):
        upd = self._updater_obj()
        st = upd.states[self._upd_keys[n]]
        if isinstance(st, tuple):
            w32, mom = st
            if mom is None:
                w32._data = arrays[0]
            else:
                mom._data = arrays[0]
                w32._data = arrays[1]
        elif st is not None:
            st._data = arrays[0]

    # -- program -----------------------------------------------------------
    def _static_attrs(self, n):
        """Per-param attrs that never change across windows (lr/wd are
        dynamic: they enter the compiled program as traced scalars so a
        per-update lr scheduler never forces a recompile)."""
        o = self._optimizer
        clip = -1.0 if o.clip_gradient is None else float(o.clip_gradient)
        return {'momentum': o.momentum, 'rescale_grad': o.rescale_grad,
                'clip_gradient': clip}

    def _sample_window_lr(self):
        """Advance the optimizer's update bookkeeping for the whole
        window and return the (lr, wd) its updater would use for the
        window's FIRST batch. Window-aligned scheduler boundaries are
        thus exact; a mid-window boundary lands <=W-1 updates late
        (see module docstring)."""
        o = self._optimizer
        for n in self._grad_names:            # the first batch's update
            o._update_count(self._upd_keys[n])
        lr = np.array([o._get_lr(self._upd_keys[n])
                       for n in self._grad_names], np.float32)
        wd = np.array([o._get_wd(self._upd_keys[n])
                       for n in self._grad_names], np.float32)
        for _ in range(self.window - 1):      # the rest of the window
            for n in self._grad_names:
                o._update_count(self._upd_keys[n])
        return lr, wd

    def _mode(self, n):
        """Update-op choice per param — mirrors SGD.update_multi_precision."""
        half = _is_half(self._exec.arg_dict[n]._data.dtype)
        mp = self._optimizer.multi_precision and half
        mom = self._optimizer.momentum != 0.0
        return ('mp_' if mp else '') + ('sgd_mom_update' if mom
                                        else 'sgd_update')

    def _build_program(self, attrs_key, shapes_key):
        run = self._run
        arg_pos = {n: i for i, n in enumerate(self._arg_names)}
        data_names = list(self.module._data_names)
        label_names = list(self.module._label_names)
        carry_names = self._carry_names
        grad_names = self._grad_names
        grad_carry_idx = [self._carry_pos[n] for n in grad_names]
        attrs_map = dict(attrs_key)
        modes = {n: self._mode(n) for n in grad_names}
        ops = {mode: _reg.get(mode) for mode in set(modes.values())}
        stat_fns = self.stat_fns
        W = self.window

        def window_fn(params, states, aux, data_stack, label_stack, key,
                      lr_arr, wd_arr):
            def body(carry, xs):
                params, states, aux = carry
                step_i, datas, labels = xs
                k = jax.random.fold_in(key, step_i)

                def f(wrt):
                    full = [None] * len(arg_pos)
                    for n, v in zip(carry_names, params):
                        full[arg_pos[n]] = v
                    for n, v in zip(data_names, datas):
                        full[arg_pos[n]] = v
                    for n, v in zip(label_names, labels):
                        full[arg_pos[n]] = v
                    for n, v in zip(grad_names, wrt):
                        full[arg_pos[n]] = v
                    return run(tuple(full), aux, k, True)

                wrt = tuple(params[i] for i in grad_carry_idx)
                (outs, new_aux), vjp = jax.vjp(mirror_wrap(f), wrt)
                heads = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
                zero_aux = tuple(jnp.zeros_like(a) for a in new_aux)
                (grads,) = vjp((heads, zero_aux))

                new_params = list(params)
                new_states = list(states)
                for j, n in enumerate(grad_names):
                    ci = grad_carry_idx[j]
                    w, g = params[ci], grads[j]
                    mode = modes[n]
                    attrs = dict(attrs_map[n])
                    attrs['lr'] = lr_arr[j]   # traced: scheduler-safe
                    attrs['wd'] = wd_arr[j]
                    res = ops[mode].fn(attrs, w, g, *states[j])
                    if mode == 'sgd_update':
                        new_params[ci] = res
                    elif mode in ('sgd_mom_update', 'mp_sgd_update'):
                        new_params[ci] = res[0]
                        new_states[j] = (res[1],)
                    else:  # mp_sgd_mom_update: (w_half, new_mom, new_w32)
                        new_params[ci] = res[0]
                        new_states[j] = (res[1], res[2])
                # all metric stats packed into ONE vector per step so
                # the host needs a single fetch per window (each fetch
                # through a tunneled runtime costs a full RTT)
                pieces = jnp.stack([v for fn in stat_fns
                                    for v in fn(outs, labels)])
                return (tuple(new_params), tuple(new_states), new_aux), \
                    pieces

            (p, s, a), pieces = jax.lax.scan(
                body, (params, states, aux),
                (jnp.arange(W), data_stack, label_stack))
            return p, s, a, pieces   # pieces: (W, 2 * n_metrics)

        return jax.jit(window_fn, donate_argnums=(0, 1, 2))

    # -- per-epoch drive ---------------------------------------------------
    def _snapshot(self):
        e = self._exec
        params = tuple(e.arg_dict[n]._data for n in self._carry_names)
        states = tuple(tuple(self._state_arrays(n))
                       for n in self._grad_names)
        aux = tuple(e.aux_dict[n]._data for n in self._aux_names)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(self._mesh, P())
            place = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda a: a if getattr(a, 'sharding', None) == rep
                else jax.device_put(a, rep), t)
            params, states, aux = place(params), place(states), place(aux)
        return params, states, aux

    def _writeback(self, params, states, aux):
        e = self._exec
        m = self.module
        for n, v in zip(self._carry_names, params):
            e.arg_dict[n]._data = v
        for n, st in zip(self._grad_names, states):
            self._writeback_state(n, list(st))
            if m._update_on_kvstore:
                # keep the kvstore's canonical copy in sync (pull reads it)
                store = m._kvstore._store.get(n)
                if store is not None:
                    store._data = e.arg_dict[n]._data
        for n, v in zip(self._aux_names, aux):
            e.aux_dict[n]._data = v
        m._params_dirty = True

    def _device_batches(self, batches):
        """Stack W host batches into device (W, ...) arrays. Identity-
        cached: synthetic/benchmark iterators yield the same arrays
        every batch, so the transfer happens once. The cache key holds
        STRONG references to the source arrays — identity is compared
        against live objects, so a freed array's id can never produce
        a false hit."""
        arrays = [a._data for b in batches
                  for a in list(b.data) + list(b.label)]
        if self._dev_cache_key is not None and \
                len(arrays) == len(self._dev_cache_key) and \
                all(a is c for a, c in zip(arrays, self._dev_cache_key)):
            return self._dev_cache
        key = arrays
        def shard(stack):
            if self._mesh is None:
                # source arrays may be committed to the host device
                # (cpu_pinned iterators); the window runs where the
                # executor's params live
                return jax.device_put(stack, self._exec._ctx.jax_device())
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = P(*((None, 'dp') + (None,) * (stack.ndim - 2)))
            return jax.device_put(stack, NamedSharding(self._mesh, spec))

        data_stack = [shard(jnp.stack([jnp.asarray(b.data[i]._data)
                                       for b in batches]))
                      for i in range(len(batches[0].data))]
        label_stack = [shard(jnp.stack([jnp.asarray(b.label[i]._data)
                                        for b in batches]))
                       for i in range(len(batches[0].label))]
        self._dev_cache_key = key
        self._dev_cache = (tuple(data_stack), tuple(label_stack))
        return self._dev_cache

    def run_epoch(self, train_data, eval_metric, epoch,
                  batch_end_callback, monitor=None):
        """Run one epoch; returns the number of batches consumed.
        Tail batches (< window) run through the reference per-batch
        path — state is written back after every window, so the two
        paths interleave safely."""
        from ..model import BatchEndParam
        from .base_module import _as_list
        from .. import random as _random
        m = self.module

        def apply_stats(pieces, nbatch):
            """One host fetch for the window's packed stats, then exact
            per-batch metric application + callbacks."""
            host = np.asarray(pieces)          # (W, 2 * n_metrics)
            for i in range(host.shape[0]):
                for j, child in enumerate(self.children):
                    child.sum_metric += float(host[i, 2 * j])
                    child.num_inst += int(host[i, 2 * j + 1])
                if batch_end_callback is not None:
                    p = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric,
                                      locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(p)
                nbatch += 1
            return nbatch

        nbatch = 0
        pending = None   # previous window's stats, fetched AFTER the
        # next window is dispatched so the RTT overlaps device compute
        it = iter(train_data)
        done = False
        while not done:
            batches = []
            while len(batches) < self.window:
                try:
                    batches.append(next(it))
                except StopIteration:
                    done = True
                    break
            if len(batches) < self.window:
                if pending is not None:
                    nbatch = apply_stats(pending, nbatch)
                    pending = None
                for b in batches:   # tail: reference per-batch path
                    m.forward_backward(b)
                    m.update()
                    m.update_metric(eval_metric, b.label)
                    if batch_end_callback is not None:
                        p = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                        for cb in _as_list(batch_end_callback):
                            cb(p)
                    nbatch += 1
                break

            # one program per (static attrs, shapes); lr/wd enter as
            # traced scalars sampled at each window start, so an lr
            # scheduler never forces a recompile
            attrs_key = tuple(
                (n, tuple(sorted(self._static_attrs(n).items())))
                for n in self._grad_names)
            shapes_key = tuple((tuple(b.shape) for b in batches[0].data))
            prog_key = (attrs_key, shapes_key)
            if prog_key not in self._programs:
                self._programs[prog_key] = self._build_program(
                    {n: dict(a) for n, a in attrs_key}, shapes_key)
            window_fn = self._programs[prog_key]

            params, states, aux = self._snapshot()
            data_stack, label_stack = self._device_batches(batches)
            lr_arr, wd_arr = self._sample_window_lr()
            self._base_key = _random.next_key()
            params, states, aux, pieces = window_fn(
                params, states, aux, data_stack, label_stack,
                self._base_key, lr_arr, wd_arr)
            self._writeback(params, states, aux)
            # dispatch is async: fetch the PREVIOUS window's stats now,
            # while this window computes — the fetch RTT disappears
            # behind device time (callbacks run one window late; values
            # and cadence are unchanged)
            if pending is not None:
                nbatch = apply_stats(pending, nbatch)
            pending = pieces
        if pending is not None:
            nbatch = apply_stats(pending, nbatch)
        return nbatch
