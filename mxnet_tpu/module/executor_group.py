"""DataParallelExecutorGroup — the data-parallel heart of Module.

Reference: python/mxnet/module/executor_group.py:99 (decide_slices:233 splits
the batch over contexts by workload, _bind_ith_exec:584 per-device
simple_bind with shared memory pool, forward/backward fan-out,
_merge_multi_context:75).

TPU note: on a mesh the idiomatic path is ONE pjit over all chips
(parallel/), which Module uses when given a single tpu context with a mesh;
this class preserves the reference's explicit per-context semantics for
multi-context CPU/TPU lists (and the multi-device-without-cluster tests).
"""
import logging

import numpy as np

from .. import context as ctx_mod
from .. import ndarray as nd
from ..io import DataDesc
from ..executor import Executor

__all__ = ['DataParallelExecutorGroup']


def _load_general(data, targets, major_axis):
    """Load a list of batch arrays into per-device slices (reference :33)."""
    for d_src, d_targets in zip(data, targets):
        if isinstance(d_targets, nd.NDArray):
            d_src.copyto(d_targets)
        else:
            for slice_idx, d_dst in d_targets:
                d_src_np = d_src.asnumpy()[slice_idx.start:slice_idx.stop]
                d_dst._data = nd.array(d_src_np, ctx=d_dst.context)._data


def _merge_multi_context(outputs, major_axis):
    """Concat per-device outputs along the batch axis (reference :75)."""
    rets = []
    for tensors, axis in zip(outputs, major_axis):
        if axis >= 0 and len(tensors) > 1:
            rets.append(nd.concatenate(tensors, axis=axis))
        else:
            rets.append(tensors[0])
    return rets


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req='write', state_names=None):
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []
        self.logger = logger

        if grad_req != 'null' and for_training:
            self.grad_req = {}
            for k in self.arg_names:
                if k in self.param_names:
                    self.grad_req[k] = 'null' if k in self.fixed_param_names \
                        else grad_req
                elif k in [d.name if isinstance(d, DataDesc) else d[0]
                           for d in data_shapes]:
                    self.grad_req[k] = grad_req if inputs_need_grad else 'null'
                else:
                    self.grad_req[k] = 'null'
        else:
            self.grad_req = {k: 'null' for k in self.arg_names}

        self.execs = []
        self.slices = None
        self.data_shapes = None
        self.label_shapes = None
        self.data_layouts = None
        self.label_layouts = None
        self.output_layouts = [0] * len(symbol.list_outputs())
        self.batch_size = None

        self.bind_exec(data_shapes, label_shapes, shared_group)

    def decide_slices(self, data_shapes):
        """Reference :233 — split batch_size over contexts by workload."""
        assert len(data_shapes) > 0
        major_axis = [DataDesc.get_batch_axis(getattr(d, 'layout', 'NCHW'))
                      for d in data_shapes]
        for (name, shape), axis in zip(
                [(d.name, d.shape) if isinstance(d, DataDesc) else d
                 for d in data_shapes], major_axis):
            if axis == -1:
                continue
            batch_size = shape[axis]
            if self.batch_size is not None:
                assert batch_size == self.batch_size, \
                    ('all data must have the same batch size: batch_size = %d,'
                     ' but %s has shape %s') % (self.batch_size, name, shape)
            else:
                self.batch_size = batch_size
                total = sum(self.workload[:len(self.contexts)])
                chunks = [self.batch_size * w // total for w in
                          self.workload[:len(self.contexts)]]
                rem = self.batch_size - sum(chunks)
                for i in range(rem):
                    chunks[i] += 1
                starts = np.cumsum([0] + chunks)
                self.slices = [slice(starts[i], starts[i + 1])
                               for i in range(len(self.contexts))]
        return major_axis

    def _sliced_shape(self, shapes, i, major_axis):
        sliced = []
        for (name, shape), axis in zip(
                [(d.name, d.shape) if isinstance(d, DataDesc) else d
                 for d in shapes], major_axis):
            shape = list(shape)
            if axis >= 0:
                shape[axis] = self.slices[i].stop - self.slices[i].start
            sliced.append(DataDesc(name, tuple(shape)))
        return sliced

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        self.data_layouts = self.decide_slices(data_shapes)
        if label_shapes is not None and len(label_shapes) > 0:
            self.label_layouts = self.decide_slices(label_shapes)
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self.execs = []
        for i in range(len(self.contexts)):
            self.execs.append(self._bind_ith_exec(i, data_shapes, label_shapes,
                                                  shared_group))

        self.data_arrays = [[(self.slices[i], e.arg_dict[name])
                             for i, e in enumerate(self.execs)]
                            for name, _ in [(d.name, d.shape) if isinstance(d, DataDesc)
                                            else d for d in data_shapes]]
        if label_shapes is not None and len(label_shapes) > 0:
            self.label_arrays = [[(self.slices[i], e.arg_dict[name])
                                  for i, e in enumerate(self.execs)]
                                 for name, _ in [(d.name, d.shape) if isinstance(d, DataDesc)
                                                 else d for d in label_shapes]]
        else:
            self.label_arrays = None

        self.param_arrays = [[e.arg_dict[name] for e in self.execs]
                             for name in self.param_names]
        if self.for_training:
            self.grad_arrays = [[e.grad_dict.get(name) for e in self.execs]
                                for name in self.param_names]
        else:
            self.grad_arrays = [[None] * len(self.execs)
                                for _ in self.param_names]
        data_names = [d.name if isinstance(d, DataDesc) else d[0]
                      for d in data_shapes]
        if self.inputs_need_grad:
            self.input_grad_arrays = [[e.grad_dict[name] for e in self.execs]
                                      for name in data_names]
        self.aux_arrays = [[e.aux_dict[name] for e in self.execs]
                           for name in self.aux_names]

    def _bind_ith_exec(self, i, data_shapes, label_shapes, shared_group):
        """Reference :584 — per-device simple_bind."""
        shapes = self._sliced_shape(data_shapes, i, self.data_layouts)
        if label_shapes is not None and len(label_shapes) > 0:
            shapes = shapes + self._sliced_shape(label_shapes, i,
                                                 self.label_layouts)
        input_shapes = {d.name: d.shape for d in shapes}
        return self.symbol.simple_bind(self.contexts[i],
                                       grad_req=self.grad_req,
                                       **input_shapes)

    def reshape(self, data_shapes, label_shapes):
        if data_shapes == self.data_shapes and label_shapes == self.label_shapes:
            return
        self.batch_size = None
        self.bind_exec(data_shapes, label_shapes, reshape=True)

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Reference :420 — weights averaged... actually copied from dev 0."""
        for name, block in zip(self.param_names, self.param_arrays):
            arg_params[name]._data = block[0]._data
        for name, block in zip(self.aux_names, self.aux_arrays):
            aux_params[name]._data = block[0]._data

    def forward(self, data_batch, is_train=None):
        _load_general(data_batch.data, self.data_arrays, self.data_layouts)
        if is_train is None:
            is_train = self.for_training
        if self.label_arrays is not None and data_batch.label:
            _load_general(data_batch.label, self.label_arrays,
                          self.label_layouts)
        for e in self.execs:
            e.forward(is_train=is_train)

    def get_output_shapes(self):
        outputs = self.execs[0].outputs
        shapes = [out.shape for out in outputs]
        concat_shapes = []
        for key, the_shape, axis in zip(self.symbol.list_outputs(), shapes,
                                        self.output_layouts):
            the_shape = list(the_shape)
            if axis >= 0:
                the_shape[axis] = self.batch_size
            concat_shapes.append((key, tuple(the_shape)))
        return concat_shapes

    def get_outputs(self, merge_multi_context=True):
        outputs = [[exec_.outputs[i] for exec_ in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            outputs = _merge_multi_context(outputs, self.output_layouts)
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        if merge_multi_context:
            return _merge_multi_context(self.input_grad_arrays,
                                        self.data_layouts)
        return self.input_grad_arrays

    def backward(self, out_grads=None):
        assert self.for_training, 're-bind with for_training=True to run backward'
        for i, exec_ in enumerate(self.execs):
            out_grads_slice = None
            if out_grads is not None:
                out_grads_slice = []
                for grad, axis in zip(out_grads, self.output_layouts):
                    if axis >= 0:
                        og = nd.array(grad.asnumpy()[self.slices[i]],
                                      ctx=self.contexts[i])
                    else:
                        og = grad.as_in_context(self.contexts[i]) \
                            if grad.context != self.contexts[i] else grad
                    out_grads_slice.append(og)
            exec_.backward(out_grads=out_grads_slice)

    def update_metric(self, eval_metric, labels):
        for texec, islice in zip(self.execs, self.slices):
            labels_slice = []
            for label in labels:
                if islice.stop - islice.start == label.shape[0]:
                    labels_slice.append(label)
                else:
                    labels_slice.append(
                        nd.array(label.asnumpy()[islice]))
            eval_metric.update(labels_slice, texec.outputs)

    def install_monitor(self, mon):
        for e in self.execs:
            mon.install(e)
