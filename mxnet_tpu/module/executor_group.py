"""DataParallelExecutorGroup — the data-parallel heart of Module.

Reference: python/mxnet/module/executor_group.py:99 (decide_slices:233 splits
the batch over contexts by workload, _bind_ith_exec:584 per-device
simple_bind with shared memory pool, forward/backward fan-out,
_merge_multi_context:75).

TPU note: when the context list is homogeneous (the common data-parallel
case) Module uses :class:`SPMDExecutorGroup` instead — ONE GSPMD
computation over a jax Mesh of the devices, with the gradient all-reduce
compiled into the step (the reference's KVStore push becomes a psum by
construction). This class keeps the reference's explicit per-context
semantics for heterogeneous/unequal-workload setups and as the fallback.
"""
import logging
import os

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import telemetry as _tele
from ..io import DataDesc
from ..executor import Executor

__all__ = ['DataParallelExecutorGroup', 'SPMDExecutorGroup']


def _load_general(data, targets, major_axis):
    """Load a list of batch arrays into per-device slices (reference :33).

    The device slice runs along each entry's BATCH axis (major_axis,
    from the DataDesc layout) — slicing axis 0 unconditionally
    truncated time-major 'TN' batches along TIME whenever T exceeded
    the batch size (and silently no-op'd when T <= batch, python
    slicing being clamped)."""
    for d_src, d_targets, axis in zip(data, targets, major_axis):
        if isinstance(d_targets, nd.NDArray):
            d_src.copyto(d_targets)
            continue
        src_np = d_src.asnumpy()
        for slice_idx, d_dst in d_targets:
            if axis >= 0:
                idx = [slice(None)] * src_np.ndim
                idx[axis] = slice(slice_idx.start, slice_idx.stop)
                part = src_np[tuple(idx)]
            else:
                part = src_np
            if tuple(part.shape) != tuple(d_dst.shape):
                raise ValueError(
                    'batch slice has shape %s but the bound buffer is %s '
                    '(batch axis %d)' % (part.shape, tuple(d_dst.shape),
                                         axis))
            d_dst._data = nd.array(part, ctx=d_dst.context)._data


def _merge_multi_context(outputs, major_axis):
    """Concat per-device outputs along the batch axis (reference :75)."""
    rets = []
    for tensors, axis in zip(outputs, major_axis):
        if axis >= 0 and len(tensors) > 1:
            rets.append(nd.concatenate(tensors, axis=axis))
        else:
            rets.append(tensors[0])
    return rets


def _output_layouts(symbol):
    """Per-output batch axis from each output's ``__layout__`` attr (the
    reference derives merge/slice/shape axes the same way), so a
    time-major ('TN') output reports/merges on its real batch axis
    instead of assuming axis 0. -1 means no batch axis."""
    return [DataDesc.get_batch_axis(symbol[name].attr('__layout__'))
            for name in symbol.list_outputs()]


def _check_label_args(label_shapes, arg_dict, symbol):
    """A label name that isn't an argument of the bound symbol can only
    come from a provide_label/label_names mismatch that the bind-time
    name check already warned about (reference base_module.py:56 warns
    for labels instead of raising) — fail like the reference's
    simple_bind/infer_shape does at the same point, with the argument
    list instead of a bare KeyError."""
    for d in label_shapes:
        name = d.name if isinstance(d, DataDesc) else d[0]
        if name not in arg_dict:
            raise ValueError(
                "label '%s' is not an argument of the symbol (arguments:"
                ' %s) — pass matching label_names to Module or rename '
                'the iterator label' % (name, symbol.list_arguments()))


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req='write', state_names=None):
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1] * len(contexts)
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []
        self.logger = logger

        if grad_req != 'null' and for_training:
            self.grad_req = {}
            for k in self.arg_names:
                if k in self.param_names:
                    self.grad_req[k] = 'null' if k in self.fixed_param_names \
                        else grad_req
                elif k in [d.name if isinstance(d, DataDesc) else d[0]
                           for d in data_shapes]:
                    self.grad_req[k] = grad_req if inputs_need_grad else 'null'
                else:
                    self.grad_req[k] = 'null'
        else:
            self.grad_req = {k: 'null' for k in self.arg_names}

        self.execs = []
        self.slices = None
        self.data_shapes = None
        self.label_shapes = None
        self.data_layouts = None
        self.label_layouts = None
        self.output_layouts = _output_layouts(symbol)
        self.batch_size = None

        self.bind_exec(data_shapes, label_shapes, shared_group)

    def decide_slices(self, data_shapes):
        """Reference :233 — split batch_size over contexts by workload."""
        assert len(data_shapes) > 0
        major_axis = [DataDesc.get_batch_axis(getattr(d, 'layout', 'NCHW'))
                      for d in data_shapes]
        if len(self.contexts) > 1 and any(a > 0 for a in major_axis):
            # output merge / head-grad slicing honor per-output layout
            # axes, but INPUT loading across unequal per-device chunks
            # with a non-leading batch axis is untested territory —
            # fail loudly rather than risk interleaving time across
            # devices. The SPMD group (homogeneous contexts, even batch)
            # handles non-zero batch axes.
            raise NotImplementedError(
                'multi-device per-context execution with a non-leading '
                'batch axis (layouts %s) is not supported; use equal '
                'workloads so the SPMD group handles it, or batch-major '
                'layouts' % [getattr(d, 'layout', 'NCHW')
                             for d in data_shapes])
        for (name, shape), axis in zip(
                [(d.name, d.shape) if isinstance(d, DataDesc) else d
                 for d in data_shapes], major_axis):
            if axis == -1:
                continue
            batch_size = shape[axis]
            if self.batch_size is not None:
                assert batch_size == self.batch_size, \
                    ('all data must have the same batch size: batch_size = %d,'
                     ' but %s has shape %s') % (self.batch_size, name, shape)
            else:
                self.batch_size = batch_size
                total = sum(self.workload[:len(self.contexts)])
                chunks = [self.batch_size * w // total for w in
                          self.workload[:len(self.contexts)]]
                rem = self.batch_size - sum(chunks)
                for i in range(rem):
                    chunks[i] += 1
                starts = np.cumsum([0] + chunks)
                self.slices = [slice(starts[i], starts[i + 1])
                               for i in range(len(self.contexts))]
        return major_axis

    def _sliced_shape(self, shapes, i, major_axis):
        sliced = []
        for (name, shape), axis in zip(
                [(d.name, d.shape) if isinstance(d, DataDesc) else d
                 for d in shapes], major_axis):
            shape = list(shape)
            if axis >= 0:
                shape[axis] = self.slices[i].stop - self.slices[i].start
            sliced.append(DataDesc(name, tuple(shape)))
        return sliced

    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        self.data_layouts = self.decide_slices(data_shapes)
        if label_shapes is not None and len(label_shapes) > 0:
            self.label_layouts = self.decide_slices(label_shapes)
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        self.execs = []
        for i in range(len(self.contexts)):
            self.execs.append(self._bind_ith_exec(i, data_shapes, label_shapes,
                                                  shared_group))

        self.data_arrays = [[(self.slices[i], e.arg_dict[name])
                             for i, e in enumerate(self.execs)]
                            for name, _ in [(d.name, d.shape) if isinstance(d, DataDesc)
                                            else d for d in data_shapes]]
        if label_shapes is not None and len(label_shapes) > 0:
            _check_label_args(label_shapes, self.execs[0].arg_dict,
                              self.symbol)
            self.label_arrays = [[(self.slices[i], e.arg_dict[name])
                                  for i, e in enumerate(self.execs)]
                                 for name, _ in [(d.name, d.shape) if isinstance(d, DataDesc)
                                                 else d for d in label_shapes]]
        else:
            self.label_arrays = None

        self.param_arrays = [[e.arg_dict[name] for e in self.execs]
                             for name in self.param_names]
        if self.for_training:
            self.grad_arrays = [[e.grad_dict.get(name) for e in self.execs]
                                for name in self.param_names]
        else:
            self.grad_arrays = [[None] * len(self.execs)
                                for _ in self.param_names]
        data_names = [d.name if isinstance(d, DataDesc) else d[0]
                      for d in data_shapes]
        if self.inputs_need_grad:
            self.input_grad_arrays = [[e.grad_dict[name] for e in self.execs]
                                      for name in data_names]
        self.aux_arrays = [[e.aux_dict[name] for e in self.execs]
                           for name in self.aux_names]

    def _bind_ith_exec(self, i, data_shapes, label_shapes, shared_group):
        """Reference :584 — per-device simple_bind."""
        shapes = self._sliced_shape(data_shapes, i, self.data_layouts)
        if label_shapes is not None and len(label_shapes) > 0:
            shapes = shapes + self._sliced_shape(label_shapes, i,
                                                 self.label_layouts)
        input_shapes = {d.name: d.shape for d in shapes}
        return self.symbol.simple_bind(self.contexts[i],
                                       grad_req=self.grad_req,
                                       **input_shapes)

    def reshape(self, data_shapes, label_shapes):
        if data_shapes == self.data_shapes and label_shapes == self.label_shapes:
            return
        self.batch_size = None
        self.bind_exec(data_shapes, label_shapes, reshape=True)

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for e in self.execs:
            e.copy_params_from(arg_params, aux_params,
                               allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        """Reference :420 — weights averaged... actually copied from dev 0."""
        for name, block in zip(self.param_names, self.param_arrays):
            arg_params[name]._data = block[0]._data
        for name, block in zip(self.aux_names, self.aux_arrays):
            aux_params[name]._data = block[0]._data

    def forward(self, data_batch, is_train=None):
        with _tele.span('exec_group.forward', 'executor'):
            _load_general(data_batch.data, self.data_arrays,
                          self.data_layouts)
            if is_train is None:
                is_train = self.for_training
            if self.label_arrays is not None and data_batch.label:
                _load_general(data_batch.label, self.label_arrays,
                              self.label_layouts)
            for e in self.execs:
                e.forward(is_train=is_train)

    def get_output_shapes(self):
        outputs = self.execs[0].outputs
        shapes = [out.shape for out in outputs]
        concat_shapes = []
        for key, the_shape, axis in zip(self.symbol.list_outputs(), shapes,
                                        self.output_layouts):
            the_shape = list(the_shape)
            if axis >= 0:
                the_shape[axis] = self.batch_size
            concat_shapes.append((key, tuple(the_shape)))
        return concat_shapes

    def get_outputs(self, merge_multi_context=True):
        outputs = [[exec_.outputs[i] for exec_ in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            outputs = _merge_multi_context(outputs, self.output_layouts)
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        if merge_multi_context:
            return _merge_multi_context(self.input_grad_arrays,
                                        self.data_layouts)
        return self.input_grad_arrays

    def backward(self, out_grads=None):
        assert self.for_training, 're-bind with for_training=True to run backward'
        with _tele.span('exec_group.backward', 'executor'):
            self._backward_impl(out_grads)

    def _backward_impl(self, out_grads):
        for i, exec_ in enumerate(self.execs):
            out_grads_slice = None
            if out_grads is not None:
                out_grads_slice = []
                for grad, axis in zip(out_grads, self.output_layouts):
                    if axis >= 0:
                        # slice the head gradient along the OUTPUT's
                        # batch axis (a 'TNC' output's is 1, not 0)
                        idx = [slice(None)] * len(grad.shape)
                        idx[axis] = self.slices[i]
                        og = nd.array(grad.asnumpy()[tuple(idx)],
                                      ctx=self.contexts[i])
                    else:
                        og = grad.as_in_context(self.contexts[i]) \
                            if grad.context != self.contexts[i] else grad
                    out_grads_slice.append(og)
            exec_.backward(out_grads=out_grads_slice)

    def update_metric(self, eval_metric, labels):
        axes = self.label_layouts if self.label_layouts is not None \
            else [0] * len(labels)
        for texec, islice in zip(self.execs, self.slices):
            labels_slice = []
            for label, axis in zip(labels, axes):
                # slice along the label's BATCH axis (TN layouts carry
                # the batch on axis 1, reference executor_group.py:549)
                if axis < 0 or \
                        islice.stop - islice.start == label.shape[axis]:
                    labels_slice.append(label)
                else:
                    idx = [slice(None)] * len(label.shape)
                    idx[axis] = islice
                    labels_slice.append(
                        nd.array(label.asnumpy()[tuple(idx)]))
            eval_metric.update(labels_slice, texec.outputs)

    def install_monitor(self, mon):
        for e in self.execs:
            mon.install(e)


class SPMDExecutorGroup:
    """GSPMD form of DataParallelExecutorGroup: one executor, one mesh.

    The reference's per-batch step is slice → per-device executors →
    KVStore reduce → update → broadcast (§3.3). Here the full-batch
    symbol is bound ONCE and its fused fwd+bwd jit runs over a 1-d
    ``dp`` Mesh of the bound contexts: data/label arrays carry a
    batch-sharded NamedSharding, parameters a replicated one, and XLA's
    partitioner inserts the gradient all-reduce exactly where the
    reference pushed to the KVStore — compiled into the step and
    overlapped with backprop. Gradients surface already merged, so
    Module's update (or kvstore push/pull) runs the optimizer once per
    parameter instead of once per device.

    Exposes the DataParallelExecutorGroup surface Module relies on, with
    single-entry per-device lists (there is one logical executor).
    """

    @staticmethod
    def window_sharding(mesh, ndim):
        """NamedSharding for a (W, batch, ...) window stack fed to a
        compiled multi-step window (the fused fit/eval loops): dp
        shards the BATCH axis (axis 1 of the stack), the window axis
        stays unsharded so lax.scan peels whole dp-sharded batches."""
        return NamedSharding(mesh, P(*((None, 'dp') + (None,) * (ndim - 2))))

    @staticmethod
    def replicate_sharding(mesh):
        """Fully-replicated NamedSharding on ``mesh``. The fused window
        pins its tiny whole-mesh operands (the scan's s32 step-index
        vector, the per-step lr/wd rows) with it: left unannotated,
        GSPMD's partitioner re-derives their placement per use and
        emits '[spmd] Involuntary full rematerialization' stderr
        warnings for each one (the PR 9 known residue) — an explicit
        replicated constraint makes the derivation trivial and the
        warnings disappear."""
        return NamedSharding(mesh, P())

    @staticmethod
    def update_sharding(mesh):
        """NamedSharding for an update-phase leaf (the ZeRO layout of
        arXiv:2004.13336): optimizer-state tensors flattened to 1-D and
        padded to a multiple of dp (parallel/sharding.zero_flatten) are
        row-sharded over the dp axis, so each device owns — and
        updates — exactly 1/dp of every leaf. The companion of
        :meth:`window_sharding` for the fused window's carried state."""
        return NamedSharding(mesh, P('dp'))

    @staticmethod
    def eligible(contexts, workload, batch_size, symbol):
        from ..config import flags as _flags
        _flags.reload('MXTPU_NO_SPMD_MODULE')  # tests toggle it per-case
        if _flags.get('MXTPU_NO_SPMD_MODULE'):
            return False
        if len(contexts) < 2:
            return False
        if len({c.device_type for c in contexts}) != 1:
            return False
        if workload and len(set(workload[:len(contexts)])) != 1:
            return False  # unequal workloads need explicit slices
        if batch_size % len(contexts):
            return False  # NamedSharding needs an even batch split
        try:
            devs = {c.jax_device() for c in contexts}
        except Exception:  # noqa: BLE001 — unresolvable device → fallback
            return False
        return len(devs) == len(contexts)

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req='write', state_names=None):
        self.param_names = param_names
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.symbol = symbol
        self.contexts = contexts
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = fixed_param_names or []
        self.logger = logger
        self.output_layouts = _output_layouts(symbol)

        self.mesh = Mesh(np.array([c.jax_device() for c in contexts]),
                         ('dp',))
        self._replicate = NamedSharding(self.mesh, P())

        self._data_names = [d.name if isinstance(d, DataDesc) else d[0]
                            for d in data_shapes]
        self._label_names = [] if not label_shapes else \
            [d.name if isinstance(d, DataDesc) else d[0] for d in label_shapes]
        if label_shapes:
            _check_label_args(label_shapes,
                              dict.fromkeys(symbol.list_arguments()), symbol)
        # dp shards each input along ITS batch axis (a 'TN' layout puts
        # the batch on axis 1; sharding axis 0 would split time)
        self._batch_axes = {
            (d.name if isinstance(d, DataDesc) else d[0]):
            DataDesc.get_batch_axis(getattr(d, 'layout', 'NCHW'))
            for d in list(data_shapes) + list(label_shapes or [])}

        if grad_req != 'null' and for_training:
            self.grad_req = {}
            for k in self.arg_names:
                if k in self.param_names:
                    self.grad_req[k] = 'null' if k in self.fixed_param_names \
                        else grad_req
                elif k in self._data_names:
                    self.grad_req[k] = grad_req if inputs_need_grad else 'null'
                else:
                    self.grad_req[k] = 'null'
        else:
            self.grad_req = {k: 'null' for k in self.arg_names}

        self.bind_exec(data_shapes, label_shapes, shared_group)

    # -- binding ---------------------------------------------------------
    def bind_exec(self, data_shapes, label_shapes, shared_group=None,
                  reshape=False):
        self.data_shapes = data_shapes
        self.label_shapes = label_shapes
        shapes = {(d.name if isinstance(d, DataDesc) else d[0]):
                  (d.shape if isinstance(d, DataDesc) else d[1])
                  for d in data_shapes}
        if label_shapes:
            shapes.update({(d.name if isinstance(d, DataDesc) else d[0]):
                           (d.shape if isinstance(d, DataDesc) else d[1])
                           for d in label_shapes})
        first = data_shapes[0]
        first_axis = max(self._batch_axes.get(
            first.name if isinstance(first, DataDesc) else first[0], 0), 0)
        self.batch_size = (first.shape if isinstance(first, DataDesc)
                           else first[1])[first_axis]
        exec_ = self.symbol.simple_bind(self.contexts[0],
                                        grad_req=self.grad_req, **shapes)
        self.execs = [exec_]
        self.slices = [slice(0, self.batch_size)]
        self.param_arrays = [[exec_.arg_dict[n]] for n in self.param_names]
        self.grad_arrays = [[exec_.grad_dict.get(n)] for n in
                            self.param_names] if self.for_training else \
            [[None] for _ in self.param_names]
        if self.inputs_need_grad:
            self.input_grad_arrays = [[exec_.grad_dict[n]]
                                      for n in self._data_names]
        self.aux_arrays = [[exec_.aux_dict[n]] for n in self.aux_names]

    def reshape(self, data_shapes, label_shapes):
        if data_shapes == self.data_shapes and \
                label_shapes == self.label_shapes:
            return
        self.bind_exec(data_shapes, label_shapes, reshape=True)

    # -- params ----------------------------------------------------------
    def set_params(self, arg_params, aux_params, allow_extra=False):
        self.execs[0].copy_params_from(arg_params, aux_params,
                                       allow_extra_params=allow_extra)
        self._place_replicated()

    def get_params(self, arg_params, aux_params):
        for name, block in zip(self.param_names, self.param_arrays):
            arg_params[name]._data = block[0]._data
        for name, block in zip(self.aux_names, self.aux_arrays):
            aux_params[name]._data = block[0]._data

    def _place_replicated(self):
        """Pin every non-data array to the replicated mesh sharding so
        GSPMD sees params/aux as broadcast and grads come out psum'd."""
        e = self.execs[0]
        skip = set(self._data_names) | set(self._label_names)
        for name, arr in e.arg_dict.items():
            if name not in skip:
                arr._data = jax.device_put(arr._data, self._replicate)
        for arr in e.aux_dict.values():
            arr._data = jax.device_put(arr._data, self._replicate)

    def _shard_for(self, name, ndim):
        axis = self._batch_axes.get(name, 0)
        if axis < 0 or axis >= ndim:
            return self._replicate
        spec = [None] * ndim
        spec[axis] = 'dp'
        return NamedSharding(self.mesh, P(*spec))

    # -- step ------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        with _tele.span('exec_group.forward', 'executor'):
            e = self.execs[0]
            if is_train is None:
                is_train = self.for_training
            for name, src in zip(self._data_names, data_batch.data):
                e.arg_dict[name]._data = jax.device_put(
                    src._data, self._shard_for(name, src._data.ndim))
            if self._label_names and data_batch.label:
                for name, src in zip(self._label_names, data_batch.label):
                    e.arg_dict[name]._data = jax.device_put(
                        src._data, self._shard_for(name, src._data.ndim))
            self._place_replicated()
            e.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.for_training, \
            're-bind with for_training=True to run backward'
        with _tele.span('exec_group.backward', 'executor'):
            self.execs[0].backward(out_grads=out_grads)

    # -- results ---------------------------------------------------------
    def get_output_shapes(self):
        return [(key, out.shape) for key, out in
                zip(self.symbol.list_outputs(), self.execs[0].outputs)]

    def get_outputs(self, merge_multi_context=True):
        outs = self.execs[0].outputs
        return list(outs) if merge_multi_context else [[o] for o in outs]

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        grads = [g[0] for g in self.input_grad_arrays]
        return grads if merge_multi_context else self.input_grad_arrays

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.execs[0].outputs)

    def install_monitor(self, mon):
        for e in self.execs:
            mon.install(e)
