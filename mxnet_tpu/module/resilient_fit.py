"""Supervised training: fit() under a restart-from-last-good loop.

``resilient_fit(module, train_data, ...)`` runs :meth:`BaseModule.fit`
and, when the run dies of something survivable — a
:class:`~mxnet_tpu.telemetry.health.TrainingHealthError` raised by the
in-graph sentinels (MXTPU_HEALTH_ACTION=raise), an injected or real
dispatch failure, a backend/runtime error — it certifies the
checkpointer's pending saves against the failure diagnostic, applies
escalating backoff, and re-enters fit(), which restores from the
last-good checkpoint and resumes mid-epoch (module/checkpointing.py:
parameters, optimizer state, RNG streams and the data-iterator cursor
all come back, so a clean replay reaches the same final state an
uninterrupted run would). Every restart is recorded as a ``restart``
JSONL record and counted under ``health.restarts``.

Budget: ``MXTPU_RESTART_MAX`` attempts with
``MXTPU_RESTART_BACKOFF * 2^(k-1)`` seconds between them (capped at
60s); a failure past the budget — or one that is not retryable
(assertion errors, keyboard interrupt, shape/user errors) — re-raises
unchanged.

For whole-process supervision (host loss, wedged backends that take
the process down) see ``tools/train_supervisor.py``, which wraps any
training command in the same restart-and-resume loop from the outside.
"""
import logging
import time

from .. import telemetry as _tele
from ..faults import FaultInjected
from ..telemetry.health import TrainingHealthError

__all__ = ['resilient_fit', 'is_retryable']

_BACKOFF_CAP_S = 60.0

# error families worth a restore-and-retry: health incidents, injected
# faults, runtime/backend failures (XlaRuntimeError subclasses
# RuntimeError), lost connections to a tunneled runtime. User/shape
# errors (ValueError/TypeError/AssertionError) re-raise immediately.
_RETRYABLE = (TrainingHealthError, FaultInjected, RuntimeError,
              ConnectionError, TimeoutError, OSError)
_FATAL = (KeyboardInterrupt, SystemExit, MemoryError)


def is_retryable(exc):
    if isinstance(exc, _FATAL):
        return False
    return isinstance(exc, _RETRYABLE)


def _budget():
    from ..config import flags
    flags.reload('MXTPU_RESTART_MAX')
    flags.reload('MXTPU_RESTART_BACKOFF')
    return flags.get('MXTPU_RESTART_MAX'), flags.get('MXTPU_RESTART_BACKOFF')


def resilient_fit(module, train_data, restart_max=None,
                  restart_backoff=None, logger=logging, **fit_kwargs):
    """Run ``module.fit(train_data, **fit_kwargs)`` under supervision.

    Returns the number of restarts it took (0 = clean first run).
    Checkpoint cadence/restore come from the MXTPU_CKPT_* flags — with
    them unset this still retries, but every retry starts from epoch 0
    (nothing to restore), which is only sane for transient backend
    errors."""
    max_restarts, backoff = _budget()
    if restart_max is not None:
        max_restarts = int(restart_max)
    if restart_backoff is not None:
        backoff = float(restart_backoff)
    attempts = 0
    while True:
        try:
            module.fit(train_data, **fit_kwargs)
            return attempts
        except Exception as e:  # noqa: BLE001 — filtered right below
            if not is_retryable(e) or attempts >= max_restarts:
                raise
            attempts += 1
            diag = dict(getattr(e, 'diagnostic', None) or {})
            ckpt = module.__dict__.get('_mxtpu_ckpt')
            restore_from = None
            if ckpt is not None:
                # drain the async writer and certify pending saves
                # against the incident before the next attempt reads
                # the last-good pointer
                try:
                    ckpt.handle_failure(diag)
                except Exception:  # noqa: BLE001 — never mask the retry
                    pass
                restore_from = ckpt.last_good
                # restart rework: every step between the restore point
                # and where the crashed attempt had reached will be
                # re-trained — badput the goodput ledger must attribute
                reached = int(getattr(ckpt, 'global_step', 0) or 0)
                _tele.goodput.note_rework(
                    reached - int(restore_from or 0))
            _tele.health.note_restart(
                attempt=attempts, reason=type(e).__name__,
                message=str(e)[:200], restore_step=restore_from,
                diagnostic=diag or None)
            delay = min(_BACKOFF_CAP_S, backoff * (2.0 ** (attempts - 1)))
            logger.warning(
                'resilient_fit: attempt %d/%d failed (%s: %s) — '
                'restoring from %s and retrying in %.1fs',
                attempts, max_restarts, type(e).__name__,
                str(e)[:200],
                'step %s' % restore_from if restore_from is not None
                else 'scratch (no certified checkpoint)', delay)
            if delay:
                time.sleep(delay)
            # the crashed attempt leaves the iterator mid-epoch; the
            # next fit() must draw epoch data from the top so the
            # skip-to-step lands on the right batches
            try:
                train_data.reset()
            except Exception:  # noqa: BLE001
                pass
