"""Shared window machinery for the fused fit/eval fast paths.

module/fused_fit.py compiles W training steps into one XLA call;
module/fused_eval.py does the same for the read-only half of the API
(score / predict / iter_predict). Both loops need identical host-side
input machinery, extracted here so it is ONE subsystem with two
consumers instead of two private copies:

- draw-time batch snapshotting (:meth:`WindowPipeline.collect`):
  iterators may legally reuse their DataBatch/NDArray buffers for the
  next batch — the reference per-batch loop consumes each batch before
  drawing the next. jax arrays are immutable, so references captured
  as each batch is drawn stay valid while a whole window is in flight,
  along with the batch's draw-time ``pad``/``index``;
- window stacking (:meth:`WindowPipeline.device_batches`): W batches
  become (W, ...) device arrays with ONE host->device transfer per
  input. Host-resident parts stack on the host first so the whole
  window crosses in a single ``device_put`` (W per-batch transfers
  each cost a full dispatch RTT on a tunneled runtime); on an SPMD
  mesh the stacks land dp-sharded over the batch axis
  (:meth:`executor_group.SPMDExecutorGroup.window_sharding`). An
  identity cache short-circuits synthetic/benchmark iterators that
  yield the same arrays every batch;
- a one-thread upload pool (:meth:`WindowPipeline.start_put`): window
  k+1's stack + transfer run on a side thread while window k computes
  on device — np.stack's memcpy and the transfer both release the
  GIL, so the overlap is real even on a one-core host;
- the in-graph metric plans (:func:`plan_metric`): sufficient
  statistics for Accuracy / TopKAccuracy / CrossEntropy (and
  composites of them) that both loops compile into their scan bodies,
  packed so the host needs a single fetch per window.
"""
import numpy as np

import jax
import jax.numpy as jnp

from .. import metric as metric_mod
from .. import telemetry as _tele
from ..ndarray.ndarray import from_jax

__all__ = ['WindowPipeline', 'window_size', 'plan_metric', 'host_wrap',
           'registered_jit', 'health_sentinel', 'dynamics_sentinel',
           'window_bisect']


def window_size(flag='MXTPU_FIT_STEPS_PER_CALL'):
    """Window size W from the given env flag; 0 = auto (32 on TPU —
    where each dispatch crosses a tunnel RTT — and 4 elsewhere, enough
    to exercise the windowed path in CPU tests)."""
    from ..config import flags
    flags.reload(flag)
    n = flags.get(flag)
    if n > 0:
        return n
    return 32 if jax.default_backend() == 'tpu' else 4


def registered_jit(name, fn, step_flops=False, **jit_kwargs):
    """``jax.jit`` + telemetry program registration in one step — the
    compile-site idiom both fused loops use. With telemetry on, the
    returned callable compiles via an explicit ``lower().compile()``
    and the executable's XLA cost/memory analysis lands in the
    per-program table (telemetry.programs); ``step_flops=True`` marks
    the program whose FLOPs define a training step (feeds the MFU
    estimate). With telemetry off this is exactly ``jax.jit(fn)``."""
    return _tele.programs.register(name, jax.jit(fn, **jit_kwargs),
                                   step_flops=step_flops)


def health_sentinel():
    """The in-graph training-health stats fn for a compiled window body
    (telemetry/health: grad/param norms, update ratio, per-output
    finite flags packed into one f32 vector per step, stacked by the
    scan so a mid-window NaN carries its exact step index through the
    window's single host fetch) — or None while the sentinels are off,
    leaving the traced window byte-identical to today's program."""
    from ..telemetry import health as _health
    return _health.step_stats if _health.enabled() else None


def dynamics_sentinel():
    """The in-graph per-layer dynamics stats fn for a compiled window
    body (telemetry/dynamics: per-layer grad/param norms + update
    ratios and per-output activation zero-fractions packed into one
    f32 vector per step, stacked by the scan so the (W, k) matrix
    rides the window's single host fetch) — or None while
    MXTPU_DYNAMICS is off, leaving the traced window byte-identical
    to today's program."""
    from ..telemetry import dynamics as _dynamics
    return _dynamics.step_stats if _dynamics.enabled() else None


def window_bisect(executor, data_names, label_names, snaps, is_train,
                  defer_fn=None):
    """First-bad-layer driver for a fused-window incident: returns
    ``bisect(i)`` replaying window step ``i``'s draw-time snapshot
    through the staged per-node executor path
    (:meth:`~mxnet_tpu.executor.Executor.first_nonfinite_node`).
    ``defer_fn`` materializes a deferred uint8 batch (fused fit's
    device-augment mode) so the replay sees the graph's real input."""
    def bisect(i):
        ds, ls, _, _ = snaps[i]
        if defer_fn is not None:
            from .. import random as _random
            ds = (defer_fn(ds[0], _random.next_key()),) + tuple(ds[1:])
        overrides = dict(zip(data_names, ds))
        overrides.update(zip(label_names, ls))
        return executor.first_nonfinite_node(overrides, is_train=is_train)
    return bisect


def host_device():
    """The host (cpu-backend) jax device, or None when unavailable."""
    try:
        return jax.local_devices(backend='cpu')[0]
    except RuntimeError:
        return None


def host_wrap(ctx):
    """Returns ``host_nd(a)``: a cpu-backed NDArray wrapper for
    already-host data, so downstream ``.asnumpy()`` calls (metric math,
    user code) cost no device round-trip."""
    dev = host_device()

    def host_nd(a):
        arr = jax.device_put(np.asarray(a), dev) if dev is not None \
            else jnp.asarray(a)
        return from_jax(arr, ctx)

    return host_nd


# ---------------------------------------------------------------------------
# metric plans: in-graph sufficient statistics + host-side apply
# ---------------------------------------------------------------------------

def _plan_one(m):
    """(stats_fn(outs, labels) -> (sum, count)) for one metric, or None
    if unsupported. Statistics mirror metric.py's numpy math — in
    particular every reference metric RAVELS the label, so an (N, 1)
    column label (CSVIter and friends) compares elementwise against the
    (N,) argmax instead of broadcasting into an (N, N) matrix."""
    if type(m) is metric_mod.Accuracy:
        if getattr(m, 'axis', 1) != 1:
            return None     # stats below assume 2-D preds, class axis 1
        def stats(outs, labels):
            pred = outs[0]
            lab = labels[0].reshape(-1).astype(jnp.int32)
            hit = jnp.argmax(pred, axis=-1).astype(jnp.int32) == lab
            return jnp.sum(hit).astype(jnp.float32), \
                jnp.float32(hit.size)
        return stats
    if type(m) is metric_mod.TopKAccuracy:
        k = m.top_k

        def stats(outs, labels, k=k):
            pred = outs[0]
            lab = labels[0].reshape(-1).astype(jnp.int32)
            # reference TopKAccuracy clamps: top_k = min(classes, k)
            # (lax.top_k would raise past the minor dim, where the
            # per-batch loop computes a valid result)
            _, idx = jax.lax.top_k(pred, min(k, pred.shape[-1]))
            hit = jnp.any(idx.astype(jnp.int32) == lab[:, None], axis=-1)
            return jnp.sum(hit).astype(jnp.float32), \
                jnp.float32(hit.size)
        return stats
    if type(m) is metric_mod.CrossEntropy:
        eps = getattr(m, 'eps', 1e-12)

        def stats(outs, labels, eps=eps):
            pred = outs[0]
            lab = labels[0].reshape(-1).astype(jnp.int32)
            p = jnp.take_along_axis(pred, lab[:, None], axis=-1)[:, 0]
            return jnp.sum(-jnp.log(p + eps)).astype(jnp.float32), \
                jnp.float32(lab.size)
        return stats
    return None


def plan_metric(eval_metric, out_shapes=None, label_names=None):
    """Returns (children, [stats_fn]) where children are the leaf
    EvalMetric objects to update, or None if any leaf is unsupported.
    When ``out_shapes``/``label_names`` are given, also enforces the
    geometry every stat fn assumes — ONE 2-D (batch, classes) output
    with classes >= 2 (reference Accuracy SKIPS the argmax on a
    width-1 class dim and compares raw values) and one label — so the
    fit and eval loops cannot drift on the eligibility condition."""
    if out_shapes is not None and (
            len(out_shapes) != 1 or len(out_shapes[0]) != 2
            or out_shapes[0][1] < 2
            or (label_names is not None and len(label_names) != 1)):
        return None
    if isinstance(eval_metric, metric_mod.CompositeEvalMetric):
        children = list(eval_metric.metrics)
    else:
        children = [eval_metric]
    fns = []
    for m in children:
        fn = _plan_one(m)
        if fn is None:
            return None
        fns.append(fn)
    return children, fns


def place_replicated(mesh, *trees):
    """device_put every array in the given pytrees onto the mesh's
    fully-replicated sharding (no-op for arrays already there): on an
    SPMD group every array a compiled window closes over must live
    replicated on the mesh, or jit rejects the mixed-device argument
    set. Returns the trees in call order."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(mesh, P())
    put = lambda a: a if getattr(a, 'sharding', None) == rep \
        else jax.device_put(a, rep)  # noqa: E731
    return tuple(jax.tree_util.tree_map(put, t) for t in trees)


def is_update_sharded(a, row):
    """Whether ``a`` is already in the ZeRO update-phase form for row
    sharding ``row`` (1-D and equivalently sharded) — jit outputs may
    come back under an equivalent-but-distinct sharding object, so
    plain equality is not enough."""
    if getattr(a, 'ndim', 0) != 1:
        return False
    sh = getattr(a, 'sharding', None)
    if sh is None:
        return False
    if sh == row:
        return True
    try:
        return sh.is_equivalent_to(row, 1)
    except Exception:  # noqa: BLE001 — sharding impl without the probe
        return False


def place_update_sharded(mesh, arrays_with_shapes):
    """Place optimizer-state leaves in the ZeRO update-phase layout
    (arXiv:2004.13336): each ``(array, canonical_shape)`` pair comes
    back as a 1-D leaf zero-padded to a multiple of dp and row-sharded
    over the mesh's dp axis (executor_group.SPMDExecutorGroup.
    update_sharding) — 1/dp of every leaf per device. Arrays already in
    that form pass through untouched, so the per-window snapshot is a
    no-op in steady state and the conversion runs only on entry to the
    fused path (first window, after a restore, after a flush)."""
    import jax
    from .executor_group import SPMDExecutorGroup
    from ..parallel.sharding import zero_flatten, zero_pad_len
    row = SPMDExecutorGroup.update_sharding(mesh)
    dp = int(mesh.shape['dp'])
    out = []
    for a, shape in arrays_with_shapes:
        padded = zero_pad_len(int(np.prod(shape)) if shape else 1, dp)
        if is_update_sharded(a, row) and int(a.shape[0]) == padded:
            out.append(a)
            continue
        out.append(jax.device_put(zero_flatten(a, dp), row))
    return out


def rebind_children(eval_metric, current_children):
    """Point a cached loop's stat writeback at the CURRENT call's
    metric objects (each call may construct fresh instances from the
    same config — exactly what the loops' reuse signatures guarantee,
    so the stat fns, which capture only config values like top_k/eps,
    stay valid). Returns the new children list (or the old one for a
    loop without in-graph stats)."""
    if isinstance(eval_metric, metric_mod.CompositeEvalMetric):
        return list(eval_metric.metrics)
    if current_children is not None:
        return [eval_metric]
    return current_children


# ---------------------------------------------------------------------------
# the pipeline
# ---------------------------------------------------------------------------

class WindowPipeline:
    """Draw/stack/upload machinery for one compiled-window loop.

    ``device_fn`` resolves the target jax device lazily (the bound
    executor's context); ``mesh`` switches placement to dp-sharded
    window stacks. ``span_prefix`` names the telemetry spans
    ('fused_fit' / 'fused_eval'). The owning loop object lives across
    fit()/score() calls, so the upload pool it carries does too.
    """

    def __init__(self, window, device_fn, mesh=None, span_prefix='window',
                 donate=False):
        self.window = window
        self.mesh = mesh
        self._device_fn = device_fn
        self._span = span_prefix
        self._dev_cache_key = None
        self._dev_cache = None
        self._pool_obj = None
        # donate=True: the consuming program DONATES the window stacks
        # to XLA, so a device stack handed out once is dead — the
        # identity cache then holds the HOST-side stacked arrays (the
        # np.stack memcpy is still saved) and the device transfer runs
        # fresh per window. The owning loop sets it to match its
        # program's donate_argnums (fused_fit honors MXTPU_FUSED_DONATE;
        # fused_eval never donates its read-only stacks).
        self.donate = donate

    # -- draw --------------------------------------------------------------
    def collect(self, it, limit=None):
        """Draw up to ``window`` batches (further bounded by ``limit``,
        the eval loops' num_batch remainder), snapshotting each batch's
        underlying jax arrays, pad, and index AT DRAW TIME. Returns
        (batches, snaps) with snaps a list of (data_arrays,
        label_arrays, pad, index) tuples."""
        n = self.window if limit is None else min(self.window, limit)
        batches, snaps = [], []
        with _tele.span(self._span + '.draw', self._span):
            while len(batches) < n:
                try:
                    b = next(it)
                except StopIteration:
                    break
                batches.append(b)
                snaps.append((tuple(a._data for a in b.data),
                              tuple(l._data for l in (b.label or ())),
                              getattr(b, 'pad', None),
                              getattr(b, 'index', None)))
        return batches, snaps

    # -- stack + upload ----------------------------------------------------
    def device_batches(self, snaps):
        """Stack W draw-time snapshots into device (W, ...) arrays.
        Identity-cached: synthetic/benchmark iterators yield the same
        arrays every batch, so the transfer happens once. The cache key
        holds STRONG references to the source arrays — identity is
        compared against live objects, so a freed array's id can never
        produce a false hit.

        With ``donate`` set the device stacks are consumed by the
        dispatch, so the cache stores the HOST-side stacks (or, for
        device-resident sources, the unstacked parts) instead and
        re-runs the device transfer per window (the prefetch pool hides
        it behind window k's compute) — returning a cached device array
        would hand the program an already-deleted donated buffer."""
        arrays = [a for ds, ls, _, _ in snaps for a in ds + ls]
        if self._dev_cache_key is not None and \
                len(arrays) == len(self._dev_cache_key) and \
                all(a is c for a, c in zip(arrays, self._dev_cache_key)):
            if not self.donate:
                return self._dev_cache
            data_e, label_e = self._dev_cache
            return (tuple(self._realize(e) for e in data_e),
                    tuple(self._realize(e) for e in label_e))
        key = arrays

        def _on_host(a):
            if isinstance(a, np.ndarray):
                return True
            try:
                return all(d.platform == 'cpu' for d in a.devices())
            except Exception:  # noqa: BLE001 — tracer/abstract array
                return False

        def build(parts):
            # host-resident parts (defer-mode uint8 batches and their
            # labels) stack on the host so the whole window crosses to
            # the device in _realize()'s ONE device_put — W per-batch
            # transfers each cost a full dispatch RTT on a tunneled
            # runtime. Device-resident parts stay unstacked in the
            # cache entry (the stacked device buffer is donate-consumed,
            # but the sources remain valid to restack from).
            if all(_on_host(p) for p in parts):
                return ('host', np.stack([np.asarray(p) for p in parts]))
            return ('dev', tuple(parts))

        data_e = [build([ds[i] for ds, _, _, _ in snaps])
                  for i in range(len(snaps[0][0]))]
        label_e = [build([ls[i] for _, ls, _, _ in snaps])
                   for i in range(len(snaps[0][1]))]
        data_stack = tuple(self._realize(e) for e in data_e)
        label_stack = tuple(self._realize(e) for e in label_e)
        self._dev_cache_key = key
        self._dev_cache = (data_e, label_e) if self.donate \
            else (data_stack, label_stack)
        return data_stack, label_stack

    def _realize(self, entry):
        """One cache entry -> a fresh placed device stack."""
        kind, v = entry
        stack = v if kind == 'host' \
            else jnp.stack([jnp.asarray(p) for p in v])
        return self._shard(stack)

    def _shard(self, stack):
        if self.mesh is None:
            # source arrays may be committed to the host device
            # (cpu_pinned iterators); the window runs where the
            # executor's params live
            return jax.device_put(stack, self._device_fn())
        from .executor_group import SPMDExecutorGroup
        return jax.device_put(
            stack, SPMDExecutorGroup.window_sharding(self.mesh,
                                                     stack.ndim))

    def pool(self):
        """One-thread executor for the pipelined window upload. A
        single worker keeps transfers ordered; the owning loop (cached
        on the module across calls) keeps it for its lifetime."""
        if self._pool_obj is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool_obj = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix='mxtpu-window-put')
        return self._pool_obj

    def start_put(self, snaps, pool):
        """Begin the window's host-stack + device transfer; returns a
        no-arg resolver. With a pool, the stack + put for window k+1
        run on the side thread while window k computes on device, the
        previous window's stats fetch waits, and the optimizer's
        host-side window bookkeeping runs — the update/upload overlap.

        The resolver carries ``hidden_ms`` after it is called: the
        share of the side thread's stack+put wall time the main thread
        did NOT wait for (put duration minus blocked time) — the
        ``fused_fit.overlap_ms`` evidence that the transfer actually
        hid under host work rather than serializing in front of the
        dispatch."""
        import time
        if pool is None:
            res = self.device_batches(snaps)

            def resolver():
                return res
            resolver.hidden_ms = 0.0   # serial mode hides nothing
            return resolver
        done = {}

        def task():
            t0 = time.perf_counter()
            try:
                return self.device_batches(snaps)
            finally:
                done['dur'] = time.perf_counter() - t0
        fut = pool.submit(task)

        def resolver():
            t0 = time.perf_counter()
            out = fut.result()
            waited = time.perf_counter() - t0
            resolver.hidden_ms = max(
                0.0, done.get('dur', 0.0) - waited) * 1e3
            return out
        resolver.hidden_ms = 0.0
        return resolver

    @staticmethod
    def drain(fut):
        """Resolve an in-flight prefetch before teardown (or an
        exception unwind) can race the side thread."""
        if fut is not None:
            try:
                fut()
            except Exception:  # noqa: BLE001 — primary error wins
                pass

    def drop_cache(self):
        """Release the last window's device stack + its strong host
        refs — the identity cache only ever hits while an epoch/pass
        is running."""
        self._dev_cache_key = None
        self._dev_cache = None
