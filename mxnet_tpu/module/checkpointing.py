"""Periodic async sharded training checkpoints + restart-from-last-good.

The observability arc can *detect* a dying run (telemetry/health raises
``TrainingHealthError`` with a first-bad-layer diagnostic); this module
is the half that *acts* on it. ``BaseModule.fit`` builds a
:class:`TrainCheckpointer` from the env flags and drives it from both
train loops (the per-batch reference loop and the fused window loop):

- every ``MXTPU_CKPT_EVERY`` trained steps the FULL training state —
  parameters, aux (BatchNorm) state, optimizer state + update counts,
  every framework RNG stream, the epoch/step cursor and the eval-metric
  partial sums — is captured as immutable array references (plus one
  device-side copy per array, so the fused window's buffer donation can
  never invalidate an in-flight write) and handed to a background
  writer. The write itself goes through ``parallel/checkpoint.py``'s
  orbax tier: each host writes only its own shards (arXiv:2004.13336's
  state-lives-sharded principle), so save cost scales with per-host
  bytes, not model size. The step loop never blocks on it.
- ``max_to_keep`` pruning rides orbax (``MXTPU_CKPT_KEEP``).
- a **last-good pointer** (``last_good.step`` in the checkpoint dir)
  only advances past a saved step once the write has committed AND the
  health plane has seen every step it covers finite. A checkpoint
  captured after a NaN trained into the parameters is never certified.
- ``MXTPU_CKPT_RESUME`` (default on): a fresh ``fit()`` against a
  directory holding a certified checkpoint restores it bit-exactly —
  restore targets the live arrays' dtypes/shardings (orbax
  restore-into-template), the optimizer update counts and RNG streams
  come back, epochs already trained are skipped, and the data iterator
  is rewound + skipped to the restored step (``seed_epoch(epoch)`` is
  called on iterators that support deterministic per-epoch reseeding).

Degradation ladder (a checkpointing failure must never kill training):
async writer dies -> synchronous saves; those fail repeatedly ->
checkpointing disabled with a warning; restore of a corrupt step ->
fall back to the next older committed step; nothing restorable ->
start fresh. ``module/resilient_fit.py`` and
``tools/train_supervisor.py`` build the restart loop on top.

All flags off = nothing here runs: ``for_fit`` returns None before
touching orbax, no thread exists, and no op is ever traced (the whole
subsystem is host-side).
"""
import logging
import os

import numpy as np

from .. import faults as _faults
from .. import random as _random
from .. import telemetry as _tele

__all__ = ['TrainCheckpointer', 'enabled', 'read_pointer', 'write_pointer',
           'agree_pointer', 'remap_cursor']

_POINTER = 'last_good.step'
_MAX_SAVE_FAILURES = 3
_FORMAT = 1


# ---------------------------------------------------------------------------
# pointer + cursor primitives (module-level: shared by TrainCheckpointer
# and multi-process drivers that checkpoint outside Module.fit, e.g. the
# gang workers tests/dist/gang_fit.py supervises)
# ---------------------------------------------------------------------------

def write_pointer(directory, step):
    """Atomically write the ``last_good.step`` pointer. The raw file
    op: multi-process callers must agree first (:func:`agree_pointer`)
    — in a gang only process 0 writes, and only a step every host has
    committed and health-cleared."""
    tmp = os.path.join(str(directory), _POINTER + '.tmp')
    with open(tmp, 'w') as f:
        f.write('%d\n' % int(step))
    os.replace(tmp, os.path.join(str(directory), _POINTER))


def read_pointer(directory):
    """The certified last-good step recorded in ``directory``, or None
    when no pointer exists (nothing was ever certified)."""
    try:
        with open(os.path.join(str(directory), _POINTER)) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def agree_pointer(directory, local_step, round_id, logger=logging):
    """Advance the last-good pointer by CROSS-HOST agreement: every
    host contributes the newest step it has locally committed and
    health-cleared (``local_step``; <= 0 = none yet), the agreed step
    is the minimum over hosts, and process 0 alone writes the pointer.
    A relaunched gang can therefore never restore a step some host
    never finished writing — the divergent-restore failure the
    per-host pointer write had. Single-process this degenerates to the
    local write. ``round_id`` must advance identically on every host
    (agreement rounds run at lockstep points of the training schedule).
    Returns the agreed step, or None when no step is agreed (nothing
    certified anywhere, or a host died mid-agreement — the bounded
    timeout turns that into "pointer unchanged", never a wedge)."""
    from ..parallel import multihost as _mh
    local = int(local_step) if local_step and int(local_step) > 0 else -1
    agreed = _mh.agree_min('ckpt.ptr.%s' % round_id, local)
    if agreed is None:
        logger.warning(
            'checkpointing: cross-host last-good agreement round %s '
            'failed — pointer unchanged', round_id)
        return None
    if agreed <= 0:
        return None
    if _mh.is_primary():
        try:
            write_pointer(directory, agreed)
        except OSError as e:
            logger.warning(
                'checkpointing: cannot write last-good pointer (%s)', e)
            return None
    return int(agreed)


def remap_cursor(r_step, old_p, new_p):
    """Translate a per-host step-in-epoch cursor saved by ``old_p``
    processes into ``new_p``-process units: the same trained SAMPLE
    count lands at ``step * old_p / new_p``. Returns ``(scaled,
    remainder)`` — a nonzero remainder means the division was inexact
    and the caller should round DOWN (retrain a few batches from the
    restored, finite, parameters rather than skip unseen data)."""
    return divmod(int(r_step) * int(old_p), int(new_p))


def _gang_processes():
    """Process count of the live multi-process job, or 1. Checked via
    the coordination client FIRST so a single-host run never touches
    the jax backend just to learn it is alone."""
    from ..parallel import multihost as _mh
    if _mh._client() is None:
        return 1
    import jax
    return int(jax.process_count())


def _flags():
    from ..config import flags
    for name in ('MXTPU_CKPT_DIR', 'MXTPU_CKPT_EVERY', 'MXTPU_CKPT_KEEP',
                 'MXTPU_CKPT_ASYNC', 'MXTPU_CKPT_RESUME'):
        flags.reload(name)
    return (flags.get('MXTPU_CKPT_DIR'), flags.get('MXTPU_CKPT_EVERY'),
            flags.get('MXTPU_CKPT_KEEP'), flags.get('MXTPU_CKPT_ASYNC'),
            flags.get('MXTPU_CKPT_RESUME'))


def enabled():
    """Whether the checkpoint flags ask for periodic saves."""
    try:
        d, every, _, _, _ = _flags()
    except Exception:  # noqa: BLE001 — stripped builds without the flags
        return False
    return bool(d) and every > 0


def _metric_children(eval_metric):
    from .. import metric as metric_mod
    if isinstance(eval_metric, metric_mod.CompositeEvalMetric):
        return list(eval_metric.metrics)
    return [eval_metric]


class TrainCheckpointer:
    """One fit() call's checkpoint/resume driver (built by
    :meth:`for_fit`, driven by the fit loops)."""

    def __init__(self, module, eval_metric, directory, every, keep,
                 async_, logger=logging):
        from ..parallel import checkpoint as ckpt
        self._ckpt = ckpt
        self.module = module
        self.eval_metric = eval_metric
        self.directory = os.path.abspath(str(directory))
        self.every = int(every)
        self.logger = logger
        self._async = bool(async_)
        self._mngr = ckpt.manager(self.directory, max_to_keep=keep)
        self._param_names = list(module._exec_group.param_names)
        self._aux_names = list(module._exec_group.aux_names)
        self._grad_names = list(self._exec._grad_names)
        from .fused_fit import updater_keys
        self._upd_keys = updater_keys(module, self._grad_names)
        self._accum = (module._grad_req == 'add')

        self.global_step = 0
        self.epoch = 0
        self.step_in_epoch = 0
        self.epoch_nbatch_base = 0  # resumed epoch: first nbatch value
        self.resumed_epoch = None  # epoch whose batches were skipped
        self._checked = 0          # steps the health plane has verified
        self._last_save = 0
        self._initiated = 0        # newest step a save actually started
        self._pending = []   # [step, nonfinite_at_capture, future, cleared]
        self._pool = None
        self._failures = 0
        self._disabled = False
        self._resume = None        # (epoch, step_in_epoch, metric_state)
        self._io_shard = None      # live iterator's shard assignment
        self.last_good = None
        self.restored_step = None
        self.resharded_from = None  # saving mesh of an N->M restore
        # gang mode (a real multi-process jax.distributed job): saves
        # are collectives, so the busy-writer skip must be agreed
        # globally, and the last-good pointer advances only by
        # cross-host agreement with process 0 writing the file
        self._gang = _gang_processes() > 1
        self._certified = 0        # newest LOCALLY committed+cleared step
        # agreement-round naming: derived from (global_step, per-step
        # sequence) — both lockstep quantities — NEVER from counters
        # that advance on per-host success paths (a lone host's
        # disk-full/capture failure must not shear the gang's round
        # names and wedge every later agreement into timeouts)
        self._round_step = -1
        self._round_k = 0
        # incident count at fit start: any NEW incident this attempt
        # marks every later capture uncertifiable (see _promote) —
        # while counts from a PREVIOUS attempt of the same process
        # (resilient_fit retry) don't freeze the restored run
        self._nf_base = self._nonfinite_count() or 0

    @property
    def _exec(self):
        # read fresh every time: a mid-fit reshape rebuilds the
        # executor list, and a capture against the orphaned old
        # executor would silently checkpoint stale parameters
        return self.module._exec_group.execs[0]

    # -- construction ------------------------------------------------------
    @classmethod
    def for_fit(cls, module, eval_metric, logger=logging):
        """Build (and maybe resume) the fit loop's checkpointer, or None
        when the flags are off / the module shape is unsupported. Any
        failure here warns and disables checkpointing — it never stops
        the fit."""
        module.__dict__.pop('_mxtpu_ckpt', None)
        try:
            directory, every, keep, async_, resume = _flags()
        except Exception:  # noqa: BLE001
            return None
        if not directory or every <= 0:
            return None
        group = getattr(module, '_exec_group', None)
        if group is None:
            logger.warning(
                'checkpointing: MXTPU_CKPT_DIR is set but %s does not '
                'expose an executor group — periodic checkpoints need '
                'the standard Module; continuing without checkpoints',
                type(module).__name__)
            return None
        execs = getattr(group, 'execs', None) or []
        if len(execs) != 1:
            logger.warning(
                'checkpointing: MXTPU_CKPT_DIR is set but the module '
                'binds %d executors — periodic checkpoints support the '
                'single-program (SPMD or single-context) path only; '
                'continuing without checkpoints', len(execs))
            return None
        try:
            self = cls(module, eval_metric, directory, every, keep,
                       async_, logger=logger)
        except Exception as e:  # noqa: BLE001 — bad dir/missing orbax
            logger.warning('checkpointing: cannot open %s (%s) — '
                           'continuing without checkpoints', directory, e)
            return None
        if resume:
            try:
                self._try_resume()
            except Exception as e:  # noqa: BLE001
                logger.warning('checkpointing: resume failed (%s) — '
                               'starting fresh', e)
                self._resume = None
        module.__dict__['_mxtpu_ckpt'] = self
        # watchdog-abort drain: a hang abort (os._exit from the monitor
        # thread) must still commit + certify the in-flight save — the
        # wedged main thread never reaches finish()/handle_failure()
        _tele.watchdog.add_abort_hook(self._abort_drain)
        return self

    # -- state capture -----------------------------------------------------
    def _updater(self):
        from .fused_fit import updater_obj
        return updater_obj(self.module)

    def _ensure_opt_states(self):
        from .fused_fit import ensure_opt_states
        ensure_opt_states(self.module, self._grad_names, self._upd_keys,
                          self._exec.arg_dict)

    def _walk_opt(self, copy):
        """(structure, arrays): the optimizer-state tree flattened into
        deterministically-named array leaves. ``copy`` guards against
        the fused window's buffer donation; the template pass (restore)
        walks the same order with copy=False.

        A leaf the fused loop holds in the ZeRO update-phase form
        (flat, zero-padded, dp-sharded — fused_fit's sharded weight
        update) is captured AS STORED — each host writes only its own
        shards — and its structure entry becomes
        ``{'k': 'opt.N', 'shape': [canonical...]}`` so a restore,
        possibly onto a different dp, can reshape it back. Plain string
        entries stay the format for canonical leaves (and are what old
        checkpoints hold)."""
        self._ensure_opt_states()
        upd = self._updater()
        from .fused_fit import zero_shape_probe
        probe = zero_shape_probe(self.module)
        # canonical (non-ZeRO) leaves get the same GSPMD->NamedSharding
        # relabel as params: window outputs leave them GSPMD-labeled too
        ccopy = self._canon_copy() if copy else None
        arrays = {}
        counter = [0]

        def enc(v):
            import jax.numpy as jnp
            if v is None:
                return None
            if isinstance(v, tuple):
                return [enc(x) for x in v]
            k = 'opt.%d' % counter[0]
            counter[0] += 1
            zshape = probe(v) if probe is not None else None
            if zshape is not None:
                # ZeRO leaf: captured AS SHARDED (plain copy — ccopy
                # would reshard it replicated and defeat the each-host-
                # writes-its-shards property)
                arrays[k] = jnp.copy(v._data) if copy else v._data
                if getattr(probe, 'row', None) is not None:
                    # relabel the (equivalent) jit-output GSPMDSharding
                    # onto the canonical NamedSharding: same shards,
                    # but orbax can serialize it without warning
                    import jax
                    arrays[k] = jax.device_put(arrays[k], probe.row)
                return {'k': k, 'shape': list(zshape)}
            arrays[k] = ccopy(v._data) if copy else v._data
            return k

        structure = [[n, enc(upd.states[self._upd_keys[n]])]
                     for n in self._grad_names]
        return structure, arrays

    def _opt_bookkeeping(self):
        o = self.module._optimizer
        return {'num_update': int(o.num_update),
                'index_update_count': [[k, int(v)] for k, v in
                                       sorted(o._index_update_count.items(),
                                              key=str)]}

    def _canon_copy(self):
        """``jnp.copy`` with the PR-9 sharding relabel extended from
        opt-state leaves to params/aux/grad-accum: a leaf captured from
        a fused-window OUTPUT carries a jit-produced ``GSPMDSharding``
        — orbax warns on (de)serializing it at every save AND every
        later load. The canonical checkpoint form for these leaves is
        the mesh-replicated ``NamedSharding``: when the window output
        is replicated-equivalent the ``device_put`` is a pure relabel
        (same shards), and when XLA's partitioner chose to emit a
        param genuinely sharded (it does — e.g. a [4,2] layout on the
        8-device mesh) the put is a real reshard onto the canonical
        layout, paid once per checkpoint cadence, never per step.
        ZeRO opt-state leaves never come through here — they stay
        dp-sharded under their own canonical ``NamedSharding``
        (``_walk_opt``'s probe.row), so each host still writes only
        its own shards."""
        import jax
        import jax.numpy as jnp
        mesh = getattr(self.module._exec_group, 'mesh', None)
        if mesh is None:
            return jnp.copy
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(mesh, P())

        def copy(a):
            sh = getattr(a, 'sharding', None)
            if sh is not None and not isinstance(sh, NamedSharding):
                try:
                    if not sh.is_equivalent_to(rep, a.ndim):
                        # genuinely sharded window output: the cross-
                        # layout put materializes fresh replicated
                        # buffers — the reshard IS the donation-proof
                        # copy (probed: equivalent-sharding puts ALIAS
                        # the source instead, hence the branch)
                        return jax.device_put(a, rep)
                    return jax.device_put(jnp.copy(a), rep)
                except Exception:  # noqa: BLE001 — an unplaceable
                    pass           # layout: fall through to the copy
            return jnp.copy(a)

        return copy

    def _capture(self):
        """The checkpoint pytree + its JSON metadata, captured on the
        MAIN thread so it names a consistent step. Arrays are device
        copies (async dispatches — cheap): the originals may be donated
        to the very next compiled window while the write is in flight.
        The RNG key is tiny, so it rides the JSON meta item — the
        array tree stays fully restorable from the live template."""
        e = self._exec
        ccopy = self._canon_copy()
        tree = {
            'params': {n: ccopy(e.arg_dict[n]._data)
                       for n in self._param_names},
            'aux': {n: ccopy(e.aux_dict[n]._data)
                    for n in self._aux_names},
        }
        structure, opt_arrays = self._walk_opt(copy=True)
        if opt_arrays:
            tree['opt'] = opt_arrays
        if self._accum:
            tree['gacc'] = {n: ccopy(e.grad_dict[n]._data)
                            for n in self._grad_names}
        rng = _random.get_state()
        key = rng.pop('key')
        if key is not None:
            key = np.asarray(key)
            rng['key_values'] = key.tolist()
            rng['key_dtype'] = str(key.dtype)
        metric_state = [[type(c).__name__, float(c.sum_metric),
                         int(c.num_inst)]
                        for c in _metric_children(self.eval_metric)]
        meta = {'format': _FORMAT, 'epoch': int(self.epoch),
                'step_in_epoch': int(self.step_in_epoch),
                'global_step': int(self.global_step),
                'opt_structure': structure,
                'opt_bookkeeping': self._opt_bookkeeping(),
                'metric': metric_state, 'rng_host': rng,
                'grad_req': self.module._grad_req}
        # reshard-on-restore sidecar: the SAVING mesh and every leaf's
        # GLOBAL shape. Global shapes are mesh-independent, so a later
        # restore onto fewer (or more) devices/hosts validates against
        # these and lets orbax re-lay the shards out to the new mesh;
        # the io record lets the resume remap its iterator cursor when
        # the process set changed (every example still covered once)
        try:
            from ..parallel import multihost as _mh
            meta['mesh'] = _mh.mesh_descriptor()
        except Exception:  # noqa: BLE001 — never block a save on this
            pass
        meta['shapes'] = self._ckpt.template_shapes(tree)
        if self._io_shard is not None:
            meta['io'] = dict(self._io_shard)
        return tree, meta

    # -- save --------------------------------------------------------------
    def _nonfinite_count(self):
        """health.nonfinite_steps right now, or None while the health
        plane is off (no gate to wait for)."""
        if not _tele.health.enabled():
            return None
        return int(_tele.get_registry()
                   .counter('health.nonfinite_steps').value)

    def _do_save(self, step, tree, meta):
        """The actual write (worker thread in async mode): one orbax
        save + barrier, then the fault-injection corrupt seam."""
        with _tele.span('ckpt.save', 'ckpt'):
            ok = self._ckpt.save(self._mngr, step, tree, wait=True,
                                 meta=meta)
        if ok is False and self._gang:
            # the cross-host commit confirmation timed out: some host
            # may still be mid-write, so THIS host must not certify the
            # step (the raise routes it through the failure path; the
            # min-agreement means the pointer cannot advance past it
            # until every host eventually certifies)
            raise RuntimeError(
                'commit confirmation barrier failed for step %d' % step)
        _faults.maybe_corrupt_checkpoint(self.directory, step)
        _tele.counter('ckpt.saves').inc()
        # a committed save is forward progress even when the step loop
        # is briefly quiet (sync fallback mode)
        _tele.watchdog.note_progress('ckpt.save')

    def _round_id(self, tag):
        """A gang agreement-round name every host derives identically:
        (tag, global step, per-step call sequence). The call SITES are
        lockstep by construction (save cadence crossings, fit end) and
        the ids carry no per-host state, so one host's local failure
        can never desynchronize later rounds' names."""
        if self._round_step != self.global_step:
            self._round_step = self.global_step
            self._round_k = 0
        self._round_k += 1
        return 'ckpt.%s.%d.%d' % (tag, self.global_step, self._round_k)

    def _initiate_save(self):
        step = self.global_step
        if not step or (self._disabled and not self._gang):
            return
        busy = bool([p for p in self._pending if p[2] is not None
                     and not p[2].done()])
        if self._gang:
            # the save is a collective (each host writes its shards
            # into ONE orbax commit): either every host initiates it or
            # none does. Each host votes with its FULL local readiness
            # — writer busy, checkpointing disabled, or the capture
            # itself failing (taken BEFORE the vote: a host that
            # discovers a capture failure after the others committed to
            # a collective save would wedge them in orbax's barrier) —
            # and any not-ready vote skips the save for the whole gang
            tree = meta = None
            if not busy and not self._disabled:
                try:
                    with _tele.span('ckpt.capture', 'ckpt'):
                        tree, meta = self._capture()
                except Exception as e:  # noqa: BLE001 — never kill
                    self._note_failure('state capture failed: %s' % e)
            from ..parallel import multihost as _mh
            any_skip = _mh.agree_any(self._round_id('busy'),
                                     tree is None)
            # a failed agreement (a host died mid-exchange) must skip:
            # initiating a collective save with a dead peer wedges
            if any_skip is None or any_skip or tree is None:
                _tele.counter('ckpt.skipped').inc()
                return
            # the GANG committed to this save: record the initiation
            # now, lockstep, so finish()'s re-initiate decision stays
            # identical on every host even if a local submit/sync
            # failure below keeps this host's write from landing
            self._initiated = step
        else:
            if busy:
                # the writer is still on a previous step: drop this
                # save rather than queue unboundedly behind slow
                # storage (finish() re-initiates after draining, so the
                # run's final state is never lost to a slow writer)
                _tele.counter('ckpt.skipped').inc()
                return
            try:
                with _tele.span('ckpt.capture', 'ckpt'):
                    tree, meta = self._capture()
            except Exception as e:  # noqa: BLE001 — never kill training
                self._note_failure('state capture failed: %s' % e)
                return
        nf0 = self._nonfinite_count()
        # health-cleared at birth when the sentinels already checked
        # through this step (lag=0 paths): later incidents then belong
        # to LATER steps and must not taint this capture
        cleared = nf0 is None or self._checked >= step
        if self._async:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix='mxtpu-ckpt')
            try:
                fut = self._pool.submit(self._do_save, step, tree, meta)
            except Exception as e:  # noqa: BLE001 — pool torn down
                self.logger.warning(
                    'checkpointing: async writer unavailable (%s) — '
                    'falling back to synchronous saves', e)
                self._async = False
                fut = None
            if fut is not None:
                self._initiated = step
                self._pending.append([step, nf0, fut, cleared])
                return
        try:
            self._do_save(step, tree, meta)
        except Exception as e:  # noqa: BLE001
            self._note_failure('save of step %d failed: %s' % (step, e))
            return
        self._initiated = step
        self._pending.append([step, nf0, None, cleared])

    def _note_failure(self, msg):
        self._failures += 1
        _tele.counter('ckpt.save_failures').inc()
        if self._failures >= _MAX_SAVE_FAILURES:
            self._disabled = True
            self.logger.warning(
                'checkpointing: %s — %d consecutive failures, disabling '
                'checkpoints for this run (training continues)', msg,
                self._failures)
        else:
            self.logger.warning(
                'checkpointing: %s — training continues', msg)

    # -- last-good promotion -----------------------------------------------
    def _write_pointer(self, step):
        if not self._gang:
            write_pointer(self.directory, step)
        else:
            # gang rollback sites (a failed restore falling back to an
            # older committed step) write a value every host derived
            # from the same shared files — process 0 alone touches it
            from ..parallel import multihost as _mh
            if _mh.is_primary():
                write_pointer(self.directory, step)
        self.last_good = int(step)
        _tele.gauge('ckpt.last_good').set(int(step))

    @staticmethod
    def read_pointer(directory):
        return read_pointer(directory)

    def _promote(self, bound=None, final=False):
        """Advance the last-good pointer over committed saves the
        health plane has certified. A pending save at step k promotes
        once its write landed AND the sentinels checked through step k
        with no non-finite incident on record this attempt (an incident
        under action=warn trains into the parameters, so every capture
        after it is tainted, not just the nearest one); ``bound``
        (the known first-bad step of an unwinding incident) promotes
        everything strictly before it instead. With the health plane
        off, commit alone promotes. ``final`` (the run is over — no
        more sentinel rows are coming) certifies on an unchanged
        incident count alone: an infra failure mid-window leaves the
        last window's rows unexamined forever, and a NaN hiding in
        them would re-raise through the sentinels on the very first
        resumed step, falling back to an older checkpoint then."""
        nf_now = self._nonfinite_count()
        keep = []
        for entry in self._pending:
            step, nf0, fut, cleared = entry
            if not cleared and nf_now is not None \
                    and nf_now == nf0 and self._checked >= step:
                # the sentinels caught up to this step with the count
                # unchanged: the capture is clean for good — incidents
                # appearing AFTER this moment belong to later steps
                entry[3] = cleared = True
            if fut is not None:
                if not fut.done():
                    keep.append(entry)
                    continue
                err = fut.exception()
                if err is not None:
                    if self._async:
                        self.logger.warning(
                            'checkpointing: async writer died (%s) — '
                            'falling back to synchronous saves', err)
                        self._async = False
                    self._note_failure('async save of step %d failed: %s'
                                       % (step, err))
                    continue
            if bound is not None:
                ok = step < bound
            elif nf_now is None:
                ok = True
            elif (nf0 or 0) > self._nf_base:
                # an incident precedes this capture within THIS attempt
                # (counts from a previous attempt of the same process
                # are baselined out): with action=warn the NaN trained
                # into the parameters and every later capture carries
                # it — never certify; the pointer freezes at the last
                # clean step
                _tele.counter('ckpt.uncertified').inc()
                continue
            elif cleared or (final and nf_now == nf0):
                ok = True
            elif nf_now != nf0:
                # an incident landed before health could check through
                # this step: it may belong to a step the capture covers
                # — never certify (conservative)
                _tele.counter('ckpt.uncertified').inc()
                continue
            else:
                keep.append(entry)   # health hasn't caught up yet
                continue
            if ok:
                if self._gang:
                    # gang mode: certification is only LOCAL knowledge —
                    # the pointer itself moves at the next agreement
                    # round (process 0 writes the agreed minimum)
                    self._certified = max(self._certified, int(step))
                else:
                    try:
                        self._write_pointer(step)
                    except OSError as e:
                        self.logger.warning(
                            'checkpointing: cannot write last-good pointer '
                            '(%s)', e)
            else:
                _tele.counter('ckpt.uncertified').inc()
        self._pending = keep

    def _agree_pointer(self):
        """One cross-host pointer-agreement round (gang mode only;
        called at lockstep points of the schedule: every save cadence
        crossing and fit end). Process 0 writes the agreed step; every
        host mirrors it into ``last_good``/the gauge so telemetry and
        restart records name the same step everywhere."""
        agreed = agree_pointer(self.directory, self._certified,
                               self._round_id('ptr'), logger=self.logger)
        if agreed is not None and agreed != self.last_good:
            self.last_good = int(agreed)
            _tele.gauge('ckpt.last_good').set(int(agreed))

    # -- fit-loop hooks ----------------------------------------------------
    def begin_epoch(self, epoch, eval_metric, train_data):
        """Epoch-start hook (after the metric reset). Returns False when
        this epoch precedes the resume target (fit skips it without
        touching the data). At the resume epoch itself the eval-metric
        partial sums are re-applied and the iterator is skipped to the
        restored step."""
        self.eval_metric = eval_metric
        # live iterator shard assignment, captured into every meta
        # sidecar (reshard-on-restore reads it to re-derive coverage)
        info_fn = getattr(train_data, 'shard_info', None)
        if callable(info_fn):
            try:
                num_parts, part_index = info_fn()
                self._io_shard = {'num_parts': int(num_parts),
                                  'part_index': int(part_index)}
            except Exception:  # noqa: BLE001
                self._io_shard = None
        if self._resume is not None:
            r_epoch, r_step, metric_state = self._resume
            if epoch < r_epoch:
                return False
            if epoch == r_epoch:
                self._resume = None
                self.epoch = epoch
                self.step_in_epoch = r_step
                # the fit loops start their batch counter here, so
                # callbacks, health incidents and the failure bound all
                # see TRUE batch-in-epoch indices on a resumed epoch
                self.epoch_nbatch_base = r_step
                self.resumed_epoch = epoch if r_step else None
                seed_fn = getattr(train_data, 'seed_epoch', None)
                if callable(seed_fn):
                    # reseeded skip-to-step: iterators with
                    # deterministic per-epoch order regenerate it
                    seed_fn(epoch)
                if r_step:
                    it = iter(train_data)
                    skipped = 0
                    while skipped < r_step:
                        try:
                            next(it)
                        except StopIteration:
                            break
                        skipped += 1
                    self.logger.info(
                        'checkpointing: resumed epoch %d at step %d '
                        '(skipped %d already-trained batches)',
                        epoch, r_step, skipped)
                if metric_state:
                    try:
                        children = _metric_children(eval_metric)
                        live = [type(c).__name__ for c in children]
                        saved = [s[0] for s in metric_state]
                        if live != saved:
                            # a changed metric list would zip-truncate
                            # silently and mis-assign partial sums
                            raise ValueError(
                                'saved %s vs live %s' % (saved, live))
                        for child, (_, s, n) in zip(children,
                                                    metric_state):
                            child.sum_metric = s
                            child.num_inst = n
                    except Exception as err:  # noqa: BLE001 — drifted
                        self.logger.warning(
                            'checkpointing: eval-metric state did not '
                            'match the checkpoint (%s); metric restarts '
                            'at 0', err)
                _tele.event('ckpt.resume', epoch=epoch, step=r_step,
                            restored_step=self.restored_step)
                return True
            self._resume = None   # target epoch already passed
        self.epoch = epoch
        self.step_in_epoch = 0
        self.epoch_nbatch_base = 0
        return True

    def allow_empty_epoch(self, epoch):
        """Whether the fit loops should tolerate drawing ZERO batches
        at this epoch's start: true only for a resumed epoch whose
        checkpoint landed exactly on the epoch boundary (the skip
        consumed every batch; there is nothing left to train). Any
        other empty iterator keeps the loud reference failure."""
        return self.resumed_epoch == epoch

    def save_due(self, n):
        """Whether :meth:`note_steps`\\ (n) will initiate a save — the
        fused loop asks BEFORE noting a window so it can flush its
        pipelined metric/health stats first: the capture must see the
        eval-metric state through the steps it claims to cover."""
        return (not self._disabled
                and self.global_step + n - self._last_save >= self.every)

    def note_steps(self, n, lag=0):
        """Step hook, called by both train loops after ``n`` more steps
        are trained. ``lag`` is how many trained steps the loop's health
        processing trails by (the fused loop fetches a window's sentinel
        rows one window late)."""
        self.global_step += n
        self.step_in_epoch += n
        self._checked = max(self._checked, self.global_step - lag)
        if self._pending:
            self._promote()
        if (not self._disabled or self._gang) \
                and self.global_step - self._last_save >= self.every:
            # a gang host that locally DISABLED checkpointing still
            # crosses every cadence point: it votes not-ready in the
            # save agreement (stopping the gang's collective saves)
            # and keeps contributing to pointer rounds — dropping out
            # would desynchronize every later round's name instead
            self._last_save = self.global_step
            self._initiate_save()
            if self._gang:
                # lockstep point (every host crosses the cadence at the
                # same global step): agree on the newest step every
                # host has committed + cleared, process 0 writes it. On
                # the async path the agreement naturally lags one
                # cadence (the in-flight save hasn't committed yet);
                # finish() runs the closing round after the drain
                self._agree_pointer()

    def _abort_drain(self):
        """Watchdog abort hook (monitor thread, bounded by the
        watchdog's hook cap): drain the async writer and certify what
        committed, so the relaunch has a last-good pointer. No new
        capture is taken — the wedged main thread owns the live arrays."""
        self._drain()
        self._promote(final=True)

    def finish(self):
        """fit() completed: take a final save, drain the writer and
        certify what the health plane has cleared. Draining FIRST means
        the final save is never dropped on the busy-writer guard — the
        run's end state always lands."""
        self._checked = self.global_step
        self._drain()
        if (not self._disabled or self._gang) \
                and self.global_step > self._initiated:
            # lockstep in gang mode: _initiated advances at the agreed
            # initiation point, and a locally-disabled host still
            # participates (voting not-ready) — see note_steps
            self._last_save = self.global_step
            self._initiate_save()
            self._drain()
        self._promote()
        if self._gang:
            # fit ends at the same global step on every host — the
            # closing agreement round lands the run's end state in the
            # pointer (the cadence rounds lag one save on the async
            # path)
            self._agree_pointer()
        self._shutdown_pool()

    def handle_failure(self, diagnostic=None):
        """fit() died (resilient_fit's except path): drain the writer,
        then certify pending saves. When the diagnostic names the first
        bad step (TrainingHealthError), every committed save strictly
        before it is known-good regardless of detector lag; otherwise
        commit + an unchanged incident count certifies (``final`` —
        see :meth:`_promote`): an infra failure is not a numeric one,
        and a NaN the crash hid from the sentinels re-raises on the
        first resumed step."""
        self._drain()
        bound = None
        if diagnostic and diagnostic.get('step') is not None:
            epoch_base = self.global_step - self.step_in_epoch
            bound = epoch_base + int(diagnostic['step'])
        self._promote(bound=bound, final=bound is None)
        # gang mode: a failure path is NOT a lockstep point (one host
        # raised while the others are wedged or dead), so no agreement
        # round runs — the pointer stays at the last agreed step and
        # the relaunched gang restores from there
        self._shutdown_pool()

    def _drain(self):
        for entry in self._pending:
            fut = entry[2]
            if fut is not None and not fut.done():
                try:
                    fut.exception(timeout=600)
                except Exception:  # noqa: BLE001
                    pass
        try:
            self._ckpt.wait(self._mngr)
        except Exception:  # noqa: BLE001
            pass

    def _shutdown_pool(self):
        _tele.watchdog.remove_abort_hook(self._abort_drain)
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- restore -----------------------------------------------------------
    def _template(self):
        """Abstract tree mirroring the LIVE state's dtypes/shardings
        (orbax restore-into-template: every shard lands back where it
        belongs without materializing the full state anywhere)."""
        e = self._exec
        tree = {
            'params': {n: e.arg_dict[n]._data for n in self._param_names},
            'aux': {n: e.aux_dict[n]._data for n in self._aux_names},
        }
        _, opt_arrays = self._walk_opt(copy=False)
        if opt_arrays:
            tree['opt'] = opt_arrays
        if self._accum:
            tree['gacc'] = {n: e.grad_dict[n]._data
                            for n in self._grad_names}
        return tree

    @staticmethod
    def _iter_zero_encs(structure):
        """Every ZeRO-layout leaf annotation dict in an opt_structure."""
        def walk(enc):
            if isinstance(enc, dict):
                yield enc
            elif isinstance(enc, list):
                for e in enc:
                    yield from walk(e)
        for _name, enc in structure or []:
            yield from walk(enc)

    def _override_zero_template(self, template, meta):
        """Leaves saved in the ZeRO update-phase form carry their
        canonical shape in the structure enc; the restore template must
        target the SAVED flat global shape (mesh-independent — a dp
        change between save and restore only changes the SHARDING and
        possibly the pad length, and the canonical shape check in
        :meth:`_apply` is the real drift gate). This is what makes a
        dp-resharding of an opt leaf a valid reshard instead of the
        shape-drift older/fresh fallback."""
        import jax
        opt = template.get('opt')
        if not opt:
            return
        saved_shapes = meta.get('shapes') or {}
        for enc in self._iter_zero_encs(meta.get('opt_structure')):
            k = enc.get('k')
            saved = saved_shapes.get('opt/%s' % k)
            live = opt.get(k)
            if saved is None or live is None:
                continue
            if tuple(saved) != tuple(live.shape):
                opt[k] = jax.ShapeDtypeStruct(
                    tuple(saved), live.dtype,
                    sharding=getattr(live, 'sharding', None))

    @staticmethod
    def _annotate_opt_leaves(msg, meta):
        """Map the anonymous ``opt/opt.N`` leaf paths in a shape-
        mismatch message back to the parameter each state leaf belongs
        to, so the warning names 'opt/opt.3 (fc1_weight)' instead of a
        bare counter."""
        owners = {}

        def walk(enc, name):
            if enc is None:
                return
            if isinstance(enc, list):
                for e in enc:
                    walk(e, name)
                return
            if isinstance(enc, dict):   # ZeRO-layout leaf annotation
                owners[enc['k']] = name
                return
            owners[enc] = name

        for name, enc in meta.get('opt_structure') or []:
            walk(enc, name)
        import re
        return re.sub(
            r'opt/(opt\.\d+)',
            lambda m: 'opt/%s (%s)' % (m.group(1),
                                       owners.get(m.group(1), '?')), msg)

    def _restore_step(self, step):
        """Restore one committed step into the module, bit-exactly:
        the read/validate/fetch phase (:meth:`_fetch_step`) followed by
        the apply. The two are separate so the gang resume path can
        reject a candidate BETWEEN them (cross-host agreement) with the
        live module still untouched on every host."""
        meta, restored = self._fetch_step(step)
        self._apply(restored, meta)
        return meta

    def _fetch_step(self, step):
        """Read + validate + fetch one committed step WITHOUT touching
        the live module; returns ``(meta, restored_tree)``.
        Restore-into-template: the CURRENT mesh's live arrays supply
        the dtypes/shardings orbax restores onto, and validation runs
        against GLOBAL shapes (recorded in the meta sidecar at save) —
        never per-host ones — so a checkpoint saved on N devices/hosts
        restores onto M as long as the model itself is unchanged, with
        orbax re-laying the shards out to the new mesh. A genuine
        model/optimizer change raises naming the exact offending leaf;
        the caller falls back to an older step."""
        meta = self._ckpt.read_meta(self._mngr, step)
        if meta.get('format') != _FORMAT:
            raise ValueError('unsupported checkpoint format %r'
                             % meta.get('format'))
        template = self._template()
        self._override_zero_template(template, meta)
        saved_shapes = meta.get('shapes')
        if saved_shapes:
            try:
                self._ckpt.validate_shapes(saved_shapes, template)
            except ValueError as e:
                raise ValueError(self._annotate_opt_leaves(str(e), meta)) \
                    from None
        saved_mesh = meta.get('mesh')
        if saved_mesh:
            try:
                from ..parallel import multihost as _mh
                now = _mh.mesh_descriptor()
            except Exception:  # noqa: BLE001
                now = None
            if now is not None and (
                    saved_mesh.get('devices') != now['devices']
                    or saved_mesh.get('processes') != now['processes']):
                self.resharded_from = dict(saved_mesh)
                self.logger.info(
                    'checkpointing: resharding step %d saved on %s '
                    'device(s) / %s process(es) onto %d / %d — global '
                    'shapes validated, orbax re-lays the shards out to '
                    'the current mesh', step,
                    saved_mesh.get('devices'), saved_mesh.get('processes'),
                    now['devices'], now['processes'])
        # state-only restore: the meta sidecar was already read (and
        # validated) above — no second JSON round-trip
        restored = self._ckpt.restore_state(self._mngr, template, step)
        return meta, restored

    def _apply(self, tree, meta):
        e = self._exec
        m = self.module

        # optimizer state: walk the SAVED structure against the live
        # NDArray state objects (created via the optimizer's own
        # create_state path, so the shapes/wrapping match). The walk
        # runs FIRST and only stages assignments: a mismatch (renamed
        # param, changed optimizer, corrupt meta) must raise while the
        # live module is still untouched, so the caller's fallback —
        # an older step, or a genuine fresh start — never inherits a
        # half-restored run
        self._ensure_opt_states()
        upd = self._updater()
        opt_arrays = tree.get('opt', {})
        staged = []   # (live state NDArray, restored array)
        from .fused_fit import zero_shape_probe
        probe = zero_shape_probe(m)

        def stage(struct, live, name):
            # every mismatch names the owning parameter — a restore
            # that cannot proceed must say WHICH leaf drifted, not just
            # that one did (the caller's older-step fallback warning
            # carries this text)
            if struct is None:
                if live is not None:
                    raise ValueError(
                        'optimizer state for %s drifted: checkpoint has '
                        'no state leaf, live optimizer has one' % name)
                return
            if isinstance(struct, list):
                if not isinstance(live, tuple) or len(live) != len(struct):
                    raise ValueError(
                        'optimizer state for %s drifted: checkpoint '
                        'holds %d state leaf(s), live optimizer %s'
                        % (name, len(struct),
                           len(live) if isinstance(live, tuple)
                           else 'a single leaf'))
                for s, l in zip(struct, live):
                    stage(s, l, name)
                return
            if live is None or isinstance(live, tuple):
                raise ValueError(
                    'optimizer state for %s drifted: checkpoint leaf %s '
                    'has no matching live state array' % (name, struct))
            if isinstance(struct, dict):
                # ZeRO-layout leaf: the saved array is flat (padded to
                # the SAVING dp's multiple, dp-sharded at save); the
                # canonical shape recorded next to it is the drift
                # gate, and a differing pad length / sharding is a
                # valid dp-reshard, not drift
                from ..parallel.sharding import zero_unflatten
                arr = opt_arrays[struct['k']]
                shape = tuple(struct['shape'])
                live_shape = tuple(live._data.shape)
                z = probe(live) if probe is not None else None
                if z is not None:
                    live_shape = tuple(z)
                if live_shape != shape:
                    raise ValueError(
                        'optimizer state for %s drifted: leaf %s saved '
                        'canonical shape %s vs live %s'
                        % (name, struct['k'], shape, live_shape))
                n_elem = 1
                for d in shape:
                    n_elem *= int(d)
                if getattr(arr, 'ndim', 0) != 1 or int(arr.shape[0]) < n_elem:
                    raise ValueError(
                        'optimizer state for %s drifted: leaf %s holds '
                        '%s elements, canonical shape %s needs %d'
                        % (name, struct['k'], tuple(arr.shape), shape,
                           n_elem))
                staged.append((live, zero_unflatten(arr, shape)))
                return
            arr = opt_arrays[struct]
            if tuple(arr.shape) != tuple(live._data.shape):
                raise ValueError(
                    'optimizer state for %s drifted: leaf %s saved '
                    'shape %s vs live %s'
                    % (name, struct, tuple(arr.shape),
                       tuple(live._data.shape)))
            staged.append((live, arr))

        for name, struct in meta['opt_structure']:
            if name not in self._upd_keys:
                raise ValueError('checkpoint names unknown param %r' % name)
            stage(struct, upd.states[self._upd_keys[name]], name)

        for n in self._param_names:
            e.arg_dict[n]._data = tree['params'][n]
            if m._update_on_kvstore:
                store = m._kvstore._store.get(n)
                if store is not None:
                    store._data = tree['params'][n]
        for n in self._aux_names:
            e.aux_dict[n]._data = tree['aux'][n]
        if self._accum and 'gacc' in tree:
            for n in self._grad_names:
                e.grad_dict[n]._data = tree['gacc'][n]
        m._params_dirty = True
        for live, arr in staged:
            live._data = arr

        book = meta.get('opt_bookkeeping') or {}
        o = m._optimizer
        o.num_update = int(book.get('num_update', o.num_update))
        o._index_update_count = {k: int(v) for k, v in
                                 book.get('index_update_count', [])}

        rng = dict(meta.get('rng_host') or {})
        values = rng.pop('key_values', None)
        dtype = rng.pop('key_dtype', 'uint32')
        rng['key'] = None if values is None \
            else np.asarray(values, dtype=np.dtype(dtype))
        _random.set_state(rng)

    def _remap_resume_cursor(self, r_step, meta):
        """Translate the saved step-in-epoch iterator cursor into the
        CURRENT process set's units after an N->M host restore. Each
        host draws per-host batches from its own 1/P shard, so one
        global "step" covers batch_size * P samples: the same trained
        sample count lands at step * P_old / P_new in the new layout.
        Inexact divisions round DOWN (a few batches retrain from the
        restored — finite — parameters rather than skipping unseen
        data); the io shard ranges themselves come from the relaunched
        processes' own iterator construction (io.auto_shard), so every
        example is covered exactly once by the new set."""
        saved_mesh = meta.get('mesh') or {}
        old_p = int(saved_mesh.get('processes') or 0)
        try:
            from ..parallel import multihost as _mh
            new_p = int(_mh.process_count())
        except Exception:  # noqa: BLE001
            new_p = old_p
        if not old_p or old_p == new_p or not r_step:
            return r_step
        scaled, rem = remap_cursor(r_step, old_p, new_p)
        io_meta = meta.get('io') or {}
        self.logger.warning(
            'checkpointing: restore crosses a process-set change '
            '(%d -> %d host(s)): iterator cursor remapped step %d -> '
            '%d%s; io shard ranges re-derived from the new process set'
            '%s', old_p, new_p, r_step, scaled,
            '' if not rem else ' (inexact — %d sample-steps retrain)'
            % rem,
            ' (was shard %s/%s)' % (io_meta.get('part_index'),
                                    io_meta.get('num_parts'))
            if io_meta else '')
        return scaled

    def _try_resume(self):
        # a fused loop cached from a previous fit() may hold ZeRO-layout
        # state: restore validates/applies against the canonical layout
        from .fused_fit import flush_sharded_states
        flush_sharded_states(self.module)
        steps = self._ckpt.all_steps(self._mngr)
        if not steps:
            return
        ptr = self.read_pointer(self.directory)
        if ptr is None:
            self.logger.warning(
                'checkpointing: %s holds %d checkpoint(s) but no '
                'last-good pointer — none was health-certified; '
                'starting fresh', self.directory, len(steps))
            return
        candidates = [s for s in sorted(steps, reverse=True) if s <= ptr]
        for step in candidates:
            self.resharded_from = None   # per-candidate bookkeeping
            failed = False
            fetched = None
            try:
                # fetch/validate WITHOUT touching the live module: the
                # gang agreement below can still reject this candidate
                fetched = self._fetch_step(step)
            except Exception as e:  # noqa: BLE001 — corrupt step
                self.logger.warning(
                    'checkpointing: restore of step %d failed (%s) — '
                    'trying an older checkpoint', step, e)
                failed = True
            if self._gang:
                # the fallback decision must be COLLECTIVE: fetch
                # failures can be asymmetric (one host's transient read
                # error), and a gang whose hosts restore different
                # steps diverges every later agreement round — the
                # exact failure the agreed pointer exists to prevent.
                # Any host failing sends the WHOLE gang to the older
                # candidate; because nothing was applied yet, a
                # rejected candidate leaves every host's live module
                # untouched — even when every candidate ends up
                # rejected and the gang starts fresh together. No
                # agreement (a dead peer) reads as failure,
                # conservatively
                from ..parallel import multihost as _mh
                any_failed = _mh.agree_any('ckpt.resume.%d' % step,
                                           failed)
                if any_failed is None or any_failed:
                    if not failed:
                        self.logger.warning(
                            'checkpointing: a peer host failed to '
                            'restore step %d — falling back together',
                            step)
                    continue
            elif failed:
                continue
            try:
                meta, restored = fetched
                self._apply(restored, meta)
            except Exception as e:  # noqa: BLE001 — drifted state
                # _apply stages everything before mutating, so a
                # failure here leaves the module untouched; staging is
                # deterministic on identical checkpoint + live
                # structure, hence symmetric across a gang
                self.logger.warning(
                    'checkpointing: restore of step %d failed (%s) — '
                    'trying an older checkpoint', step, e)
                continue
            # steps newer than the restore point are stale (and, after
            # an incident, possibly poisoned): clear them so pruning
            # and replay renumbering stay sane (one deleter in a gang —
            # every host would race the same shared step dirs)
            from ..parallel import multihost as _mh
            if not self._gang or _mh.is_primary():
                for s in steps:
                    if s > step:
                        try:
                            self._ckpt.delete_step(self._mngr, s)
                        except Exception:  # noqa: BLE001
                            pass
            self.global_step = int(meta['global_step'])
            self._last_save = self.global_step
            self._initiated = self.global_step
            self._checked = self.global_step
            self.last_good = step
            self.restored_step = step
            # the restored step is certified by construction (the
            # pointer named it): agreement rounds start from it instead
            # of re-earning a step the whole gang already trusts
            self._certified = int(step)
            if step != ptr:
                try:
                    self._write_pointer(step)
                except OSError:
                    pass
            r_step = int(meta['step_in_epoch'])
            r_step = self._remap_resume_cursor(r_step, meta)
            self._resume = (int(meta['epoch']), r_step,
                            meta.get('metric') or [])
            self.logger.info(
                'checkpointing: restored step %d (epoch %d, step %d) '
                'from %s', step, meta['epoch'], meta['step_in_epoch'],
                self.directory)
            return
        self.logger.warning(
            'checkpointing: no checkpoint in %s was restorable — '
            'starting fresh', self.directory)
