"""Fused inference/evaluation fast path for score / predict / iter_predict.

Reference base_module.py:204 (score) and :292 (predict) run one
synchronous forward + one device->host copy per batch. On a TPU behind
a tunneled runtime each dispatch and each fetch costs a full RTT, which
caps eval throughput exactly the way the per-batch train loop capped
fit (module/fused_fit.py) — the dispatch-bound pattern whole-program
compilation kills (TVM arXiv:1802.04799, Julia->TPU arXiv:1810.09868:
hand XLA a large region once, not a kernel per batch). This module
compiles a WINDOW of W forward steps into ONE XLA computation via
lax.scan — the read-only twin of FusedFitLoop — behind the unchanged
score/predict/iter_predict APIs:

- score: Accuracy / TopKAccuracy / CrossEntropy (and composites of
  them) are accumulated from in-graph sufficient statistics packed
  into one vector per step — a single host fetch per window. ANY other
  metric takes stacked-output mode: the window ships the per-step
  outputs (still one fetch per window) and eval_metric.update runs per
  batch on the host exactly as the reference loop would. Metric values
  and batch_end_callback cadence match the reference loop (callbacks
  fire in a burst after each window — the one observable difference);
- predict / iter_predict: the window returns the stacked per-step
  outputs; ONE host fetch per window replaces a per-batch ``.copy()``
  + device->host round-trip, then pad rows are trimmed per batch on
  the host exactly where the reference slices them (axis 0,
  ``out[:shape[0]-pad]``) before merging;
- batches are snapshotted at draw time and stacked/uploaded through
  the shared :class:`~.window_pipeline.WindowPipeline` — window k+1's
  stack + host->device transfer run on a side thread while window k
  computes on device (MXTPU_FUSED_EVAL_PREFETCH=0 restores the serial
  order);
- tail batches (< window, or a ``num_batch`` remainder) run the
  reference per-batch path on batches rebuilt from the draw-time
  snapshots, so buffer-reusing iterators stay correct;
- forward-only means nothing is written back: parameters and aux
  (BatchNorm moving stats) are read-only, matching the reference's
  ``is_train=False`` forward.

Eligibility (build() returns None -> the reference per-batch loop runs,
mirroring FusedFitLoop.build_cached's silent fallback): plain Module,
one executor (single context or SPMD group), non-staged graph, no
monitor, inferable shapes; stacked-output modes additionally cap the
window's output footprint. Toggles: MXTPU_FUSED_EVAL=0 disables;
MXTPU_EVAL_STEPS_PER_CALL sets W (default 32 on TPU, 4 elsewhere).
"""
import logging

import numpy as np

import jax
import jax.numpy as jnp

from .. import random as _random
from .. import telemetry as _tele
from ..ndarray.ndarray import from_jax
from .window_pipeline import (WindowPipeline, health_sentinel, host_wrap,
                              plan_metric, registered_jit, window_bisect,
                              window_size)

__all__ = ['FusedEvalLoop']

# stacked-output modes ship W batches of outputs per fetch; bound the
# device-side footprint the same way the fit loop's host-metric mode does
_OUT_STACK_CAP = 256 * 1024 * 1024


def _eval_window():
    return window_size('MXTPU_EVAL_STEPS_PER_CALL')


class FusedEvalLoop:
    """One compiled W-step forward window driving score/predict."""

    def __init__(self, module, children, stat_fns, window, kind='eval'):
        self.module = module
        self.children = children   # leaf metrics fed by in-graph stats
        self.stat_fns = stat_fns   # None => stacked-output mode
        self.window = window
        self._programs = {}
        e = module._exec_group.execs[0]
        self._exec = e
        self._run = e._run_eager
        from ..telemetry.programs import scope_name
        # score and predict build separate loop instances (separate
        # cache slots) compiling different programs — give each its own
        # registrar row so neither masks the other's cost/memory record
        self._prog_name = 'fused_eval.%s[%s]' % (kind, scope_name(
            getattr(module._symbol, 'name', None) or 'graph'))
        self._arg_names = list(e._prog.arg_names)
        self._aux_names = list(e._prog.aux_names)
        from .executor_group import SPMDExecutorGroup
        self._mesh = module._exec_group.mesh \
            if isinstance(module._exec_group, SPMDExecutorGroup) else None
        self._pipe = WindowPipeline(window,
                                    device_fn=lambda: e._ctx.jax_device(),
                                    mesh=self._mesh,
                                    span_prefix='fused_eval')
        # training-health sentinels (per-output finite flags only — a
        # forward window has no grads/updates); None = window traced
        # byte-identical to the plain form
        self._health_fn = health_sentinel()

    # -- reuse across score()/predict() calls ------------------------------
    def _rebind_metric(self, eval_metric):
        from .window_pipeline import rebind_children
        self.children = rebind_children(eval_metric, self.children)

    @classmethod
    def build_cached(cls, module, eval_metric, logger=logging):
        """build(), but reuse the previous call's loop — with its
        compiled window programs — when everything the traced window
        depends on is unchanged: same bound executor, window size, and
        (for score) an equal-config metric. ``eval_metric=None`` is the
        predict/iter_predict form. Score and predict loops cache in
        separate slots, so a score-between-epochs driver that also
        predicts never thrashes either program set."""
        from ..config import flags
        flags.reload('MXTPU_FUSED_EVAL')
        if not flags.get('MXTPU_FUSED_EVAL'):
            module.__dict__.pop('_fused_eval_cache', None)
            return None
        kind = 'score' if eval_metric is not None else 'predict'
        eg = getattr(module, '_exec_group', None)
        execs = getattr(eg, 'execs', None) or []
        sig = None
        if len(execs) == 1 and execs[0]._monitor is None \
                and not execs[0]._use_staged():
            # a monitor installed (or staging forced) between calls
            # must invalidate reuse the same way build() rejects it
            if eval_metric is None:
                msig = '<predict>'
            else:
                from .fused_fit import FusedFitLoop
                msig = FusedFitLoop._metric_sig(eval_metric)
            if msig is not None:
                # the health sentinels are traced INTO the window
                # program — flipping MXTPU_HEALTH between calls must
                # rebuild the loop
                from ..telemetry import health as _health
                sig = (id(execs[0]), _eval_window(), msig,
                       bool(_health.enabled()))
        cache = module.__dict__.get('_fused_eval_cache')
        if sig is None:
            # unsignable (monitor/staged/multi-exec, or a metric whose
            # get_config raises): an uncached loop would re-trace and
            # re-compile the window EVERY score() call — strictly worse
            # than the per-batch loop it was built to beat. Fall back.
            if cache is not None:
                cache.pop(kind, None)
            return None
        cached = cache.get(kind) if cache is not None else None
        if cached is not None and cached[0] == sig:
            loop = cached[1]
            if eval_metric is not None:
                loop._rebind_metric(eval_metric)
            return loop
        loop = cls.build(module, eval_metric, logger=logger)
        if loop is not None:
            module.__dict__.setdefault('_fused_eval_cache', {})[kind] = \
                (sig, loop)
        elif cache is not None:
            cache.pop(kind, None)
        return loop

    # -- eligibility -------------------------------------------------------
    @staticmethod
    def build(module, eval_metric, logger=logging):
        from ..config import flags
        flags.reload('MXTPU_FUSED_EVAL')
        if not flags.get('MXTPU_FUSED_EVAL'):
            return None
        from .module import Module
        if type(module) is not Module:
            return None
        eg = module._exec_group
        if len(getattr(eg, 'execs', ())) != 1:
            return None
        e = eg.execs[0]
        if e._use_staged() or e._monitor is not None:
            return None
        shapes = {d.name: d.shape for d in
                  list(module.data_shapes) + list(module.label_shapes or [])}
        try:
            _, out_shapes, _ = module._symbol.infer_shape(**shapes)
        except Exception:  # noqa: BLE001 — undecidable shapes: fall back
            return None
        if out_shapes is None:
            return None
        window = _eval_window()
        children, fns = None, None
        if eval_metric is not None:
            # plan_metric also enforces the stat fns' output/label
            # geometry; other geometries use stacked-output mode, whose
            # host-side eval_metric.update is reference-exact
            plan = plan_metric(eval_metric, out_shapes,
                               module._label_names)
            if plan is not None:
                children, fns = plan
        if fns is None:
            # stacked-output mode (predict, and score with an unplanned
            # metric): W stacked fp32 outputs must stay under the
            # device-memory cap
            est = 4 * window * sum(
                int(np.prod(s)) for s in out_shapes if s)
            if est > _OUT_STACK_CAP:
                return None
        loop = FusedEvalLoop(module, children, fns, window,
                             kind='score' if eval_metric is not None
                             else 'predict')
        logger.info('fused eval fast path active: %d steps/device-call%s',
                    window,
                    '' if fns is not None else ' (stacked-output mode)')
        return loop

    # -- program -----------------------------------------------------------
    def _program(self, snaps):
        """Compiled window for the drawn batches' shapes. One program
        per (shapes, labels-present) signature; everything else —
        params, aux, RNG key — enters traced."""
        has_labels = len(snaps[0][1]) > 0
        shapes_key = tuple((tuple(a.shape), str(a.dtype))
                           for a in snaps[0][0] + snaps[0][1])
        key = (has_labels, shapes_key)
        entry = self._programs.get(key)
        if entry is None:
            with _tele.span('fused_eval.build', 'fused_eval'):
                entry = self._build_program(has_labels)
            self._programs[key] = entry
            # same-key rebuilds only happen when the program dict was
            # torn down; the storm detector keys on the SHAPES
            _tele.xla.note_retrace(('fused_eval.window', shapes_key))
        return entry

    def _build_program(self, has_labels):
        run = self._run
        arg_pos = {n: i for i, n in enumerate(self._arg_names)}
        data_names = list(self.module._data_names)
        label_names = list(self.module._label_names) if has_labels else []
        # a label that is an argument of the bound graph is fed into it
        # (a predict-bound module may carry label args as plain zeros —
        # the reference forward loads labels only when both sides have
        # them); labels the graph does not consume still reach the
        # metric stat fns through the scan xs
        fed_pairs = [(li, arg_pos[n]) for li, n in enumerate(label_names)
                     if n in arg_pos]
        io_pos = set(arg_pos[n] for n in data_names) | \
            set(ai for _, ai in fed_pairs)
        fixed_names = [n for i, n in enumerate(self._arg_names)
                       if i not in io_pos]
        stat_fns = self.stat_fns
        health_fn = self._health_fn
        W = self.window

        def window_fn(fixed, aux, data_stack, label_stack, key):
            def body(carry, xs):
                step_i, datas, labels = xs
                k = jax.random.fold_in(key, step_i)
                full = [None] * len(arg_pos)
                for n, v in zip(fixed_names, fixed):
                    full[arg_pos[n]] = v
                for n, v in zip(data_names, datas):
                    full[arg_pos[n]] = v
                for li, ai in fed_pairs:
                    full[ai] = labels[li]
                outs, _ = run(tuple(full), aux, k, False)
                if stat_fns is not None:
                    # all metric stats packed into ONE vector per step
                    # so the host needs a single fetch per window
                    ys = jnp.stack([v for fn in stat_fns
                                    for v in fn(outs, labels)])
                else:
                    # stacked-output mode: scan stacks the per-step
                    # outputs into (W, ...) per output
                    ys = outs
                if health_fn is not None:
                    # per-step finite flags ride the scan ys — home in
                    # the window's existing single fetch
                    ys = (ys, health_fn(outs))
                return carry, ys

            # XLA:CPU parallelizes poorly inside while-loop bodies: the
            # rolled scan ran a ResNet-50 window ~as slow as (112px,
            # f32) or slower than (224px, bf16) per-batch forwards,
            # while the fully unrolled window is ~2.3x FASTER than
            # per-batch — XLA fuses/parallelizes across steps. TPU
            # keeps the rolled form: at W=32 unrolling multiplies
            # compile time for no dispatch win.
            unroll = W if jax.default_backend() != 'tpu' else 1
            _, ys = jax.lax.scan(
                body, 0, (jnp.arange(W), data_stack, label_stack),
                unroll=unroll)
            return ys

        # no donation: eval mutates nothing — params/aux stay live for
        # the next window and for the module's own per-batch paths.
        # registered_jit routes the compile through the telemetry
        # program registrar (cost/memory analysis per program)
        return registered_jit(self._prog_name, window_fn), fixed_names

    def _snapshot(self, fixed_names):
        """Current parameter/aux arrays in program order, mesh-
        replicated on an SPMD group (window_pipeline.place_replicated,
        shared with the fit loop)."""
        from .window_pipeline import place_replicated
        e = self._exec
        fixed = tuple(e.arg_dict[n]._data for n in fixed_names)
        aux = tuple(e.aux_dict[n]._data for n in self._aux_names)
        if self._mesh is not None:
            fixed, aux = place_replicated(self._mesh, fixed, aux)
        return fixed, aux

    def _pool(self):
        from ..config import flags
        return self._pipe.pool() \
            if flags.get('MXTPU_FUSED_EVAL_PREFETCH') else None

    def _rebuild_batch(self, snap):
        """Reference-path DataBatch from a draw-time snapshot (the
        iterator's own batch buffers may have been overwritten by
        later draws)."""
        from ..io import DataBatch
        ds, ls, pad, idx = snap
        ctx = self._exec._ctx
        return DataBatch(data=[from_jax(d, ctx) for d in ds],
                         label=[from_jax(l, ctx) for l in ls],
                         pad=pad, index=idx)

    # -- the shared window drive -------------------------------------------
    def _drive(self, eval_data, num_batch, snap_labels=False):
        """Drive the pipelined window loop once for score AND predict:
        yields ('window', pieces, win_snaps, labels_snap) per resolved
        window and ('tail', rebuilt_batch, snap, None) per remaining
        batch. Window results surface ONE WINDOW LATE by design — the
        consumer's host fetch at the yield point overlaps the next
        window's device compute and side-thread upload; values and
        per-batch cadence are unchanged."""
        it = iter(eval_data)
        pipe = self._pipe
        pool = self._pool()
        drawn = 0
        pending = None

        def collect():
            nonlocal drawn
            lim = None if num_batch is None else num_batch - drawn
            batches, snaps = pipe.collect(it, limit=lim)
            drawn += len(batches)
            return batches, snaps

        batches, snaps = collect()
        fut = pipe.start_put(snaps, pool) \
            if len(batches) == self.window else None
        try:
            while len(batches) == self.window:
                window_fn, fixed_names = self._program(snaps)
                labels_snap = None
                if snap_labels:
                    # stacked-output score: keep per-batch label
                    # wrappers from the draw-time snapshots for the
                    # deferred eval_metric.update
                    labels_snap = [[from_jax(l, self._exec._ctx)
                                    for l in ls] for _, ls, _, _ in snaps]
                fixed, aux = self._snapshot(fixed_names)
                with _tele.span('fused_eval.put', 'fused_eval'):
                    data_stack, label_stack = fut()
                with _tele.span('fused_eval.dispatch', 'fused_eval'):
                    pieces = window_fn(fixed, aux, data_stack, label_stack,
                                       _random.next_key())
                _tele.counter('fused_eval.windows').inc()
                _tele.counter('eval.batches').inc(self.window)
                # hang-watchdog progress mark: eval windows count too,
                # or a long between-epoch score() would false-trip it
                _tele.watchdog.note_progress('fused_eval.window')
                # dispatch is async: draw the NEXT window (its stack +
                # transfer start on the side thread), then hand the
                # PREVIOUS window to the consumer while this one
                # computes
                win_snaps = snaps
                batches, snaps = collect()
                fut = pipe.start_put(snaps, pool) \
                    if len(batches) == self.window else None
                if pending is not None:
                    yield ('window',) + pending
                pending = (pieces, win_snaps, labels_snap)
        except Exception as e:
            # RESOURCE_EXHAUSTED in the upload/dispatch drive: dump the
            # per-program memory breakdown (no-op otherwise)
            _tele.programs.maybe_oom_report(e)
            raise
        finally:
            # drain an in-flight prefetch before the cache teardown (or
            # an exception/close unwind) can race the side thread
            if pool is not None:
                WindowPipeline.drain(fut)
            pipe.drop_cache()
        if pending is not None:
            yield ('window',) + pending
        for snap in snaps:
            # tail (< window, or a num_batch remainder): reference
            # per-batch path on snapshot-rebuilt batches
            yield ('tail', self._rebuild_batch(snap), snap, None)

    def _note_window_health(self, hrows, win_snaps, nbatch):
        """Check a fetched (W, k) sentinel matrix (no-op when the
        sentinels are off): exact-step attribution + the staged-path
        bisect on the offending batch's snapshot, is_train=False."""
        if hrows is None:
            return
        _tele.health.note_window(
            hrows, source='fused_eval',
            nbatch_base=nbatch, has_grads=False,
            bisect=window_bisect(self._exec,
                                 list(self.module._data_names),
                                 list(self.module._label_names),
                                 win_snaps, False))

    # -- score -------------------------------------------------------------
    def run_score(self, eval_data, eval_metric, num_batch,
                  batch_end_callback, epoch):
        """Windowed score pass; returns the number of batches consumed
        (the reference's actual_num_batch)."""
        from ..model import BatchEndParam
        from .base_module import _as_list

        m = self.module
        _tele.gauge('fused_eval.steps_per_call').set(self.window)
        host_nd = host_wrap(self._exec._ctx)
        nbatch = 0

        def fire_callback(nbatch):
            if batch_end_callback is not None:
                p = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                  eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(p)

        for kind, a, b, labels_w in self._drive(
                eval_data, num_batch, snap_labels=self.stat_fns is None):
            if kind == 'tail':
                sb = a
                with _tele.span('eval.dispatch', 'eval'):
                    m.forward(sb, is_train=False)
                with _tele.span('eval.metric', 'eval'):
                    m.update_metric(eval_metric, sb.label)
                _tele.counter('eval.batches').inc()
                fire_callback(nbatch)
                nbatch += 1
                continue
            # one host fetch for the window's results, then exact
            # per-batch metric application + callbacks (the fit loop's
            # deferred-apply shape)
            pieces = a
            hmat = None
            if self._health_fn is not None:
                pieces, hrows = pieces
            with _tele.span('fused_eval.fetch', 'fused_eval'):
                if self.stat_fns is not None:
                    host = np.asarray(pieces)      # (W, 2 * n_metrics)
                    steps = host.shape[0]
                else:
                    outs_host = [np.asarray(o) for o in pieces]  # (W, ...)
                    steps = outs_host[0].shape[0]
                if self._health_fn is not None:
                    hmat = np.asarray(hrows)
            self._note_window_health(hmat, b, nbatch)
            for i in range(steps):
                if self.stat_fns is not None:
                    for j, child in enumerate(self.children):
                        child.sum_metric += float(host[i, 2 * j])
                        child.num_inst += int(host[i, 2 * j + 1])
                else:
                    preds = [host_nd(o[i]) for o in outs_host]
                    eval_metric.update(labels_w[i], preds)
                fire_callback(nbatch)
                nbatch += 1
        return nbatch

    # -- predict / iter_predict --------------------------------------------
    def iter_windows(self, eval_data, num_batch):
        """Windowed generator behind predict/iter_predict: yields
        (outputs, nbatch, batch) per BATCH — the iter_predict contract —
        but fetches one stacked window at a time. Windowed outputs are
        HOST-resident NDArrays (carrying the host cpu context — that IS
        the fast path: one fetch per window instead of a per-batch
        device round-trip), already trimmed of pad rows exactly where
        the reference slices them (axis 0). Use as_in_context to move
        one back to the accelerator for further device math."""
        from ..context import cpu as _cpu

        m = self.module
        _tele.gauge('fused_eval.steps_per_call').set(self.window)
        host_nd = host_wrap(_cpu())
        nbatch = 0
        for kind, a, b, _ in self._drive(eval_data, num_batch):
            if kind == 'tail':
                sb = a
                with _tele.span('eval.dispatch', 'eval'):
                    m.forward(sb, is_train=False)
                pad = sb.pad or 0
                with _tele.span('eval.fetch', 'eval'):
                    # host-resident like the windowed outputs, so a
                    # predict merge never concatenates across devices
                    outputs = [host_nd(out[0:out.shape[0] - pad].asnumpy())
                               for out in m.get_outputs()]
                _tele.counter('eval.batches').inc()
                yield outputs, nbatch, sb
                nbatch += 1
                continue
            pieces, win_snaps = a, b
            hmat = None
            if self._health_fn is not None:
                pieces, hrows = pieces
            # one host fetch for the window's stacked outputs, then
            # per-batch pad trim + wrap
            with _tele.span('fused_eval.fetch', 'fused_eval'):
                outs_host = [np.asarray(o) for o in pieces]   # (W, ...)
                if self._health_fn is not None:
                    hmat = np.asarray(hrows)
            self._note_window_health(hmat, win_snaps, nbatch)
            for i, snap in enumerate(win_snaps):
                pad = snap[2] or 0
                outputs = [host_nd(o[i][0:o[i].shape[0] - pad])
                           for o in outs_host]
                yield outputs, nbatch, self._rebuild_batch(snap)
                nbatch += 1
