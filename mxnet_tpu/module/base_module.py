"""BaseModule — the canonical train loop.

Reference: python/mxnet/module/base_module.py:376 (fit: bind → init_params →
init_optimizer → per-batch forward_backward/update/metric/callbacks),
score/predict/forward_backward and the parameter-access contract.
"""
import logging
import time
import warnings

import numpy as np

from .. import faults as _faults
from .. import metric as metric_mod
from .. import ndarray as nd
from .. import profiler as _profiler
from .. import telemetry as _tele
from ..io import DataDesc
from ..model import BatchEndParam
from ..initializer import Uniform

__all__ = ['BaseModule']


def _check_input_names(symbol, names, typename, throw):
    args = symbol.list_arguments()
    for name in names:
        if name in args:
            continue
        candidates = [arg for arg in args if not arg.endswith('_weight') and
                      not arg.endswith('_bias') and not arg.endswith('_gamma')
                      and not arg.endswith('_beta')]
        msg = "\033[91mYou created Module with Module(..., %s_names=%s) but " \
              "input with name '%s' is not found in symbol.list_arguments(). " \
              "Did you mean one of:\n\t%s\033[0m" % (
                  typename, str(names), name, '\n\t'.join(candidates))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _check_names_match(data_names, data_shapes, name, throw):
    """Reference base_module.py:56 — input descriptor names must match
    the module's declared names: mismatched data names raise; label
    mismatches only warn (predict-time modules bind without labels).
    Without this gate a wrong label_name surfaces much later as a
    KeyError in the executor group (or trains silently through the
    fused window's positional binding)."""
    actual = [x[0] for x in data_shapes]
    if sorted(data_names) != sorted(actual):
        msg = "Data provided by %s_shapes don't match names specified by " \
              "%s_names (%s vs. %s)" % (name, name, str(data_shapes),
                                        str(data_names))
        if throw:
            raise ValueError(msg)
        warnings.warn(msg)


def _parse_data_desc(data_names, label_names, data_shapes, label_shapes):
    data_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                   for x in data_shapes]
    _check_names_match(data_names, data_shapes, 'data', True)
    if label_shapes is not None:
        label_shapes = [x if isinstance(x, DataDesc) else DataDesc(*x)
                        for x in label_shapes]
        _check_names_match(label_names, label_shapes, 'label', False)
    else:
        _check_names_match(label_names, [], 'label', False)
    return data_shapes, label_shapes


class BaseModule:
    """Reference base_module.py:66."""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- high-level API ---------------------------------------------------
    def forward_backward(self, data_batch):
        """Reference base_module.py:189."""
        self.forward(data_batch, is_train=True)
        self.backward()

    def _set_eval_rate(self, nbatches, batch_size, tic):
        """eval_samples_per_sec gauge, the eval twin of the fit loop's
        speedometer.samples_per_sec (no-op while telemetry is off)."""
        if nbatches and batch_size:
            dt = time.time() - tic
            if dt > 0:
                _tele.gauge('eval_samples_per_sec').set(
                    round(nbatches * batch_size / dt, 2))

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Reference base_module.py:204."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        tic = time.time()

        # TPU fast path: compile a window of N forward steps + on-device
        # metric accumulation into one XLA call (lax.scan) when the
        # module/metric combination allows it — one dispatch and one
        # fetch per window instead of two per batch (module/
        # fused_eval.py). Falls back silently, like fit's fused window.
        from .fused_eval import FusedEvalLoop
        fused = FusedEvalLoop.build_cached(self, eval_metric,
                                           logger=self.logger)
        if fused is not None:
            actual_num_batch = fused.run_score(eval_data, eval_metric,
                                               num_batch,
                                               batch_end_callback, epoch)
        else:
            actual_num_batch = 0
            for nbatch, eval_batch in enumerate(eval_data):
                if num_batch is not None and nbatch == num_batch:
                    break
                with _tele.span('eval.dispatch', 'eval'):
                    self.forward(eval_batch, is_train=False)
                with _tele.span('eval.metric', 'eval'):
                    self.update_metric(eval_metric, eval_batch.label)
                _tele.counter('eval.batches').inc()
                _tele.watchdog.note_progress('eval.step')
                if batch_end_callback is not None:
                    params = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                           eval_metric=eval_metric,
                                           locals=locals())
                    for callback in _as_list(batch_end_callback):
                        callback(params)
                actual_num_batch += 1
        self._set_eval_rate(actual_num_batch,
                            getattr(eval_data, 'batch_size', 0), tic)
        if score_end_callback:
            params = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                   eval_metric=eval_metric, locals=locals())
            for callback in _as_list(score_end_callback):
                callback(params)
        # the eval loop's progress marks armed the hang watchdog; this
        # driven region is over — disarm so a standalone score followed
        # by long host work cannot false-trip (inside fit the next
        # epoch's first step mark re-arms immediately)
        _tele.watchdog.suspend()
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        try:
            # fused window path (one dispatch + one fetch per N
            # batches); silent fallback to the per-batch loop
            from .fused_eval import FusedEvalLoop
            fused = FusedEvalLoop.build_cached(self, None,
                                               logger=self.logger)
            if fused is not None:
                yield from fused.iter_windows(eval_data, num_batch)
                return
            for nbatch, eval_batch in enumerate(eval_data):
                if num_batch is not None and nbatch == num_batch:
                    break
                with _tele.span('eval.dispatch', 'eval'):
                    self.forward(eval_batch, is_train=False)
                pad = eval_batch.pad
                with _tele.span('eval.fetch', 'eval'):
                    outputs = [out[0:out.shape[0] - pad]
                               for out in self.get_outputs()]
                _tele.counter('eval.batches').inc()
                yield (outputs, nbatch, eval_batch)
        finally:
            # fused windows marked the hang watchdog: disarm when the
            # consumer stops (exhaustion OR early generator close)
            _tele.watchdog.suspend()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Reference base_module.py:292."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        tic = time.time()
        from .fused_eval import FusedEvalLoop
        fused = FusedEvalLoop.build_cached(self, None, logger=self.logger)
        output_list = []
        if fused is not None:
            # windowed forward: outputs arrive per batch already
            # pad-trimmed and host-resident (one fetch per window)
            for outputs, _, _ in fused.iter_windows(eval_data, num_batch):
                output_list.append(outputs)
        else:
            for nbatch, eval_batch in enumerate(eval_data):
                if num_batch is not None and nbatch == num_batch:
                    break
                with _tele.span('eval.dispatch', 'eval'):
                    self.forward(eval_batch, is_train=False)
                pad = eval_batch.pad
                with _tele.span('eval.fetch', 'eval'):
                    outputs = [out[0:out.shape[0] - pad].copy()
                               for out in self.get_outputs()]
                _tele.counter('eval.batches').inc()
                output_list.append(outputs)
        self._set_eval_rate(len(output_list),
                            getattr(eval_data, 'batch_size', 0), tic)
        # same disarm as score(): predict's windows marked the watchdog
        _tele.watchdog.suspend()
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                assert len(out) == num_outputs, \
                    'Cannot merge batches, as num of outputs is not the same ' \
                    'in mini-batches. Maybe bucketing is used?'
            output_list2 = [nd.concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric='acc',
            epoch_end_callback=None, batch_end_callback=None, kvstore='local',
            optimizer='sgd', optimizer_params=(('learning_rate', 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """THE canonical train loop (reference base_module.py:376)."""
        assert num_epoch is not None, 'please specify number of epochs'

        # decide telemetry before bind: the XLA compile listener must be
        # live before this fit's first compile so warmups are counted
        _tele.enabled()
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        # resilience tier (module/checkpointing.py): periodic async
        # sharded checkpoints + restore-from-last-good, built from the
        # MXTPU_CKPT_* flags. Restore happens HERE — before the fused
        # window programs are built — so a resumed run binds the same
        # programs a fresh one would. Flags off = None, nothing runs.
        from .checkpointing import TrainCheckpointer
        ckpt = TrainCheckpointer.for_fit(self, eval_metric,
                                         logger=self.logger)
        # fault-injection harness (mxnet_tpu/faults.py): one cached
        # bool; every seam below is dead code while the flag is unset
        faults_on = _faults.enabled()

        # TPU fast path: compile a window of N steps into one XLA call
        # (lax.scan) when the module/optimizer/metric combination allows
        # it — same numerics, one dispatch per window instead of four
        # per batch (see module/fused_fit.py). Falls back silently.
        fused = None
        if monitor is None:
            from .fused_fit import FusedFitLoop
            fused = FusedFitLoop.build_cached(self, eval_metric,
                                              logger=self.logger)
        if fused is None:
            # flag honesty: an explicitly-requested MXTPU_SHARDED_UPDATE
            # can only engage inside the fused SPMD window — the
            # per-batch reference loop below updates replicated
            from .fused_fit import (_shard_update_requested,
                                    note_replicated_update)
            if _shard_update_requested():
                note_replicated_update(
                    'the per-batch reference loop is running '
                    '(no fused window built)', site='fit')
        # training-health sentinels (telemetry/health): the per-batch
        # loop feeds the step-time spike detector; the in-graph
        # finite/norm sentinels ride the executor's fwd+bwd program.
        # One cached-bool check — zero overhead while off. The cluster
        # sync hook (telemetry/cluster.py) is gated the same way.
        health_on = _tele.health.enabled()
        # per-layer dynamics (telemetry/dynamics): executor-level rows
        # take their step index from the same note_batch context the
        # health incidents use, so the batch context is fed when EITHER
        # plane is on
        dyn_on = _tele.dynamics.enabled()
        cluster_on = _tele.cluster.enabled()
        # run ledger (telemetry/ledger): every fit() emits a fresh
        # run_seq-tagged manifest — a second in-process fit (or a
        # resilient_fit retry) may run under different flags, and
        # run_compare keys on the latest; the per-step scalars
        # (loss/lr/throughput/grad stats) bank at MXTPU_SCALARS_EVERY
        ledger_on = _tele.ledger.enabled()
        _tele.ledger.begin_run(module=self)
        # hang watchdog (telemetry/watchdog.py): per-step progress marks
        # feed the stall monitor; off = one cached-bool check here and
        # no call in the loop
        wd_on = _tele.watchdog.enabled()
        # live-bytes timeline (telemetry/memory): one cached-bool check
        # here, a host-side allocator sample at the scalars cadence
        mem_on = _tele.memory.enabled()
        # pod step timeline (telemetry/timeline): the per-step counter
        # behind the phase ledger's per-step normalization — the phase
        # durations themselves ride the spans this loop already emits
        tl_on = _tele.timeline.enabled()

        try:
            for epoch in range(begin_epoch, num_epoch):
                tic = time.time()
                eval_metric.reset()
                if ckpt is not None and not ckpt.begin_epoch(
                        epoch, eval_metric, train_data):
                    # resume fast-forward: this epoch was fully trained
                    # before the restore point — skip it without touching
                    # the data or running its eval
                    continue
                if fused is not None:
                    nbatch = fused.run_epoch(train_data, eval_metric, epoch,
                                             batch_end_callback, ckpt=ckpt)
                    self._fit_epoch_end(epoch, eval_metric, tic,
                                        epoch_end_callback, eval_data,
                                        validation_metric, eval_end_callback,
                                        eval_batch_end_callback)
                    if cluster_on:
                        # elastic input re-balancing: a pending shard
                        # shift applies here, before the reset re-draws
                        _tele.cluster.apply_shard_shift(train_data,
                                                        logger=self.logger)
                    train_data.reset()
                    continue
                # a resumed epoch's first batch IS batch r_step: true
                # batch-in-epoch indices for callbacks and incidents
                nbatch = ckpt.epoch_nbatch_base if ckpt is not None else 0
                data_iter = iter(train_data)
                end_of_batch = False
                next_data_batch = None
                try:
                    next_data_batch = next(data_iter)
                except StopIteration:
                    if ckpt is None or not ckpt.allow_empty_epoch(epoch):
                        raise
                    # the restore point was exactly this epoch's boundary:
                    # the resume skip consumed every batch, so the epoch
                    # is already trained — fall through to its epoch end
                    end_of_batch = True
                while not end_of_batch:
                    data_batch = next_data_batch
                    if faults_on:
                        # nan-grad draw seam (batches counted in step order)
                        data_batch = _faults.maybe_poison_batch(data_batch)
                    if monitor is not None:
                        monitor.tic()
                    t_step = time.time() if health_on else 0.0
                    if health_on or dyn_on:
                        # executor-level incidents carry the real batch index
                        _tele.health.note_batch(nbatch)
                    # per-batch telemetry: host-dispatch vs draw vs metric vs
                    # callback time (all no-ops unless MXTPU_TELEMETRY=1 or
                    # the chrome-trace profiler is running)
                    with _tele.span('fit.batch', 'fit'):
                        with _tele.span('fit.dispatch', 'fit'):
                            self.forward_backward(data_batch)
                            self.update()
                        _tele.counter('fit.steps').inc()
                        if wd_on:
                            _tele.watchdog.note_progress('fit.step')
                        # MXTPU_XPROF step-windowed device-trace capture
                        _profiler.note_step()
                        try:
                            with _tele.span('fit.draw', 'fit'):
                                next_data_batch = next(data_iter)
                            self.prepare(next_data_batch)
                        except StopIteration:
                            end_of_batch = True
                        with _tele.span('fit.metric', 'fit'):
                            self.update_metric(eval_metric, data_batch.label)
                        if monitor is not None:
                            monitor.toc_print()
                        if batch_end_callback is not None:
                            batch_end_params = BatchEndParam(
                                epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals())
                            with _tele.span('fit.callback', 'fit'):
                                for callback in _as_list(batch_end_callback):
                                    callback(batch_end_params)
                    if health_on:
                        _tele.health.note_step_time(time.time() - t_step)
                    if cluster_on:
                        # off-sync steps: one clock read + a deque append;
                        # the allgather fires every SYNC_EVERY steps only
                        _tele.cluster.note_step()
                    if ledger_on:
                        # lr passed lazily: the scheduler sample only
                        # runs on the decimated due steps
                        _tele.ledger.note_train_step(
                            lr=lambda: _cur_lr(
                                getattr(self, '_optimizer', None)),
                            metric=eval_metric)
                    if ckpt is not None:
                        # per-batch path: the sentinel check already ran in
                        # backward, so health trails by nothing (lag=0)
                        ckpt.note_steps(1)
                    if faults_on:
                        _faults.note_steps(1)
                    if mem_on:
                        _tele.memory.note_step(1)
                    if tl_on:
                        _tele.timeline.note_step(1)
                    nbatch += 1

                self._fit_epoch_end(epoch, eval_metric, tic,
                                    epoch_end_callback, eval_data,
                                    validation_metric, eval_end_callback,
                                    eval_batch_end_callback)
                if cluster_on:
                    _tele.cluster.apply_shard_shift(train_data,
                                                    logger=self.logger)
                train_data.reset()
        except BaseException as e:  # noqa: BLE001 — incl. Ctrl-C/exit
            if ckpt is not None:
                # the run is dying with a save possibly in flight: drain
                # and certify NOW, while the interpreter is whole — at
                # teardown orbax's commit thread loses its executors
                # ("cannot schedule new futures after shutdown") and the
                # save would never commit, leaving a supervised relaunch
                # (tools/train_supervisor.py) nothing to restore. A
                # KeyboardInterrupt drains too: preserving the last save
                # is exactly what an interrupted operator wants.
                # Idempotent: resilient_fit's handle_failure call after
                # this re-raise finds nothing pending.
                diag = getattr(e, 'diagnostic', None)
                try:
                    ckpt.handle_failure(dict(diag) if diag else None)
                except Exception:  # noqa: BLE001 — never mask the failure
                    pass
            if wd_on:
                # fit is over (however it ended): stop expecting marks
                # so post-training host work cannot false-trip
                _tele.watchdog.suspend()
            raise

        if ckpt is not None:
            # final save + writer drain + last-good certification
            # (its commit emits one more progress mark — suspend after)
            ckpt.finish()
        if wd_on:
            _tele.watchdog.suspend()

    def _fit_epoch_end(self, epoch, eval_metric, tic, epoch_end_callback,
                       eval_data, validation_metric, eval_end_callback,
                       eval_batch_end_callback):
        """Epoch-end bookkeeping shared by the reference per-batch loop
        and the fused fast path (reference base_module.py:528-553)."""
        # the batch loop is over: clear the executor-incident step
        # context so a later custom-loop incident cannot inherit a
        # stale index (one attribute store — safe while health is off)
        _tele.health.note_batch(None)
        _tele.counter('fit.epochs').inc()
        _tele.xla.sample_memory()   # live/peak device bytes, once per epoch
        name_vals = eval_metric.get_name_value()
        for name, val in name_vals:
            self.logger.info('Epoch[%d] Train-%s=%f', epoch, name, val)
        _tele.ledger.note_eval([('train-%s' % n, v) for n, v in name_vals],
                               epoch=epoch)
        toc = time.time()
        self.logger.info('Epoch[%d] Time cost=%.3f', epoch, (toc - tic))

        arg_params_, aux_params_ = self.get_params()
        self.set_params(arg_params_, aux_params_)
        if epoch_end_callback is not None:
            for callback in _as_list(epoch_end_callback):
                callback(epoch, self.symbol, arg_params_, aux_params_)

        if eval_data:
            res = self.score(eval_data, validation_metric,
                             score_end_callback=eval_end_callback,
                             batch_end_callback=eval_batch_end_callback,
                             epoch=epoch)
            for name, val in res:
                self.logger.info('Epoch[%d] Validation-%s=%f',
                                 epoch, name, val)
            _tele.ledger.note_eval([('val-%s' % n, v) for n, v in res],
                                   epoch=epoch)
        # score() suspends the hang watchdog on exit (standalone-eval
        # semantics); mid-fit the NEXT epoch is coming, so re-arm here
        # — a host lost during eval wedges exactly the next epoch's
        # first collective, and that window must stay covered
        _tele.watchdog.note_progress('fit.epoch_end')

    # -- parameter contract (implemented by subclasses) --------------------
    @property
    def symbol(self):
        return self._symbol

    @property
    def data_names(self):
        raise NotImplementedError()

    @property
    def output_names(self):
        raise NotImplementedError()

    @property
    def data_shapes(self):
        raise NotImplementedError()

    @property
    def label_shapes(self):
        raise NotImplementedError()

    @property
    def output_shapes(self):
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError()

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        arg_params, aux_params = self.get_params()
        save_dict = {('arg:%s' % k): v for k, v in arg_params.items()}
        save_dict.update({('aux:%s' % k): v for k, v in aux_params.items()})
        nd.save(fname, save_dict)

    def load_params(self, fname):
        save_dict = nd.load(fname)
        arg_params = {}
        aux_params = {}
        for k, value in save_dict.items():
            arg_type, name = k.split(':', 1)
            if arg_type == 'arg':
                arg_params[name] = value
            elif arg_type == 'aux':
                aux_params[name] = value
            else:
                raise ValueError('Invalid param file ' + fname)
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        assert not merge_multi_context
        return []

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        assert not states and not value

    def install_monitor(self, mon):
        raise NotImplementedError()

    def prepare(self, data_batch):
        pass

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError()

    def get_input_grads(self, merge_multi_context=True):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req='write'):
        raise NotImplementedError()

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        raise NotImplementedError()


def _as_list(obj):
    if isinstance(obj, list):
        return obj
    return [obj]


def _cur_lr(opt):
    """The optimizer's CURRENT effective base learning rate (scheduler
    honored), or None — the run ledger's lr scalar."""
    if opt is None:
        return None
    try:
        if getattr(opt, 'lr_scheduler', None) is not None:
            return float(opt.lr_scheduler(opt.num_update))
        return float(opt.lr)
    except Exception:  # noqa: BLE001 — exotic optimizer: no lr scalar
        return None
