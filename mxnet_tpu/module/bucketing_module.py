"""BucketingModule — per-bucket Modules sharing one master's parameters.

Reference: python/mxnet/module/bucketing_module.py:35 — the reference's
long-sequence strategy (SURVEY.md §5.7): one Module per sequence-length
bucket, all sharing the widest ("default") bucket's parameter arrays;
_curr_module switches per batch.

TPU note: each bucket shape is its own XLA compilation (cached); sharing
works because shared_module passes the same parameter NDArrays through.
"""
import logging
import warnings

from .. import context as ctx_mod
from ..initializer import Uniform
from .base_module import BaseModule
from .module import Module

__all__ = ['BucketingModule']


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._fixed_param_names = fixed_param_names or []
        self._state_names = state_names or []
        self._context = context if context is not None else ctx_mod.cpu()
        self._work_load_list = work_load_list
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False
        self._monitor = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    def _call_sym_gen(self, *args, **kwargs):
        return self._sym_gen(*args, **kwargs)

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def get_params(self):
        assert self.binded and self.params_initialized
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=allow_missing,
                             force_init=force_init)
            return
        if self.params_initialized and not force_init:
            warnings.warn('Parameters already initialized and force_init=False. '
                          'set_params call ignored.', stacklevel=2)
            return
        self._curr_module.set_params(arg_params, aux_params,
                                     allow_missing=allow_missing,
                                     force_init=force_init,
                                     allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, 'call bind before initializing the parameters'
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def get_states(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        self._curr_module.set_states(states, value)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req='write'):
        assert shared_module is None, \
            'shared_module for BucketingModule is not supported'
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning('Already bound, ignoring bind()')
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        symbol, data_names, label_names = self._call_sym_gen(
            self._default_bucket_key)
        module = Module(symbol, data_names, label_names, logger=self.logger,
                        context=self._context,
                        work_load_list=self._work_load_list,
                        fixed_param_names=self._fixed_param_names,
                        state_names=self._state_names)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=False, shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module
        if self._monitor is not None:
            # a force_rebind recreates the default bucket; the saved
            # monitor must follow it or default-key batches go silent
            module.install_monitor(self._monitor)

        if self.params_initialized:
            self.set_params(self._arg_params, self._aux_params)

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Reference bucketing_module.py:322."""
        assert self.binded, 'call bind before switching bucket'
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            work_load_list=self._work_load_list,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names)
            module.bind(data_shapes, label_shapes, self._curr_module.for_training,
                        self._curr_module.inputs_need_grad,
                        force_rebind=False,
                        shared_module=self._buckets[self._default_bucket_key])
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key
        # share parameters from the master module
        master = self._buckets[self._default_bucket_key]
        if master.params_initialized:
            arg_params, aux_params = master._arg_params, master._aux_params
            self._curr_module._arg_params = arg_params
            self._curr_module._aux_params = aux_params
            self._curr_module.params_initialized = True
            self._curr_module._exec_group.set_params(arg_params, aux_params)

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        self._params_dirty = True
        self._curr_module.update()
        # propagate updated params back into the master module's arrays
        master = self._buckets[self._default_bucket_key]
        if self._curr_module is not master:
            self._curr_module._sync_params_from_devices()
            master._exec_group.set_params(self._curr_module._arg_params,
                                          self._curr_module._aux_params)

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._curr_module.get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._curr_module.get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        self._curr_module.update_metric(eval_metric, labels)

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning('optimizer already initialized, ignoring.')
            return
        self._curr_module.init_optimizer(kvstore, optimizer, optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod.borrow_optimizer(self._curr_module)
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        """Install on every live bucket AND save the monitor so
        bind/switch_bucket install it on later-created bucket modules
        (the reference's install_monitor, bucketing_module.py:496-500,
        only covers already-created buckets — lazily-created ones went
        silently unmonitored; fixed here rather than mirrored)."""
        assert self.binded
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    @property
    def _arg_params(self):
        return self._buckets[self._default_bucket_key]._arg_params \
            if self._buckets else None

    @_arg_params.setter
    def _arg_params(self, value):
        pass

    @property
    def _aux_params(self):
        return self._buckets[self._default_bucket_key]._aux_params \
            if self._buckets else None

    @_aux_params.setter
    def _aux_params(self, value):
        pass
