"""Pure-python Module subclasses (no symbolic graph, no executor).

Reference: python/mxnet/module/python_module.py:28 (PythonModule — a
BaseModule whose compute is arbitrary python/NDArray code) and :240
(PythonLossModule — a loss "layer" as a module, used to terminate a
pipeline of chained modules with a hand-written gradient).

TPU note: these run eagerly on NDArrays (each op is an XLA call), which
is exactly their role in the reference too — glue/diagnostic modules,
not the hot path. Anything hot belongs in a symbolic/Gluon module that
compiles to one XLA program.
"""
import logging

from .base_module import BaseModule
from ..initializer import Uniform
from .. import ndarray as nd


class PythonModule(BaseModule):
    """A module whose forward/backward are written directly in python.

    Subclasses override :meth:`forward`, :meth:`backward` and (when the
    module owns parameters) :meth:`get_params` / :meth:`init_params` /
    :meth:`update`. Parameter-free modules get working defaults.
    """

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        if isinstance(data_names, tuple):
            data_names = list(data_names)
        if isinstance(label_names, tuple):
            label_names = list(label_names)
        self._data_names = data_names
        self._label_names = label_names
        self._output_names = output_names
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def get_params(self):
        """Parameter-free by default (reference python_module.py:96)."""
        return (dict(), dict())

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is None:
            return
        eval_metric.update(labels, self.get_outputs())

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req='write'):
        """Record shapes and compute output shapes; there is no executor
        to create (reference python_module.py:162)."""
        if self.binded and not force_rebind:
            self.logger.warning('Already bound, ignoring bind()')
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()

    def _compute_output_shapes(self):
        raise NotImplementedError()

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        self.optimizer_initialized = True


class PythonLossModule(PythonModule):
    """A loss layer as a module: forward is identity on the score input,
    backward produces the hand-written gradient (reference
    python_module.py:240). ``grad_func(scores, labels) -> NDArray``
    overrides the default MakeLoss-style gradient of 1."""

    def __init__(self, name='pyloss', data_names=('data',),
                 label_names=('softmax_label',), logger=logging,
                 grad_func=None):
        super().__init__(list(data_names), list(label_names),
                         [name + '_output'], logger=logger)
        self._name = name
        if grad_func is not None and not callable(grad_func):
            raise TypeError('grad_func must be callable')
        self._grad_func = grad_func
        self._scores = None
        self._labels = None
        self._scores_grad = None

    def _compute_output_shapes(self):
        return [(self._name + '_output', self._data_shapes[0][1])]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if is_train is None:
            is_train = self.for_training
        if is_train:
            self._labels = data_batch.label[0] if data_batch.label else None

    def get_outputs(self, merge_multi_context=True):
        if not merge_multi_context:
            return [[self._scores]]
        return [self._scores]

    def backward(self, out_grads=None):
        if out_grads is not None:
            raise ValueError('PythonLossModule is a terminal loss; '
                             'out_grads is not accepted')
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(grad)
            self._scores_grad = grad
        else:
            self._scores_grad = nd.ones_like(self._scores)

    def get_input_grads(self, merge_multi_context=True):
        if not merge_multi_context:
            return [[self._scores_grad]]
        return [self._scores_grad]

    def install_monitor(self, mon):
        raise NotImplementedError()
