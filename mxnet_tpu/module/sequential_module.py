"""SequentialModule — chain of modules, each consuming the previous outputs.

Reference: python/mxnet/module/sequential_module.py.
"""
import logging
import copy

from ..initializer import Uniform
from .base_module import BaseModule

__all__ = ['SequentialModule']


class SequentialModule(BaseModule):
    """Runs constituent modules back to back: forward threads each
    module's outputs into the next one's data, backward threads input
    gradients the other way. Per-module metadata selects which layers
    see labels (``take_labels``) and whether data names are rewired to
    the next module's inputs (``auto_wiring``)."""

    META_TAKE_LABELS = 'take_labels'
    META_AUTO_WIRING = 'auto_wiring'

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None
        self._meta_keys = {getattr(SequentialModule, name)
                           for name in dir(SequentialModule)
                           if name.startswith('META_')}

    def add(self, module, **kwargs):
        unknown = set(kwargs) - self._meta_keys
        if unknown:
            raise AssertionError('Unknown meta "%s", a typo?'
                                 % unknown.pop())
        self._modules.append(module)
        self._metas.append(kwargs)
        # a structural change invalidates every derived state
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    def _takes_labels(self, index):
        return bool(self._metas[index].get(self.META_TAKE_LABELS))

    # -- shapes/names delegate to the chain's ends ------------------------
    @property
    def data_names(self):
        return self._modules[0].data_names if self._modules else []

    @property
    def output_names(self):
        return self._modules[-1].output_names if self._modules else []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, 'call bind before initializing the parameters'
        for module in self._modules:
            module.init_params(initializer=initializer, arg_params=arg_params,
                               aux_params=aux_params,
                               allow_missing=allow_missing,
                               force_init=force_init, allow_extra=allow_extra)
        self._assert_unique_params()
        self.params_initialized = True

    def _assert_unique_params(self):
        """No parameter name may appear in two chained modules (arg and
        aux namespaces are independent, as in the reference)."""
        arg_owner, aux_owner = {}, {}
        for index, module in enumerate(self._modules):
            arg, aux = module.get_params()
            for owner, names in ((arg_owner, arg), (aux_owner, aux)):
                for name in names:
                    if name in owner:
                        raise AssertionError(
                            'Duplicated parameter names: name "%s" in layer '
                            '%d (%s) is already used in layer %d (%s).'
                            % (name, index, type(module), owner[name],
                               type(self._modules[owner[name]])))
                    owner[name] = index

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req='write'):
        if self.binded and not force_rebind:
            self.logger.warning('Already bound, ignoring bind()')
            return
        if inputs_need_grad:
            assert for_training
        assert shared_module is None, 'Shared module is not supported'
        assert self._modules, 'Attempting to bind an empty SequentialModule'

        self.binded = True
        self._label_shapes = label_shapes

        chained_shapes = data_shapes
        label_consumed = False
        for index, module in enumerate(self._modules):
            if self._takes_labels(index):
                label_consumed = True
            if self._metas[index].get(self.META_AUTO_WIRING, False):
                names = module.data_names
                assert len(names) == len(chained_shapes)
                chained_shapes = [
                    (name, shape)
                    for name, (_, shape) in zip(names, chained_shapes)]
            module.bind(
                data_shapes=chained_shapes,
                label_shapes=label_shapes if self._takes_labels(index)
                else None,
                for_training=for_training,
                # interior modules always need input grads to continue
                # the chain rule upstream
                inputs_need_grad=bool(inputs_need_grad or
                                      (for_training and index > 0)),
                force_rebind=force_rebind, shared_module=None,
                grad_req=grad_req)
            chained_shapes = module.output_shapes

        if not label_consumed:
            self._label_shapes = None

    def init_optimizer(self, kvstore='local', optimizer='sgd',
                       optimizer_params=(('learning_rate', 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            self.logger.warning('optimizer already initialized, ignoring.')
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        batch = copy.copy(data_batch)
        last = len(self._modules) - 1
        for index, module in enumerate(self._modules):
            module.forward(batch, is_train=is_train)
            if index == last:
                break
            # next module consumes this one's outputs as its data
            batch.data = module.get_outputs()
            if hasattr(batch, 'provide_data'):
                names = [name for name, _ in module.output_shapes]
                assert len(names) == len(batch.data)
                batch.provide_data = [(name, out.shape) for name, out
                                      in zip(names, batch.data)]

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        for index in range(len(self._modules) - 1, -1, -1):
            module = self._modules[index]
            module.backward(out_grads=out_grads)
            if index == 0:
                break
            out_grads = module.get_input_grads()

    def update(self):
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        assert self.binded and self.params_initialized
        return self._modules[-1].get_outputs(
            merge_multi_context=merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.params_initialized and \
            self.inputs_need_grad
        return self._modules[0].get_input_grads(
            merge_multi_context=merge_multi_context)

    def update_metric(self, eval_metric, labels):
        assert self.binded and self.params_initialized
        for index, module in enumerate(self._modules):
            if self._takes_labels(index):
                module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        assert self.binded
        for module in self._modules:
            module.install_monitor(mon)
