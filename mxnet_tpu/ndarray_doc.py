"""Per-operator docstring addenda for the ndarray namespace (reference
python/mxnet/ndarray_doc.py): subclass NDArrayDoc with the operator's
name to append examples to the generated wrapper's docstring."""
from .base import build_param_doc as _build_param_doc  # noqa: F401

__all__ = ['NDArrayDoc']


class NDArrayDoc(object):
    """Base class: subclasses named ``<op>Doc`` contribute their
    docstring to the generated ``nd.<op>`` wrapper."""


class ReshapeDoc(NDArrayDoc):
    """
    Examples
    --------
    >>> x = mx.nd.arange(6).reshape((2, 3))
    >>> x.shape
    (2, 3)
    """


class elemwise_addDoc(NDArrayDoc):
    """
    Example
    -------
    >>> (mx.nd.ones((2,)) + mx.nd.ones((2,))).asnumpy()
    array([ 2.,  2.], dtype=float32)
    """


class BroadcastToDoc(NDArrayDoc):
    """
    Examples
    --------
    >>> mx.nd.ones((1, 3)).broadcast_to((2, 3)).shape
    (2, 3)
    """


class CustomDoc(NDArrayDoc):
    """
    Example
    -------
    >>> mx.nd.Custom(x, label, op_type='my_softmax')
    """


def _build_doc(func_name, desc, arg_names, arg_types, arg_desc,
               key_var_num_args=None, ret_type=None):
    """Assemble a generated-wrapper docstring (reference
    ndarray_doc.py:_build_doc)."""
    doc_str = desc + '\n\n' + _build_param_doc(arg_names, arg_types,
                                               arg_desc)
    if key_var_num_args:
        doc_str += '\nThis function supports variable length of '
        doc_str += 'positional input.\n'
    if ret_type:
        doc_str += '\nReturns\n-------\n%s\n    The result.' % ret_type
    hook = globals().get('%sDoc' % func_name)
    if hook and hook.__doc__:
        doc_str += hook.__doc__
    return doc_str
