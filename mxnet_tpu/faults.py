"""Deterministic fault injection for the resilience test matrix.

``MXTPU_FAULT_INJECT=<kind>:<step>[:<arg>]`` arms ONE fault that fires
at a deterministic training step, so every recovery path in the
resilient training stack (module/checkpointing.py restore-from-last-
good, module/resilient_fit.py restart loop, tools/train_supervisor.py)
is exercised by real failures instead of mocks. Kinds:

- ``nan-grad:<k>``       — poison the k-th drawn training batch with a
  NaN (host-side, before upload), so step k computes non-finite
  gradients: the health sentinels detect it at the exact step, the
  bisect names the input, and MXTPU_HEALTH_ACTION=raise turns it into
  the TrainingHealthError the restart driver recovers from. Fires once.
- ``checkpoint-corrupt:<k>`` — scribble over the data files of the
  first checkpoint saved at step >= k AFTER it commits, so a later
  restore of that step fails and the restore path must fall back to an
  older checkpoint. Fires once.
- ``dispatch-exception:<k>[:<seam>]`` — raise :class:`FaultInjected`
  from a dispatch seam (the fused-fit window dispatch, the executor's
  fused fwd+bwd, or the kvstore push) when the training-step counter
  reaches k. ``seam`` restricts which seam fires ('dispatch',
  'executor', 'kvstore'; default: whichever reaches the step first).
  Fires once.
- ``backend-probe-timeout:<n>`` — bench.py's device-backend probe
  reports a timeout for its first n attempts (the r02/r04 flaky-tunnel
  shape), exercising the exponential-backoff reprobe path. bench.py
  parses this flag itself (it must not import the framework before its
  backend decision).
- ``slow-host:<k>[:<ms>]`` — sleep ``ms`` (default 50) per training
  step from step k on, persistently: this host becomes the straggler
  the cluster telemetry names. Never disarms.
- ``mem-hog:<k>[:<mb>]`` — allocate and retain ``mb`` MiB (default 8)
  of device memory per training step from step k on, persistently:
  deterministic host-side allocation growth (a leak's shape) at the
  same step-counter seam slow-host uses. The MXTPU_MEMORY forecaster
  is what should notice — steps-to-OOM shrinking, /healthz flipping to
  ``mem_pressure``, the flight recorder dumped — before the allocator
  dies. Never disarms; the compiled programs are untouched.
- ``clock-skew:<k>[:<ms>]`` — shift this host's wall clock BY MS as
  the timeline plane samples it (telemetry/timeline.py's
  ``note_sync_exit``), from step k on, persistently: injected clock
  drift with zero effect on the training math, the schedule or the
  real clocks. The MXTPU_TIMELINE offset estimator is what should
  notice — ``cluster.h<i>.clock_offset_ms`` naming this host's offset
  while the merged Perfetto trace stays aligned. Never disarms.
- ``hang:<k>[:<secs>]`` — wedge the first dispatch seam that reaches
  step k by sleeping ``secs`` (default 3600) in place: the shape of a
  collective waiting on a dead peer or a tunneled dispatch that never
  returns. The hang watchdog (telemetry/watchdog.py) is what should
  notice; with MXTPU_WATCHDOG_ACTION=abort the process dies with the
  distinct exit code and the supervisor relaunches. Fires once.
- ``host-loss:<k>`` — ``os._exit`` (exit code 113) from the first
  dispatch seam that reaches step k: the process vanishes mid-window
  with no unwind, no atexit, no final checkpoint — exactly what losing
  a host looks like to the supervisor. Fires once (per process; a
  relaunch re-arms unless the driver disarms the env).

Gang scoping: ``MXTPU_FAULT_HOST=<i>`` restricts an armed fault to ONE
host of a multi-process job (matched against this process's
``MXTPU_HOST_ID``). The launcher env rides into every worker of a gang,
so without the guard a ``host-loss:<k>`` would kill EVERY worker at
step k — the chaos tests need to lose exactly one. Unset (default) =
the fault arms wherever the env reaches.

Off (the default, flag empty) every seam is one cached-bool check —
the same zero-overhead contract the telemetry stack keeps. Nothing
here is ever traced into a compiled program: injection happens at
host-side seams (batch draw, dispatch call, checkpoint commit), so the
lowered XLA programs are byte-identical with the harness armed or not.
"""
import logging
import os
import threading
import time

import numpy as np

__all__ = ['FaultInjected', 'HOST_LOSS_EXIT_CODE', 'enabled', 'spec',
           'note_steps', 'clock_skew_ms', 'maybe_poison_snap',
           'maybe_poison_batch', 'maybe_raise',
           'maybe_corrupt_checkpoint']

KINDS = ('nan-grad', 'checkpoint-corrupt', 'dispatch-exception',
         'backend-probe-timeout', 'slow-host', 'hang', 'host-loss',
         'mem-hog', 'clock-skew')

_SLOW_DEFAULT_MS = 50.0
_HOG_DEFAULT_MB = 8.0
_SKEW_DEFAULT_MS = 100.0
_hog = []   # mem-hog's retained device allocations (the leak itself)
_HANG_DEFAULT_SECS = 3600.0
HOST_LOSS_EXIT_CODE = 113   # distinct from the watchdog's 85


class FaultInjected(RuntimeError):
    """Raised by an armed ``dispatch-exception`` fault; carries the
    seam and step for the restart driver's restart record."""

    def __init__(self, message, seam=None, step=None):
        super().__init__(message)
        self.seam = seam
        self.step = step


class _FState:
    __slots__ = ('decided', 'active', 'kind', 'step', 'arg', 'drawn',
                 'steps', 'fired', 'lock')

    def __init__(self):
        self.decided = False
        self.active = False
        self.kind = None
        self.step = 0
        self.arg = None
        self.drawn = 0      # training batches drawn so far (draw order
        self.steps = 0      # == step order in every fit loop)
        self.fired = False
        self.lock = threading.Lock()


_state = _FState()
_decide_lock = threading.Lock()


def _parse(raw):
    """'<kind>:<step>[:<arg>]' -> (kind, step, arg) or None."""
    parts = raw.split(':')
    if len(parts) < 2 or parts[0] not in KINDS:
        raise ValueError(
            'MXTPU_FAULT_INJECT=%r: expected <kind>:<step>[:<arg>] with '
            'kind one of %s' % (raw, list(KINDS)))
    return parts[0], int(parts[1]), (parts[2] if len(parts) > 2 else None)


def _host_guard():
    """(fault_host, my_host): the MXTPU_FAULT_HOST restriction and this
    process's MXTPU_HOST_ID rank. fault_host None = unrestricted."""
    try:
        from .config import flags
        flags.reload('MXTPU_FAULT_HOST')
        flags.reload('MXTPU_HOST_ID')
        fault_host = flags.get('MXTPU_FAULT_HOST')
        my_host = flags.get('MXTPU_HOST_ID')
    except Exception:  # noqa: BLE001 — stripped builds without the flags
        try:
            fault_host = int(os.environ.get('MXTPU_FAULT_HOST', '-1'))
            my_host = int(os.environ.get('MXTPU_HOST_ID', '0'))
        except ValueError:
            return None, 0
    return (None if fault_host is None or fault_host < 0 else
            int(fault_host)), int(my_host)


def _decide():
    with _decide_lock:
        if _state.decided:
            return _state.active
        raw = ''
        try:
            from .config import flags
            flags.reload('MXTPU_FAULT_INJECT')
            raw = flags.get('MXTPU_FAULT_INJECT') or ''
        except Exception:  # noqa: BLE001 — stripped builds without the flag
            raw = os.environ.get('MXTPU_FAULT_INJECT', '')
        raw = raw.strip()
        if raw:
            try:
                kind, step, arg = _parse(raw)
                fault_host, my_host = _host_guard()
                if fault_host is not None and fault_host != my_host:
                    # another gang member's fault: the launcher env
                    # reaches every worker, but only host <fault_host>
                    # arms — this process runs clean (and says so once,
                    # or a one-worker kill would look like magic)
                    logging.info(
                        'fault injection: %s armed for host %d only — '
                        'this is host %d, fault inert', kind, fault_host,
                        my_host)
                else:
                    _state.kind, _state.step, _state.arg = kind, step, arg
                    _state.active = True
                    logging.warning(
                        'fault injection armed: %s at step %d%s%s',
                        kind, step, ' (%s)' % arg if arg else '',
                        ' [host %d]' % my_host
                        if fault_host is not None else '')
            except ValueError as e:
                logging.warning('%s — fault injection disabled', e)
        _state.decided = True
    return _state.active


def enabled():
    """Whether a fault is armed (decided once from MXTPU_FAULT_INJECT).
    One attribute check after the first call — the seams' gate."""
    if _state.decided:
        return _state.active
    return _decide()


def spec():
    """(kind, step, arg) of the armed fault, or None."""
    if not enabled():
        return None
    return _state.kind, _state.step, _state.arg


def note_steps(n=1):
    """Advance the trained-step counter (fed by the fit loops at the
    same sites that count fit.steps). An armed ``slow-host`` fault
    sleeps here once the counter passes its step; an armed ``mem-hog``
    allocates-and-retains here — both persist, never disarm."""
    if not enabled():
        return
    with _state.lock:
        _state.steps += n
        slow = (_state.kind == 'slow-host' and _state.steps > _state.step)
        hog = (_state.kind == 'mem-hog' and _state.steps > _state.step)
    if slow:
        try:
            ms = float(_state.arg) if _state.arg else _SLOW_DEFAULT_MS
        except ValueError:
            ms = _SLOW_DEFAULT_MS
        time.sleep(n * ms / 1e3)
    if hog:
        try:
            mb = float(_state.arg) if _state.arg else _HOG_DEFAULT_MB
        except ValueError:
            mb = _HOG_DEFAULT_MB
        try:
            import jax.numpy as jnp
            # n steps' worth of leak, committed to the device so the
            # allocator's bytes_in_use actually climbs (block_until_
            # ready: a never-dispatched lazy array leaks nothing)
            arr = jnp.zeros((max(1, int(n * mb * 2**20 / 4)),),
                            jnp.float32)
            _hog.append(arr.block_until_ready())
        except Exception as e:  # noqa: BLE001 — a chaos harness must
            logging.warning(                   # not crash the run itself
                'fault injection: mem-hog allocation failed: %s', e)


def clock_skew_ms():
    """The wall-clock shift (ms) an armed ``clock-skew`` fault applies
    to this host's timeline clock samples — 0.0 unarmed / before the
    armed step. ``>=`` so ``clock-skew:0`` skews from the very first
    sync round (the trained-step counter may still be 0 then); like
    slow-host/mem-hog it persists and never disarms."""
    if not enabled():
        return 0.0
    with _state.lock:
        hit = (_state.kind == 'clock-skew' and _state.steps >= _state.step)
        arg = _state.arg
    if not hit:
        return 0.0
    try:
        return float(arg) if arg else _SKEW_DEFAULT_MS
    except ValueError:
        return _SKEW_DEFAULT_MS


def _poison(arr):
    """One NaN planted at the origin of a float array (jax or numpy);
    non-float arrays come back unchanged."""
    import jax.numpy as jnp
    idx = tuple(0 for _ in arr.shape)
    if isinstance(arr, np.ndarray):
        if arr.dtype.kind != 'f':
            return arr, False
        out = arr.copy()
        out[idx] = np.nan
        return out, True
    if jnp.issubdtype(arr.dtype, jnp.floating):
        return arr.at[idx].set(jnp.nan), True
    return arr, False


def _poison_arrays(datas, labels):
    """Poison the first float array among datas then labels (defer-mode
    uint8 batches fall through to the label). Returns (datas, labels,
    poisoned_any)."""
    datas = list(datas)
    for i, a in enumerate(datas):
        out, ok = _poison(a)
        if ok:
            datas[i] = out
            return tuple(datas), tuple(labels), True
    labels = list(labels)
    for i, a in enumerate(labels):
        out, ok = _poison(a)
        if ok:
            labels[i] = out
            return tuple(datas), tuple(labels), True
    return tuple(datas), tuple(labels), False


def _armed_draw():
    """True when THIS draw is the poisoned one (advances the counter)."""
    with _state.lock:
        hit = (_state.kind == 'nan-grad' and not _state.fired
               and _state.drawn == _state.step)
        _state.drawn += 1
        if hit:
            _state.fired = True
    return hit


def _note_poison(hit):
    if hit:
        logging.warning('fault injection: nan-grad fired on batch %d',
                        _state.step)
    else:
        # the armed draw is consumed either way (firing at a LATER step
        # than requested would be worse) — but dropping the fault
        # silently would make a hung chaos test undebuggable
        logging.warning(
            'fault injection: nan-grad armed for batch %d but the batch '
            'holds no float array (defer-mode uint8 data, int labels?) '
            '— fault NOT injected', _state.step)


def maybe_poison_snap(snap):
    """Fused-loop draw seam: one (data_arrays, label_arrays, pad, index)
    draw-time snapshot in, possibly NaN-poisoned out. Counts every
    drawn training batch so the armed step is a global batch index."""
    if not _armed_draw():
        return snap
    ds, ls, pad, idx = snap
    ds, ls, hit = _poison_arrays(ds, ls)
    _note_poison(hit)
    return ds, ls, pad, idx


def maybe_poison_batch(batch):
    """Per-batch-loop draw seam: poison a DataBatch's NDArrays in place
    (same counter as :func:`maybe_poison_snap`)."""
    if not _armed_draw():
        return batch
    ds = tuple(a._data for a in batch.data)
    ls = tuple(a._data for a in (batch.label or ()))
    ds, ls, hit = _poison_arrays(ds, ls)
    if hit:
        for a, v in zip(batch.data, ds):
            a._data = v
        for a, v in zip(batch.label or (), ls):
            a._data = v
    _note_poison(hit)
    return batch


def maybe_raise(seam, upcoming=1):
    """Dispatch seam: fire an armed ``dispatch-exception`` (raise
    :class:`FaultInjected`), ``hang`` (sleep in place — the wedged-
    collective shape the watchdog must catch) or ``host-loss``
    (``os._exit``, no unwind) fault when its step falls inside the
    ``upcoming`` steps this dispatch is about to advance (the fused
    window passes its window size). For ``dispatch-exception``,
    ``arg`` (when set) restricts the firing seam."""
    if not enabled():
        return
    with _state.lock:
        kind = _state.kind
        if (kind not in ('dispatch-exception', 'hang', 'host-loss')
                or _state.fired
                or _state.steps + upcoming <= _state.step):
            return
        if kind == 'dispatch-exception' and _state.arg \
                and _state.arg != seam:
            return
        _state.fired = True
        step = _state.step
        arg = _state.arg
    if kind == 'hang':
        try:
            secs = float(arg) if arg else _HANG_DEFAULT_SECS
        except ValueError:
            secs = _HANG_DEFAULT_SECS
        logging.warning('fault injection: hang fired at the %s seam '
                        '(step %d) — sleeping %.1fs', seam, step, secs)
        time.sleep(secs)
        return
    if kind == 'host-loss':
        logging.warning('fault injection: host-loss fired at the %s seam '
                        '(step %d) — os._exit(%d)', seam, step,
                        HOST_LOSS_EXIT_CODE)
        os._exit(HOST_LOSS_EXIT_CODE)
    raise FaultInjected(
        'injected dispatch failure at the %s seam (step %d)'
        % (seam, step), seam=seam, step=step)


def maybe_corrupt_checkpoint(directory, step):
    """Checkpoint seam (called after a save commits): truncate the
    committed step's data files so a later restore of it fails. Fires
    on the first save at step >= the armed step."""
    if not enabled():
        return False
    with _state.lock:
        hit = (_state.kind == 'checkpoint-corrupt' and not _state.fired
               and int(step) >= _state.step)
        if hit:
            _state.fired = True
    if not hit:
        return False
    n = 0
    for root, _, files in os.walk(os.path.join(str(directory), str(step))):
        for name in files:
            try:
                with open(os.path.join(root, name), 'r+b') as f:
                    f.truncate(2)
                n += 1
            except OSError:
                pass
    logging.warning('fault injection: checkpoint-corrupt fired — '
                    'truncated %d file(s) of step %s in %s',
                    n, step, directory)
    return True


def _reset_for_tests():
    global _state
    _state = _FState()
    _hog.clear()
