"""Automatic naming for the symbolic API — module-path parity shim.

Reference: python/mxnet/name.py (NameManager/Prefix). The
implementations live in attribute.py beside AttrScope (one scope
stack); this module keeps the reference's import path working.
"""
from .attribute import NameManager, Prefix

__all__ = ['NameManager', 'Prefix']
