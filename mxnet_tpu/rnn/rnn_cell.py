"""Symbolic RNN cells.

Reference: python/mxnet/rnn/rnn_cell.py (1,423 LoC): BaseRNNCell:108,
RNNCell/LSTMCell/GRUCell, FusedRNNCell:536 (maps to the fused RNN op;
unfuse() back to explicit cells), SequentialRNNCell, BidirectionalCell,
DropoutCell, ModifierCell (Zoneout/Residual).
"""
from .. import symbol
from ..symbol.symbol import Symbol
from ..base import string_types

__all__ = ['BaseRNNCell', 'RNNCell', 'LSTMCell', 'GRUCell', 'FusedRNNCell',
           'SequentialRNNCell', 'BidirectionalCell', 'DropoutCell',
           'ModifierCell', 'ZoneoutCell', 'ResidualCell', 'RNNParams',
           'BaseConvRNNCell', 'ConvRNNCell', 'ConvLSTMCell', 'ConvGRUCell']


class RNNParams:
    """Container for holding variables (reference rnn_cell.py:39)."""

    def __init__(self, prefix=''):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Reference rnn_cell.py:108."""

    def __init__(self, prefix='', params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError()

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError()

    @property
    def state_shape(self):
        return [ele['shape'] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified, \
            'After applying modifier cells the base cell cannot be called directly. Call the modifier cell instead.'
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if info is None:
                state = func(name='%sbegin_state_%d' % (self._prefix,
                                                        self._init_counter),
                             **kwargs)
            else:
                kwargs.update(info)
                state = func(name='%sbegin_state_%d' % (self._prefix,
                                                        self._init_counter),
                             **kwargs)
            states.append(state)
        return states

    def unpack_weights(self, args):
        """Reference rnn_cell.py:247 — fused vector → per-gate matrices."""
        args = args.copy()
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ['i2h', 'h2h']:
            weight = args.pop('%s%s_weight' % (self._prefix, group_name))
            bias = args.pop('%s%s_bias' % (self._prefix, group_name))
            for j, gate in enumerate(self._gate_names):
                wname = '%s%s%s_weight' % (self._prefix, group_name, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = '%s%s%s_bias' % (self._prefix, group_name, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        args = args.copy()
        if not self._gate_names:
            return args
        from .. import ndarray as nd
        for group_name in ['i2h', 'h2h']:
            weight = []
            bias = []
            for gate in self._gate_names:
                wname = '%s%s%s_weight' % (self._prefix, group_name, gate)
                weight.append(args.pop(wname))
                bname = '%s%s%s_bias' % (self._prefix, group_name, gate)
                bias.append(args.pop(bname))
            args['%s%s_weight' % (self._prefix, group_name)] = \
                nd.concatenate(weight)
            args['%s%s_bias' % (self._prefix, group_name)] = \
                nd.concatenate(bias)
        return args

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        """Reference rnn_cell.py:310."""
        self.reset()
        inputs, _ = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = _batch_states(self, inputs[0], batch_axis=0)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _ = _normalize_sequence(length, outputs, layout,
                                         merge_outputs)
        return outputs, states


def _batch_states(cell, ref_sym, batch_axis=0):
    """begin_state with batch taken from ``ref_sym`` via _state_zeros, so
    forward shape inference resolves the reference's 0-batch convention."""
    def func(name, shape=(), dtype='float32', **kwargs):
        return symbol._state_zeros(ref_sym, name=name, shape=tuple(shape),
                                   dtype=dtype, batch_axis=batch_axis)
    return cell.begin_state(func=func)


def _unroll_ref_input(length, inputs, layout):
    """A (symbol, batch_axis) pair naming where the batch dim lives."""
    if isinstance(inputs, Symbol):
        return inputs, layout.find('N')
    return inputs[0], 0


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    assert inputs is not None
    axis = layout.find('T')
    in_axis = in_layout.find('T') if in_layout is not None else axis
    if isinstance(inputs, Symbol):
        if merge is False:
            assert len(inputs.list_outputs()) == 1, \
                'unroll doesn\'t allow grouped symbol as input. Please convert ' \
                'to list with list(inputs) first or let unroll handle splitting.'
            inputs = list(symbol.SliceChannel(inputs, axis=in_axis,
                                              num_outputs=length,
                                              squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if merge is True:
            inputs = [symbol.expand_dims(i, axis=axis) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=axis)
            in_axis = axis
    if isinstance(inputs, Symbol) and axis != in_axis:
        inputs = symbol.swapaxes(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis


class RNNCell(BaseRNNCell):
    """Simple tanh/relu recurrent cell (reference rnn_cell.py:409)."""

    def __init__(self, num_hidden, activation='tanh', prefix='rnn_',
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get('i2h_weight')
        self._iB = self.params.get('i2h_bias')
        self._hW = self.params.get('h2h_weight')
        self._hB = self.params.get('h2h_bias')

    @property
    def state_info(self):
        return [{'shape': (0, self._num_hidden), '__layout__': 'NC'}]

    @property
    def _gate_names(self):
        return ('',)

    def __call__(self, inputs, states):
        self._counter += 1
        name = '%st%d_' % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden,
                                    name='%si2h' % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden,
                                    name='%sh2h' % name)
        output = symbol.Activation(i2h + h2h, act_type=self._activation,
                                   name='%sout' % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """Reference rnn_cell.py:459. Gate order i,f,c,o (cuDNN convention)."""

    def __init__(self, num_hidden, prefix='lstm_', params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get('i2h_weight')
        self._hW = self.params.get('h2h_weight')
        from ..initializer import Constant
        self._iB = self.params.get('i2h_bias')
        self._hB = self.params.get('h2h_bias')
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{'shape': (0, self._num_hidden), '__layout__': 'NC'},
                {'shape': (0, self._num_hidden), '__layout__': 'NC'}]

    @property
    def _gate_names(self):
        return ['_i', '_f', '_c', '_o']

    def __call__(self, inputs, states):
        self._counter += 1
        name = '%st%d_' % (self._prefix, self._counter)
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 4,
                                    name='%si2h' % name)
        h2h = symbol.FullyConnected(data=states[0], weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 4,
                                    name='%sh2h' % name)
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(gates, num_outputs=4,
                                          name='%sslice' % name)
        in_gate = symbol.Activation(slice_gates[0], act_type='sigmoid',
                                    name='%si' % name)
        forget_gate = symbol.Activation(slice_gates[1], act_type='sigmoid',
                                        name='%sf' % name)
        in_transform = symbol.Activation(slice_gates[2], act_type='tanh',
                                         name='%sc' % name)
        out_gate = symbol.Activation(slice_gates[3], act_type='sigmoid',
                                     name='%so' % name)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type='tanh')
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """Reference rnn_cell.py:578. Gate order r,z,n (cuDNN convention)."""

    def __init__(self, num_hidden, prefix='gru_', params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get('i2h_weight')
        self._iB = self.params.get('i2h_bias')
        self._hW = self.params.get('h2h_weight')
        self._hB = self.params.get('h2h_bias')

    @property
    def state_info(self):
        return [{'shape': (0, self._num_hidden), '__layout__': 'NC'}]

    @property
    def _gate_names(self):
        return ['_r', '_z', '_o']

    def __call__(self, inputs, states):
        self._counter += 1
        name = '%st%d_' % (self._prefix, self._counter)
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(data=inputs, weight=self._iW,
                                    bias=self._iB,
                                    num_hidden=self._num_hidden * 3,
                                    name='%si2h' % name)
        h2h = symbol.FullyConnected(data=prev_state_h, weight=self._hW,
                                    bias=self._hB,
                                    num_hidden=self._num_hidden * 3,
                                    name='%sh2h' % name)
        i2h_r, i2h_z, i2h = symbol.SliceChannel(i2h, num_outputs=3,
                                                name='%si2h_slice' % name)
        h2h_r, h2h_z, h2h = symbol.SliceChannel(h2h, num_outputs=3,
                                                name='%sh2h_slice' % name)
        reset_gate = symbol.Activation(i2h_r + h2h_r, act_type='sigmoid',
                                       name='%sr_act' % name)
        update_gate = symbol.Activation(i2h_z + h2h_z, act_type='sigmoid',
                                        name='%sz_act' % name)
        next_h_tmp = symbol.Activation(i2h + reset_gate * h2h,
                                       act_type='tanh', name='%sh_act' % name)
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Maps to the fused RNN op (reference rnn_cell.py:536)."""

    def __init__(self, num_hidden, num_layers=1, mode='lstm',
                 bidirectional=False, dropout=0., get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = '%s_' % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        self._directions = 2 if bidirectional else 1
        # the flat parameter vector carries its own initializer as the
        # variable's __init__ attr (reference rnn_cell.py:578-580): a
        # global Xavier cannot init a 1-D vector, and the gate/bias
        # layout needs init.FusedRNN's unpack-init-repack
        from .. import initializer as _init
        self._parameter = self.params.get(
            'parameters', init=_init.FusedRNN(
                None, num_hidden, num_layers, mode,
                bidirectional=bidirectional, forget_bias=forget_bias))

    @property
    def state_info(self):
        b = self._directions
        n = (self._mode == 'lstm') + 1
        return [{'shape': (b * self._num_layers, 0, self._num_hidden),
                 '__layout__': 'LNC'} for _ in range(n)]

    @property
    def _gate_names(self):
        return {'rnn_relu': [''], 'rnn_tanh': [''],
                'lstm': ['_i', '_f', '_c', '_o'],
                'gru': ['_r', '_z', '_o']}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def __call__(self, inputs, states):
        raise NotImplementedError('FusedRNNCell cannot be stepped. Please use unroll')

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, True)
        if axis == 1:
            inputs = symbol.swapaxes(inputs, dim1=0, dim2=1)
        if begin_state is None:
            begin_state = _batch_states(self, inputs, batch_axis=1)
        states = begin_state

        if self._mode == 'lstm':
            states = {'state': states[0], 'state_cell': states[1]}
        else:
            states = {'state': states[0]}

        rnn = symbol.RNN(data=inputs, parameters=self._parameter,
                         state_size=self._num_hidden,
                         num_layers=self._num_layers,
                         bidirectional=self._bidirectional, p=self._dropout,
                         state_outputs=self._get_next_state, mode=self._mode,
                         name=self._prefix + 'rnn', **states)

        attr = {}
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == 'lstm':
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if axis == 1:
            outputs = symbol.swapaxes(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            outputs = list(symbol.SliceChannel(outputs, axis=axis,
                                               num_outputs=length,
                                               squeeze_axis=1))
        return outputs, states

    def unfuse(self):
        """Reference rnn_cell.py:706 — explicit-cell equivalent stack."""
        stack = SequentialRNNCell()
        get_cell = {'rnn_relu': lambda cell_prefix: RNNCell(self._num_hidden,
                                                            activation='relu',
                                                            prefix=cell_prefix),
                    'rnn_tanh': lambda cell_prefix: RNNCell(self._num_hidden,
                                                            activation='tanh',
                                                            prefix=cell_prefix),
                    'lstm': lambda cell_prefix: LSTMCell(self._num_hidden,
                                                         prefix=cell_prefix),
                    'gru': lambda cell_prefix: GRUCell(self._num_hidden,
                                                       prefix=cell_prefix)}[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell('%sl%d_' % (self._prefix, i)),
                    get_cell('%sr%d_' % (self._prefix, i)),
                    output_prefix='%sbi_l%d_' % (self._prefix, i)))
            else:
                stack.add(get_cell('%sl%d_' % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix='%s_dropout%d_' % (self._prefix, i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Reference rnn_cell.py:760."""

    def __init__(self, params=None):
        super().__init__(prefix='', params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, \
                'Either specify params for SequentialRNNCell or child cells, not both.'
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            ref, b_axis = _unroll_ref_input(length, inputs, layout)
            begin_state = _batch_states(self, ref, batch_axis=b_axis)
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """Reference rnn_cell.py:844."""

    def __init__(self, dropout, prefix='dropout_', params=None):
        super().__init__(prefix, params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Reference rnn_cell.py:878."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=symbol.zeros, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError()


class ZoneoutCell(ModifierCell):
    """Reference rnn_cell.py:929."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, FusedRNNCell), \
            'FusedRNNCell doesn\'t support zoneout. Please unfuse first.'
        assert not isinstance(base_cell, BidirectionalCell), \
            'BidirectionalCell doesn\'t support zoneout since it doesn\'t support step. ' \
            'Please add ZoneoutCell to the cells underneath instead.'
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = self.base_cell, self.zoneout_outputs, \
            self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(like * 0 + 1, p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else next_output * 0
        output = symbol.where(mask(p_outputs, next_output), next_output,
                              prev_output) if p_outputs != 0. else next_output
        states = [symbol.where(mask(p_states, new_s), new_s, old_s)
                  for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Reference rnn_cell.py:997."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, Symbol) if merge_outputs is None \
            else merge_outputs
        inputs, _ = _normalize_sequence(length, inputs, layout, merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [i + j for i, j in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Reference rnn_cell.py:1034."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix='bi_'):
        super().__init__('', params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params, \
                'Either specify params for BidirectionalCell or child cells, not both.'
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return _cells_unpack_weights(self._cells, args)

    def pack_weights(self, args):
        return _cells_pack_weights(self._cells, args)

    def __call__(self, inputs, states):
        raise NotImplementedError('Bidirectional cannot be stepped. Please use unroll')

    @property
    def state_info(self):
        return _cells_state_info(self._cells)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout='NTC',
               merge_outputs=None):
        self.reset()
        inputs, axis = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            ref, b_axis = _unroll_ref_input(length, inputs, layout)
            begin_state = _batch_states(self, ref, batch_axis=b_axis)
        states = begin_state
        l_cell, r_cell = self._cells
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info)], layout=layout,
            merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info):], layout=layout,
            merge_outputs=merge_outputs)
        if merge_outputs is None:
            merge_outputs = isinstance(l_outputs, Symbol) and \
                isinstance(r_outputs, Symbol)
            if not merge_outputs:
                if isinstance(l_outputs, Symbol):
                    l_outputs = list(symbol.SliceChannel(
                        l_outputs, axis=axis, num_outputs=length,
                        squeeze_axis=1))
                if isinstance(r_outputs, Symbol):
                    r_outputs = list(symbol.SliceChannel(
                        r_outputs, axis=axis, num_outputs=length,
                        squeeze_axis=1))
        if merge_outputs:
            r_outputs = symbol.reverse(r_outputs, axis=axis)
            outputs = symbol.Concat(l_outputs, r_outputs, dim=2,
                                    name='%sout' % self._output_prefix)
        else:
            outputs = [symbol.Concat(l_o, r_o, dim=1,
                                     name='%st%d' % (self._output_prefix, i))
                       for i, (l_o, r_o) in
                       enumerate(zip(l_outputs, reversed(r_outputs)))]
        states = l_states + r_states
        return outputs, states


def _cells_state_info(cells):
    return sum([c.state_info for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _cells_unpack_weights(cells, args):
    for cell in cells:
        args = cell.unpack_weights(args)
    return args


def _cells_pack_weights(cells, args):
    for cell in cells:
        args = cell.pack_weights(args)
    return args


class BaseConvRNNCell(BaseRNNCell):
    """Convolutional RNN cell base (reference rnn_cell.py BaseConvRNNCell):
    i2h and h2h are Convolutions over NCHW feature maps instead of
    FullyConnected over vectors; states are feature maps."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1), activation='tanh',
                 prefix='', params=None, conv_layout='NCHW'):
        super().__init__(prefix=prefix, params=params)
        assert h2h_kernel[0] % 2 == 1 and h2h_kernel[1] % 2 == 1, \
            'h2h_kernel must be odd, got %s' % str(h2h_kernel)
        self._h2h_kernel = h2h_kernel
        self._h2h_pad = (h2h_dilate[0] * (h2h_kernel[0] - 1) // 2,
                         h2h_dilate[1] * (h2h_kernel[1] - 1) // 2)
        self._h2h_dilate = h2h_dilate
        self._i2h_kernel = i2h_kernel
        self._i2h_stride = i2h_stride
        self._i2h_pad = i2h_pad
        self._i2h_dilate = i2h_dilate
        self._num_hidden = num_hidden
        self._input_shape = input_shape
        self._activation = activation

        # state shape = the i2h conv's output shape at batch 0
        probe = symbol.Convolution(
            symbol.Variable('data'), num_filter=num_hidden,
            kernel=i2h_kernel, stride=i2h_stride, pad=i2h_pad,
            dilate=i2h_dilate)
        _, out_shapes, _ = probe.infer_shape(data=input_shape)
        self._state_shape = (0,) + tuple(out_shapes[0][1:])

        self._iW = self.params.get('i2h_weight')
        self._hW = self.params.get('h2h_weight')
        self._iB = self.params.get('i2h_bias')
        self._hB = self.params.get('h2h_bias')

    @property
    def _num_gates(self):
        return len(self._gate_names)

    @property
    def state_info(self):
        return [{'shape': self._state_shape, '__layout__': 'NCHW'}]

    def _conv_forward(self, inputs, states, name):
        i2h = symbol.Convolution(
            inputs, weight=self._iW, bias=self._iB,
            num_filter=self._num_hidden * self._num_gates,
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate,
            name='%si2h' % name)
        h2h = symbol.Convolution(
            states[0], weight=self._hW, bias=self._hB,
            num_filter=self._num_hidden * self._num_gates,
            kernel=self._h2h_kernel, pad=self._h2h_pad,
            dilate=self._h2h_dilate, name='%sh2h' % name)
        return i2h, h2h


class ConvRNNCell(BaseConvRNNCell):
    """Plain convolutional RNN (reference rnn_cell.py ConvRNNCell)."""

    def __init__(self, input_shape, num_hidden, prefix='ConvRNN_', **kw):
        super().__init__(input_shape, num_hidden, prefix=prefix, **kw)

    @property
    def _gate_names(self):
        return ('',)

    def __call__(self, inputs, states):
        self._counter += 1
        name = '%st%d_' % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        out = symbol.Activation(i2h + h2h, act_type=self._activation,
                                name='%sout' % name)
        return out, [out]


class ConvLSTMCell(BaseConvRNNCell):
    """Convolutional LSTM (reference rnn_cell.py ConvLSTMCell,
    Shi et al. 2015): LSTM gating over feature maps."""

    def __init__(self, input_shape, num_hidden, prefix='ConvLSTM_',
                 forget_bias=1.0, **kw):
        super().__init__(input_shape, num_hidden, prefix=prefix, **kw)
        self._forget_bias = forget_bias

    @property
    def _gate_names(self):
        return ('_i', '_f', '_c', '_o')

    @property
    def state_info(self):
        return [{'shape': self._state_shape, '__layout__': 'NCHW'},
                {'shape': self._state_shape, '__layout__': 'NCHW'}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = '%st%d_' % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        gates = i2h + h2h
        sliced = symbol.SliceChannel(gates, num_outputs=4,
                                     name='%sslice' % name)
        in_gate = symbol.Activation(sliced[0], act_type='sigmoid')
        forget_gate = symbol.Activation(sliced[1] + self._forget_bias,
                                        act_type='sigmoid')
        in_transform = symbol.Activation(sliced[2],
                                         act_type=self._activation)
        out_gate = symbol.Activation(sliced[3], act_type='sigmoid')
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c,
                                              act_type=self._activation)
        return next_h, [next_h, next_c]


class ConvGRUCell(BaseConvRNNCell):
    """Convolutional GRU (reference rnn_cell.py ConvGRUCell)."""

    def __init__(self, input_shape, num_hidden, prefix='ConvGRU_', **kw):
        super().__init__(input_shape, num_hidden, prefix=prefix, **kw)

    @property
    def _gate_names(self):
        return ('_r', '_z', '_o')

    def __call__(self, inputs, states):
        self._counter += 1
        name = '%st%d_' % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        i2h_s = symbol.SliceChannel(i2h, num_outputs=3,
                                    name='%si2h_slice' % name)
        h2h_s = symbol.SliceChannel(h2h, num_outputs=3,
                                    name='%sh2h_slice' % name)
        reset = symbol.Activation(i2h_s[0] + h2h_s[0], act_type='sigmoid',
                                  name='%sr' % name)
        update = symbol.Activation(i2h_s[1] + h2h_s[1], act_type='sigmoid',
                                   name='%sz' % name)
        cand = symbol.Activation(i2h_s[2] + reset * h2h_s[2],
                                 act_type=self._activation,
                                 name='%sh' % name)
        next_h = (1.0 - update) * cand + update * states[0]
        return next_h, [next_h]
