"""RNN checkpoint helpers (reference python/mxnet/rnn/rnn.py)."""
from .. import model
from .rnn_cell import BaseRNNCell

__all__ = ['rnn_unroll', 'save_rnn_checkpoint', 'load_rnn_checkpoint',
           'do_rnn_checkpoint']


def _in_cells(cells):
    if isinstance(cells, BaseRNNCell):
        cells = [cells]
    return cells


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Reference rnn/rnn.py:28 — unpacks fused weights before saving."""
    cells = _in_cells(cells)
    for cell in cells:
        arg_params = cell.unpack_weights(arg_params)
    model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Reference rnn/rnn.py:60."""
    sym, arg, aux = model.load_checkpoint(prefix, epoch)
    cells = _in_cells(cells)
    for cell in cells:
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Reference rnn/rnn.py:92."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback


def rnn_unroll(cell, length, inputs=None, begin_state=None,
               input_prefix='', layout='NTC'):
    """Deprecated (reference rnn/rnn.py:26): use cell.unroll directly.
    With ``inputs=None`` the legacy form creates one
    ``<input_prefix>t%d_data`` Variable per step, as the reference's
    unroll did."""
    import warnings

    from .. import symbol
    warnings.warn('rnn_unroll is deprecated. '
                  'Please call cell.unroll directly.')
    if inputs is None:
        inputs = [symbol.Variable('%st%d_data' % (input_prefix, i))
                  for i in range(length)]
    outputs, states = cell.unroll(length=length, inputs=inputs,
                                  begin_state=begin_state, layout=layout)
    return outputs, states
