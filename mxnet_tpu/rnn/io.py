"""Bucketed sequence iterators.

Reference: python/mxnet/rnn/io.py — encode_sentences + BucketSentenceIter
(assigns sentences to length buckets; feeds BucketingModule).
"""
import bisect

import numpy as np

from ..io import DataIter, DataBatch, DataDesc
from .. import random as _random
from ..ndarray import array

# framework-private stdlib-style stream: mx.random.seed controls it,
# user-global `random` state is untouched
random = _random.host_pyrng()

__all__ = ['encode_sentences', 'BucketSentenceIter']


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key='\n', start_label=0):
    """Map token sequences to integer-id sequences, growing ``vocab``
    (only when it was not supplied) as new tokens appear.
    Reference rnn/io.py:29."""
    grow = vocab is None
    if grow:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    encoded = []
    for sentence in sentences:
        ids = []
        for token in sentence:
            if token not in vocab:
                if not grow:
                    raise AssertionError('Unknown token %s' % token)
                if next_id == invalid_label:
                    next_id += 1   # never hand out the padding id
                vocab[token] = next_id
                next_id += 1
            ids.append(vocab[token])
        encoded.append(ids)
    return encoded, vocab


def _default_buckets(sentences, batch_size):
    """One bucket per sentence length that can fill a batch."""
    counts = np.bincount([len(s) for s in sentences])
    return [length for length, n in enumerate(counts) if n >= batch_size]


class BucketSentenceIter(DataIter):
    """Pads each sentence to the smallest bucket that fits it; batches
    are drawn bucket-by-bucket so every batch has one static shape
    (``bucket_key``). Labels are the data shifted left by one with
    ``invalid_label`` at the end. Reference rnn/io.py:70."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name='data', label_name='softmax_label', dtype='float32',
                 layout='NT'):
        super().__init__()
        self.batch_size = batch_size
        self.buckets = sorted(buckets or
                              _default_buckets(sentences, batch_size))
        self.data_name, self.label_name = data_name, label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        self.major_axis = layout.find('N')
        if self.major_axis not in (0, 1):
            raise ValueError('Invalid layout %s: Must by NT (batch major) or'
                             ' TN (time major)' % layout)
        self.default_bucket_key = max(self.buckets)

        # pad each sentence into the smallest bucket that holds it;
        # longer-than-every-bucket sentences are dropped
        per_bucket = [[] for _ in self.buckets]
        for sentence in sentences:
            which = bisect.bisect_left(self.buckets, len(sentence))
            if which == len(self.buckets):
                continue
            row = np.full((self.buckets[which],), invalid_label, dtype=dtype)
            row[:len(sentence)] = sentence
            per_bucket[which].append(row)
        # an empty bucket's asarray is 1-D (0,); give it the (0, length)
        # shape so reset()'s label[:, :-1] slicing stays valid (the
        # reference never hits this — PTB fills every default bucket)
        self.data = [np.asarray(rows, dtype=dtype) if rows else
                     np.empty((0, length), dtype=dtype)
                     for rows, length in zip(per_bucket, self.buckets)]

        batch_shape = self._oriented((batch_size, self.default_bucket_key))
        self.provide_data = [DataDesc(name=data_name, shape=batch_shape,
                                      layout=layout)]
        self.provide_label = [DataDesc(name=label_name, shape=batch_shape,
                                       layout=layout)]

        # (bucket, row-offset) of every full batch
        self.idx = [(b, start)
                    for b, rows in enumerate(self.data)
                    for start in range(0, len(rows) - batch_size + 1,
                                       batch_size)]
        self.nddata = []
        self.ndlabel = []
        self.curr_idx = 0
        self.reset()

    def _oriented(self, nt_shape):
        """(N, T) -> layout order."""
        return nt_shape if self.major_axis == 0 else nt_shape[::-1]

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for rows in self.data:
            _random.host_rng().shuffle(rows)
        self.nddata = list(self.data)
        self.ndlabel = []
        for rows in self.data:
            shifted = np.empty_like(rows)
            shifted[:, :-1] = rows[:, 1:]
            shifted[:, -1] = self.invalid_label
            self.ndlabel.append(shifted)

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        bucket, start = self.idx[self.curr_idx]
        self.curr_idx += 1

        rows = slice(start, start + self.batch_size)
        data_np = self.nddata[bucket][rows]
        label_np = self.ndlabel[bucket][rows]
        if self.major_axis == 1:   # time-major
            data_np, label_np = data_np.T, label_np.T
        data, label = array(data_np), array(label_np)
        return DataBatch([data], [label], pad=0,
                         bucket_key=self.buckets[bucket],
                         provide_data=[DataDesc(name=self.data_name,
                                                shape=data.shape,
                                                layout=self.layout)],
                         provide_label=[DataDesc(name=self.label_name,
                                                 shape=label.shape,
                                                 layout=self.layout)])
