"""Imperative autograd — tape-based, VJP-chained.

Reference: src/ndarray/autograd.{h,cc} (AutogradRuntime: thread-local
train/record flags autograd.cc:45-48, MarkVariables:79, RecordOp:160,
ComputeGradient:244) and python/mxnet/autograd.py (record/pause scopes,
backward, grad_and_loss, Function).

TPU-native design: the reference records an NNVM tape and replays it through
a freshly-built GraphExecutor. Here each recorded op is executed via
``jax.vjp`` — the vjp closure (an XLA-compiled pullback) IS the tape entry,
so backward is a pure reverse walk accumulating cotangents; no graph executor
needs to be constructed.
"""
import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ['record', 'pause', 'train_mode', 'predict_mode', 'is_recording',
           'is_training', 'mark_variables', 'backward', 'grad_and_loss',
           'grad', 'Function', 'get_symbol', 'set_recording',
           'set_training']

_state = threading.local()


def _st():
    if not hasattr(_state, 'recording'):
        _state.recording = False
        _state.training = False
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_recording(flag):
    prev = _st().recording
    _state.recording = flag
    return prev


def set_training(flag):
    prev = _st().training
    _state.training = flag
    return prev


class _RecordingScope:
    def __init__(self, recording, training):
        self._recording = recording
        self._training = training

    def __enter__(self):
        st = _st()
        self._prev = (st.recording, st.training)
        if self._recording is not None:
            st.recording = self._recording
        if self._training is not None:
            st.training = self._training
        return self

    def __exit__(self, *args):
        _state.recording, _state.training = self._prev


def record(train_mode=True):
    """``with autograd.record():`` — reference python/mxnet/autograd.py:87."""
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op: holds the vjp closure + links to parent nodes.

    Parallels AGNodeEntry/AGNode in src/ndarray/autograd.h:40-70.
    """
    __slots__ = ('vjp_fn', 'parents', 'n_outputs', 'out_grads', 'n_grad_inputs',
                 'head_ids', 'op_info')

    def __init__(self, vjp_fn, parents, n_outputs, n_grad_inputs,
                 op_info=None):
        self.vjp_fn = vjp_fn
        self.parents = parents          # list[TapeNode|None] aligned with grad inputs
        self.n_outputs = n_outputs
        self.n_grad_inputs = n_grad_inputs
        self.out_grads = None           # list of cotangents, filled during backward
        # (op_name, attrs) — lets MXAutogradGetSymbol export the recorded
        # history as a Symbol (reference nnvm graph behind the tape)
        self.op_info = op_info


class LeafNode:
    """A marked variable (MarkVariables, autograd.cc:79)."""
    __slots__ = ('array_ref', 'grad_req')

    def __init__(self, array_ref, grad_req='write'):
        self.array_ref = array_ref  # the NDArray whose .grad we accumulate into
        self.grad_req = grad_req


def record_op(vjp_fn, parent_entries, n_outputs, n_grad_inputs,
              op_info=None):
    return TapeNode(vjp_fn, parent_entries, n_outputs, n_grad_inputs,
                    op_info=op_info)


def mark_variables(variables, gradients, grad_reqs='write'):
    """Attach gradient buffers to variables (reference autograd.py:36)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._grad = grad
        var._leaf = LeafNode(var, req)


def _toposort(heads):
    """Reverse-topological order over TapeNodes reachable from heads."""
    order = []
    visited = set()
    stack = [(n, False) for n in heads if isinstance(n, TapeNode)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for p, _ in node.parents:
            if isinstance(p, TapeNode) and id(p) not in visited:
                stack.append((p, False))
    order.reverse()
    return order


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Run the tape backward from head NDArrays.

    Reference: MXAutogradBackwardEx (c_api_ndarray.cc:799) →
    AutogradRuntime::ComputeGradient (autograd.cc:244). There the tape is
    compiled into a GraphExecutor; here we chain the stored vjp closures.
    """
    from .ndarray.ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    # Seed cotangents on the head nodes.
    for h, hg in zip(heads, head_grads):
        node = getattr(h, '_node', None)
        if node is None:
            leaf = getattr(h, '_leaf', None)
            if leaf is not None and h._grad is not None:
                g = hg._data if hg is not None else jnp.ones_like(h._data)
                _accumulate_leaf(leaf, g)
                continue
            # reference MXAutogradBackwardEx errors on heads outside any
            # recorded graph instead of silently producing no gradients
            raise ValueError(
                'cannot run backward: the array is not part of a recorded '
                'computation graph (compute it inside autograd.record())')
        if node.out_grads is None:
            node.out_grads = [None] * node.n_outputs
        g = hg._data if hg is not None else jnp.ones_like(h._data)
        idx = h._out_idx
        node.out_grads[idx] = g if node.out_grads[idx] is None else node.out_grads[idx] + g

    head_nodes = [h._node for h in heads if getattr(h, '_node', None) is not None]
    order = _toposort(head_nodes)  # heads-first (reverse-topological)

    for node in order:
        if node.out_grads is None:
            continue
        cotangents = tuple(
            g if g is not None else jnp.zeros(shape, dtype)
            for g, (shape, dtype) in zip(node.out_grads, node.head_ids))
        if node.n_outputs == 1:
            in_grads = node.vjp_fn(cotangents[0])
        else:
            in_grads = node.vjp_fn(cotangents)
        for (parent, out_idx), g in zip(node.parents, in_grads):
            if parent is None or g is None:
                continue
            if isinstance(g, jax.Array) and g.dtype == jax.dtypes.float0:
                continue
            if isinstance(parent, LeafNode):
                _accumulate_leaf(parent, g)
            else:
                if parent.out_grads is None:
                    parent.out_grads = [None] * parent.n_outputs
                og = parent.out_grads[out_idx]
                parent.out_grads[out_idx] = g if og is None else og + g
        if not retain_graph:
            node.out_grads = None
            node.vjp_fn = None

    # Drop tape references from the heads so memory is freed.
    if not retain_graph:
        for h in heads:
            if getattr(h, '_node', None) is not None:
                h._node = None


def _accumulate_leaf(leaf, g):
    var = leaf.array_ref
    if var._grad is None:
        return
    g = g.astype(var._grad._data.dtype)
    if leaf.grad_req == 'add':
        var._grad._data = var._grad._data + g
    elif leaf.grad_req != 'null':
        if getattr(var, '_fresh_grad', True):
            var._grad._data = jnp.broadcast_to(g, var._grad.shape) if g.shape != var._grad.shape else g
            var._fresh_grad = False
        else:
            var._grad._data = var._grad._data + g


def reset_fresh_grads(variables):
    for v in variables:
        v._fresh_grad = True


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient and loss (reference autograd.py:257)."""
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else argnum
            variables = [args[i] for i in argnums]
        for v in variables:
            v.attach_grad()
        with record():
            outputs = func(*args)
        backward([outputs] if not isinstance(outputs, (list, tuple)) else list(outputs))
        grads = [v.grad for v in variables]
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    def wrapped(*args):
        return grad_and_loss(func, argnum)(*args)[0]
    return wrapped


def get_symbol(x):
    """Export the recorded computation history of ``x`` as a Symbol
    (reference autograd.py:273 / MXAutogradGetSymbol)."""
    from ._c_api_impl import autograd_get_symbol
    return autograd_get_symbol(x)


class Function:
    """User-defined differentiable function (reference autograd.py:292):
    define ``forward`` and ``backward``; during gradient computation the
    custom backward replaces the chain rule. Example::

        class sigmoid(Function):
            def forward(self, x):
                y = 1 / (1 + mx.nd.exp(-x))
                self.save_for_backward(y)
                return y

            def backward(self, dy):
                y, = self.saved_tensors
                return dy * y * (1 - y)
    """

    def __init__(self):
        self._used = False
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        self.saved_tensors = args

    def forward(self, *inputs):
        raise NotImplementedError()

    def backward(self, *output_grads):
        raise NotImplementedError()

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _parent_entry
        assert not self._used, \
            'Each Function instance can only be called once. ' \
            'Please create another instance.'
        self._used = True

        prev = is_recording()
        if prev:
            set_recording(False)
        try:
            outputs = self.forward(*inputs)
        finally:
            if prev:
                set_recording(True)
        if not prev:
            return outputs

        single = isinstance(outputs, NDArray)
        outs = (outputs,) if single else tuple(outputs)

        def vjp_fn(cots):
            cots_t = (cots,) if len(outs) == 1 else tuple(cots)
            rets = self.backward(*[NDArray(c, None) for c in cots_t])
            if isinstance(rets, NDArray):
                rets = (rets,)
            assert len(rets) == len(inputs), (
                '%s.backward must return exactly as many NDArrays as '
                'forward takes arguments (expected %d, got %d)'
                % (type(self).__name__, len(inputs), len(rets)))
            return tuple(r._data for r in rets)

        node = record_op(vjp_fn, [_parent_entry(i) for i in inputs],
                         len(outs), len(inputs),
                         op_info=('_CustomFunction', {}))
        node.head_ids = [(tuple(o.shape), o._data.dtype) for o in outs]
        for i, o in enumerate(outs):
            o._node = node
            o._out_idx = i
        return outputs
