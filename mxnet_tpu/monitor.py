"""Monitor — per-op output statistics during execution.

Reference: python/mxnet/monitor.py:143 (regex-selected per-op stats via
the executor monitor callback; tic arms a window every ``interval``
steps, toc drains it plus the matching weight arrays).
"""
import logging
import re
from collections import namedtuple
from math import sqrt

__all__ = ['Monitor']

_Record = namedtuple('_Record', ['step', 'name', 'stat'])


def _rms_stat(x):
    """Default statistic: RMS of the tensor, as a string. A zero-size
    array (empty bucket slice, degenerate shape) has no RMS — report
    'nan' instead of raising ZeroDivisionError mid-fit."""
    if x.size == 0:
        return 'nan'
    return str((x.norm() / sqrt(x.size)).asscalar())


class Monitor:
    """Collects a statistic for every executor output whose name matches
    ``pattern``, on every ``interval``-th step between tic() and toc().

    install() hooks an Executor's monitor callback; Module.fit calls
    tic/toc_print around each batch when given a monitor.
    """

    def __init__(self, interval, stat_func=None, pattern='.*', sort=False):
        self.interval = interval
        self.stat_func = stat_func or _rms_stat
        self.sort = sort
        self.re_prog = re.compile(pattern)
        self.step = 0
        self.exes = []
        self.activated = False
        self.queue = []

        monitor = self

        def stat_helper(name, array):
            # invoked by the executor for every op output while armed
            if monitor.activated and monitor.re_prog.match(name):
                monitor.queue.append(
                    _Record(monitor.step, name, monitor.stat_func(array)))
        self.stat_helper = stat_helper

    def install(self, exe):
        """Register with an executor; may be called for many executors."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def _barrier(self):
        for exe in self.exes:
            for array in exe.arg_arrays:
                array.wait_to_read()

    def tic(self):
        """Open a collection window if this step is on the interval."""
        if self.step % self.interval == 0:
            self._barrier()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Close the window: also sample matching weight arrays, then
        return [(step, name, tab-joined stat string), ...]."""
        if not self.activated:
            return []
        self._barrier()
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_prog.match(name):
                    self.queue.append(
                        _Record(self.step, name, self.stat_func(array)))
        self.activated = False
        pending = sorted(self.queue, key=lambda r: r.name) if self.sort \
            else self.queue
        results = [(r.step, r.name, self._render(r.stat)) for r in pending]
        self.queue = []
        return results

    @staticmethod
    def _render(stat):
        values = stat if isinstance(stat, list) else [stat]
        return ''.join(str(v) + '\t' for v in values)

    def toc_print(self):
        """toc() and log each row."""
        for step, name, stat in self.toc():
            logging.info('Batch: {:7d} {:30s} {:s}'.format(step, name, stat))
