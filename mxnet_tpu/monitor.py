"""Monitor — per-op output statistics during execution.

Reference: python/mxnet/monitor.py:143 (regex-selected per-op stats via
the executor monitor callback; tic arms a window every ``interval``
steps, toc drains it plus the matching weight arrays).

``stat_func`` may be ONE callable or a LIST of callables. With a list,
every matched array is fetched from the device ONCE per callback —
``stat_helper`` pulls the value to the host and hands all stat funcs
the same host-resident NDArray, so N stat funcs cost one fetch instead
of N device syncs. A single stat func keeps the legacy device-resident
form (the default RMS is a device reduction + scalar fetch — cheaper
than shipping a large tensor to the host for one scalar).
"""
import logging
import re
from collections import namedtuple
from math import sqrt

__all__ = ['Monitor']

_Record = namedtuple('_Record', ['step', 'name', 'stat'])


def _rms_stat(x):
    """Default statistic: RMS of the tensor, as a string. A zero-size
    array (empty bucket slice, degenerate shape) has no RMS — report
    'nan' instead of raising ZeroDivisionError mid-fit."""
    if x.size == 0:
        return 'nan'
    return str((x.norm() / sqrt(x.size)).asscalar())


def _host_fetch(array):
    """One device->host fetch, rewrapped as a host-resident NDArray so
    stat funcs keep the NDArray API (norm/asscalar/asnumpy) without
    touching the accelerator again."""
    from .ndarray.ndarray import array as _nd_array
    try:
        return _nd_array(array.asnumpy())
    except Exception:  # noqa: BLE001 — exotic dtype: stat on the original
        return array


class Monitor:
    """Collects statistics for every executor output whose name matches
    ``pattern``, on every ``interval``-th step between tic() and toc().

    install() hooks an Executor's monitor callback; Module.fit calls
    tic/toc_print around each batch when given a monitor.
    """

    def __init__(self, interval, stat_func=None, pattern='.*', sort=False):
        self.interval = interval
        if stat_func is None:
            stat_funcs = [_rms_stat]
        elif callable(stat_func):
            stat_funcs = [stat_func]
        else:
            stat_funcs = list(stat_func)
        self.stat_func = stat_funcs[0]       # back-compat attribute
        self.stat_funcs = stat_funcs
        self.sort = sort
        self.re_prog = re.compile(pattern)
        self.step = 0
        self.exes = []
        self.activated = False
        self.queue = []

        monitor = self

        def stat_helper(name, array):
            # invoked by the executor for every op output while armed;
            # ONE host fetch per array, shared by every stat func
            if monitor.activated and monitor.re_prog.match(name):
                monitor._collect(name, array)
        self.stat_helper = stat_helper

    def _funcs(self):
        # reference-MXNet pattern: `mon.stat_func = my_fn` AFTER
        # construction must keep working — a mutated stat_func wins
        # over the list frozen at __init__
        if callable(self.stat_func) and self.stat_func \
                is not self.stat_funcs[0]:
            return [self.stat_func]
        return self.stat_funcs

    def _collect(self, name, array):
        funcs = self._funcs()
        # the shared host fetch only pays for itself when SEVERAL stat
        # funcs would otherwise each sync the device; a single func
        # (the default RMS: one device reduction + a scalar fetch)
        # keeps the device-side form — shipping a monitored 100MB
        # embedding to the host to compute one scalar would regress it
        host = _host_fetch(array) if len(funcs) > 1 else array
        for fn in funcs:
            self.queue.append(_Record(self.step, name, fn(host)))

    @classmethod
    def nan_watch(cls, interval=1, pattern='.*'):
        """Preset: flag NaN/Inf in every matched tensor — the staged-
        path (per-op, monitor-callback) twin of the in-graph finite
        sentinels, built on the same host finite check
        (telemetry.health.finite_report). Rows read 'ok' or
        'nan=<n> inf=<n> of <size>'; weights are checked at toc() too.

        Use when the compiled-path sentinels flagged an incident and
        you want per-op visibility without a full bisect, or on a
        module the fused paths cannot take."""
        from .telemetry.health import finite_report
        return cls(interval, stat_func=lambda x: finite_report(x.asnumpy()),
                   pattern=pattern)

    def install(self, exe):
        """Register with an executor; may be called for many executors."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def _barrier(self):
        for exe in self.exes:
            for array in exe.arg_arrays:
                array.wait_to_read()

    def tic(self):
        """Open a collection window if this step is on the interval."""
        if self.step % self.interval == 0:
            self._barrier()
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Close the window: also sample matching weight arrays (one
        fetch each, shared across stat funcs), then return
        [(step, name, tab-joined stat string), ...]."""
        if not self.activated:
            return []
        self._barrier()
        for exe in self.exes:
            for name, array in exe.arg_dict.items():
                if self.re_prog.match(name):
                    self._collect(name, array)
        self.activated = False
        pending = sorted(self.queue, key=lambda r: r.name) if self.sort \
            else self.queue
        results = [(r.step, r.name, self._render(r.stat)) for r in pending]
        self.queue = []
        return results

    @staticmethod
    def _render(stat):
        values = stat if isinstance(stat, list) else [stat]
        return ''.join(str(v) + '\t' for v in values)

    def toc_print(self):
        """toc() and log each row."""
        for step, name, stat in self.toc():
            logging.info('Batch: {:7d} {:30s} {:s}'.format(step, name, stat))
