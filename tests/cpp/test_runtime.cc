/*
 * Native runtime unit tests (reference tests/cpp: threaded_engine_test.cc
 * randomized dependency workloads, storage_test.cc alloc/free reuse) —
 * a standalone binary over the MXT C ABI, no gtest dependency.
 *
 * Build + run: make -C tests/cpp test   (or via tests/unittest/test_native.py)
 */
#include <atomic>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "../../src/mxtpu.h"

static int g_failures = 0;
#define CHECK_MSG(cond, msg)                                              \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, msg);  \
      ++g_failures;                                                       \
    }                                                                     \
  } while (0)
#define CHECK_OK(call) CHECK_MSG((call) == 0, MXTGetLastError())

/* -- engine: randomized workload vs serial oracle ---------------------- */
struct Cell {
  double value = 0.0;
};
struct Task {
  Cell *reads[4];
  int n_reads;
  Cell *write;
  double coeff;
};

static void apply_task(void *param) {
  Task *t = static_cast<Task *>(param);
  double acc = 0.0;
  for (int i = 0; i < t->n_reads; ++i) acc += t->reads[i]->value;
  t->write->value = acc * t->coeff + 1.0;
}

static void test_engine_randomized() {
  for (int workers : {0, 1, 4}) {
    EngineHandle eng;
    CHECK_OK(MXTEngineCreate(workers, &eng));
    const int kVars = 8, kOps = 400;
    std::vector<Cell> cells(kVars), oracle(kVars);
    std::vector<VarHandle> vars(kVars);
    for (auto &v : vars) CHECK_OK(MXTEngineNewVar(eng, &v));

    std::mt19937 rng(workers * 7919 + 13);
    std::vector<Task> tasks(kOps);
    std::vector<Task> otasks(kOps);
    for (int i = 0; i < kOps; ++i) {
      int n_reads = 1 + static_cast<int>(rng() % 3);
      int widx = static_cast<int>(rng() % kVars);
      Task &t = tasks[i];
      t.n_reads = 0;
      VarHandle rvars[4];
      for (int r = 0; r < n_reads; ++r) {
        int ridx = static_cast<int>(rng() % kVars);
        if (ridx == widx) continue;
        rvars[t.n_reads] = vars[ridx];
        t.reads[t.n_reads++] = &cells[ridx];
      }
      t.write = &cells[widx];
      t.coeff = 0.5 + 0.001 * static_cast<double>(i % 7);
      otasks[i] = t;
      for (int r = 0; r < t.n_reads; ++r)
        otasks[i].reads[r] = &oracle[t.reads[r] - &cells[0]];
      otasks[i].write = &oracle[widx];
      VarHandle wv = vars[widx];
      CHECK_OK(MXTEnginePushSync(eng, apply_task, &t, rvars, t.n_reads,
                                 &wv, 1, 0, "task"));
    }
    CHECK_OK(MXTEngineWaitForAll(eng));
    for (auto &t : otasks) apply_task(&t);  /* serial oracle */
    for (int i = 0; i < kVars; ++i)
      CHECK_MSG(cells[i].value == oracle[i].value,
                "engine result diverged from serial oracle");
    int64_t pending = -1;
    CHECK_OK(MXTEnginePendingOps(eng, &pending));
    CHECK_MSG(pending == 0, "pending ops after WaitForAll");
    for (auto &v : vars) CHECK_OK(MXTEngineDeleteVar(eng, v));
    CHECK_OK(MXTEngineFree(eng));
  }
  std::puts("engine_randomized OK");
}

/* crossing read/write sets pushed from two threads must not deadlock
 * (the grant-ordering hazard: op1 r:A w:B vs op2 r:B w:A) */
static void test_engine_crossing_sets() {
  EngineHandle eng;
  CHECK_OK(MXTEngineCreate(2, &eng));
  VarHandle a, b;
  CHECK_OK(MXTEngineNewVar(eng, &a));
  CHECK_OK(MXTEngineNewVar(eng, &b));
  static std::atomic<int> counter{0};
  auto bump = [](void *) { counter.fetch_add(1); };
  const int kRounds = 200;
  std::thread t1([&] {
    for (int i = 0; i < kRounds; ++i)
      MXTEnginePushSync(eng, bump, nullptr, &a, 1, &b, 1, 0, "x");
  });
  std::thread t2([&] {
    for (int i = 0; i < kRounds; ++i)
      MXTEnginePushSync(eng, bump, nullptr, &b, 1, &a, 1, 0, "y");
  });
  t1.join();
  t2.join();
  CHECK_OK(MXTEngineWaitForAll(eng));
  CHECK_MSG(counter.load() == 2 * kRounds, "lost ops under crossing sets");
  CHECK_OK(MXTEngineFree(eng));
  std::puts("engine_crossing_sets OK");
}

/* -- storage: pooled reuse --------------------------------------------- */
static void test_storage_pool() {
  CHECK_OK(MXTStorageReleaseAll());
  int64_t s0[4], s1[4];
  CHECK_OK(MXTStorageStats(s0));
  void *p = nullptr;
  CHECK_OK(MXTStorageAlloc(1 << 16, &p));
  CHECK_MSG(p != nullptr, "null alloc");
  std::memset(p, 0xAB, 1 << 16);
  CHECK_OK(MXTStorageFree(p));
  void *q = nullptr;
  CHECK_OK(MXTStorageAlloc(1 << 16, &q));  /* same bucket -> pool hit */
  CHECK_OK(MXTStorageStats(s1));
  CHECK_MSG(s1[3] > s0[3], "free+alloc of same bucket missed the pool");
  CHECK_OK(MXTStorageDirectFree(q));
  CHECK_OK(MXTStorageReleaseAll());
  std::puts("storage_pool OK");
}

/* -- recordio: roundtrip incl. magic-collision + multipart ------------- */
static void test_recordio() {
  const char *path = "/tmp/mxtpu_test_cc.rec";
  RecordIOHandle w;
  CHECK_OK(MXTRecordIOWriterCreate(path, &w));
  /* payload containing the magic bytes forces escaping */
  uint32_t magic = 0xced7230a;
  std::string rec1(reinterpret_cast<char *>(&magic), 4);
  rec1 += "hello";
  std::string rec2(1 << 20, 'z');          /* 1 MB */
  for (size_t i = 0; i < rec2.size(); i += 4096)
    rec2[i] = static_cast<char>(i & 0xff);
  std::string rec3 = "";                   /* empty record */
  CHECK_OK(MXTRecordIOWriterWrite(w, rec1.data(), rec1.size()));
  CHECK_OK(MXTRecordIOWriterWrite(w, rec2.data(), rec2.size()));
  CHECK_OK(MXTRecordIOWriterWrite(w, rec3.data(), rec3.size()));
  CHECK_OK(MXTRecordIOWriterFree(w));

  RecordIOHandle r;
  CHECK_OK(MXTRecordIOReaderCreate(path, &r));
  const char *buf;
  size_t len;
  CHECK_OK(MXTRecordIOReaderNext(r, &buf, &len));
  CHECK_MSG(len == rec1.size() && std::memcmp(buf, rec1.data(), len) == 0,
            "rec1 mismatch (magic escaping)");
  CHECK_OK(MXTRecordIOReaderNext(r, &buf, &len));
  CHECK_MSG(len == rec2.size() && std::memcmp(buf, rec2.data(), len) == 0,
            "rec2 mismatch (1MB)");
  CHECK_OK(MXTRecordIOReaderNext(r, &buf, &len));
  CHECK_MSG(len == 0, "rec3 should be empty");
  CHECK_OK(MXTRecordIOReaderNext(r, &buf, &len));
  CHECK_MSG(len == (size_t)-1, "expected end of stream");
  CHECK_OK(MXTRecordIOReaderFree(r));
  std::remove(path);
  std::puts("recordio OK");
}

/* -- profiler: explicit events -> chrome trace JSON -------------------- */
static void test_profiler() {
  const char *path = "/tmp/mxtpu_test_cc_trace.json";
  CHECK_OK(MXTProfilerSetState(1));
  int64_t t0 = MXTNowUS();
  CHECK_OK(MXTProfilerAddEvent("unit_event", "test", t0, t0 + 42));
  CHECK_OK(MXTProfilerSetState(0));
  CHECK_OK(MXTProfilerDump(path));
  FILE *f = std::fopen(path, "rb");
  CHECK_MSG(f != nullptr, "trace file missing");
  if (f) {
    std::string content;
    char chunk[4096];
    size_t n;
    while ((n = std::fread(chunk, 1, sizeof chunk, f)) > 0)
      content.append(chunk, n);
    std::fclose(f);
    CHECK_MSG(content.find("unit_event") != std::string::npos,
              "event name absent from trace");
    CHECK_MSG(content.find("traceEvents") != std::string::npos,
              "not chrome trace format");
  }
  std::remove(path);
  std::puts("profiler OK");
}

int main() {
  test_engine_randomized();
  test_engine_crossing_sets();
  test_storage_pool();
  test_recordio();
  test_profiler();
  if (g_failures) {
    std::fprintf(stderr, "%d FAILURES\n", g_failures);
    return 1;
  }
  std::puts("ALL CPP TESTS PASSED");
  return 0;
}
