"""Registry superset vs the reference + legacy op behavior.

The reference registers ops via MXNET_REGISTER_OP_PROPERTY /
NNVM_REGISTER_OP / .add_alias across src/operator (see
ops/legacy_ops.py for the per-family citations). The sweep here
re-derives the reference name list from those sources and fails on any
missing registration (modulo `_backward_*`, subsumed by jax.vjp).
"""
import os
import re

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ops import registry

REF_SRC = '/root/reference/src'


def _reference_op_names():
    names = set()
    reg = re.compile(r'(?:MXNET_REGISTER_OP_PROPERTY|NNVM_REGISTER_OP|'
                     r'MXNET_REGISTER_SIMPLE_OP)\(\s*"?([A-Za-z0-9_.]+)"?\s*[,)]')
    alias = re.compile(r'\.add_alias\(\s*"([A-Za-z0-9_.]+)"\s*\)')
    for root, _, files in os.walk(REF_SRC):
        for f in files:
            if not f.endswith(('.cc', '.cu', '.h')):
                continue
            try:
                s = open(os.path.join(root, f), errors='ignore').read()
            except OSError:
                continue
            for m in reg.finditer(s):
                names.add(m.group(1))
            for m in alias.finditer(s):
                names.add(m.group(1))
    names.discard('name')  # macro parameter, not a registration
    return names


@pytest.mark.skipif(not os.path.isdir(REF_SRC),
                    reason='reference tree not present')
def test_registry_is_a_superset_of_reference():
    ours = set(registry.list_ops())
    missing = sorted(n for n in _reference_op_names() - ours
                     if not n.startswith('_backward'))
    assert not missing, 'missing registrations: %s' % missing


def test_capitalized_aliases_compute():
    a = mx.nd.array([[1., 2.], [3., 4.]])
    b = mx.nd.array([[10., 20.], [30., 40.]])
    np.testing.assert_array_equal(
        mx.nd._internal._Plus(a, b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_array_equal(
        mx.nd._internal._Mul(a, b).asnumpy(), [[10, 40], [90, 160]])
    np.testing.assert_array_equal(
        mx.nd._internal._MaximumScalar(a, scalar=2.5).asnumpy(),
        [[2.5, 2.5], [3, 4]])
    np.testing.assert_array_equal(
        mx.nd._internal._Greater(a, b).asnumpy(), np.zeros((2, 2)))
    np.testing.assert_array_equal(
        mx.nd.broadcast_plus(a, mx.nd.array([[1.], [2.]])).asnumpy(),
        [[2, 3], [5, 6]])


def test_negbinomial_sampler_aliases():
    mx.random.seed(0)
    s = mx.nd._internal._sample_negbinomial(k=5, p=0.5, shape=(500,))
    assert s.shape == (500,)
    assert float(s.asnumpy().min()) >= 0
    # negbinomial(k, p) mean = k(1-p)/p = 5
    assert abs(float(s.asnumpy().mean()) - 5.0) < 1.0
    g = mx.nd._internal._sample_gennegbinomial(mu=2.0, alpha=0.5, shape=(300,))
    assert g.shape == (300,)


def test_convolution_v1_matches_convolution():
    mx.random.seed(1)
    x = mx.nd.random.uniform(shape=(1, 3, 8, 8))
    w = mx.nd.random.uniform(shape=(4, 3, 3, 3))
    bz = mx.nd.zeros((4,))
    y1 = mx.nd.Convolution(x, w, bz, kernel=(3, 3), num_filter=4)
    y2 = mx.nd.Convolution_v1(x, w, bz, kernel=(3, 3), num_filter=4)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-5)


def test_cv_host_ops(tmp_path):
    img = (np.random.RandomState(0).rand(10, 12, 3) * 255).astype(np.uint8)
    r = mx.nd._internal._cvimresize(mx.nd.array(img.astype(np.float32)),
                                    w=6, h=5)
    assert r.shape == (5, 6, 3)
    p = mx.nd._internal._cvcopyMakeBorder(
        mx.nd.array(img.astype(np.float32)), top=1, bot=2, left=3, right=4)
    assert p.shape == (13, 19, 3)
    np.testing.assert_array_equal(p.asnumpy()[0], np.zeros((19, 3)))
    PIL = pytest.importorskip('PIL.Image')
    fn = str(tmp_path / 'im.png')
    PIL.fromarray(img).save(fn)
    rd = mx.nd._internal._cvimread(filename=fn)
    assert rd.shape == (10, 12, 3)
    np.testing.assert_array_equal(rd.asnumpy(), img)
    raw = open(fn, 'rb').read()
    dec = mx.nd._internal._cvimdecode(
        mx.nd.array(np.frombuffer(raw, np.uint8).astype(np.float32)))
    assert dec.shape == (10, 12, 3)


def test_legacy_numpy_and_ndarray_ops():
    class Scale2(mx.operator.NumpyOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] * 2

    sym = Scale2()(mx.sym.Variable('data'))
    ex = sym.bind(mx.cpu(), {'data': mx.nd.array([[1., 2.]])})
    np.testing.assert_array_equal(ex.forward()[0].asnumpy(), [[2., 4.]])

    class AddOne(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] + 1

    sym2 = AddOne()(mx.sym.Variable('data'))
    ex2 = sym2.bind(mx.cpu(), {'data': mx.nd.array([3., 4.])})
    np.testing.assert_array_equal(ex2.forward()[0].asnumpy(), [4., 5.])


def test_legacy_op_simple_bind_and_backward():
    """Host ops work through shape inference (simple_bind) and the
    traced backward via the pure_callback bridge, with the user's
    python backward supplying the VJP."""
    class Scale3(mx.operator.NumpyOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] * 3

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0] * 3

    sym = Scale3()(mx.sym.Variable('data'))
    ex = sym.simple_bind(mx.cpu(), data=(2, 3))
    ex.arg_dict['data'][:] = mx.nd.ones((2, 3))
    out = ex.forward(is_train=True)[0]
    np.testing.assert_array_equal(out.asnumpy(), 3 * np.ones((2, 3)))
    ex.backward(mx.nd.ones((2, 3)))
    np.testing.assert_array_equal(ex.grad_dict['data'].asnumpy(),
                                  3 * np.ones((2, 3)))


def test_legacy_ndarray_op_imperative_autograd():
    from mxnet_tpu.ops.legacy_ops import register_legacy_callback

    class Sq(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0].asnumpy() ** 2

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = 2 * in_data[0].asnumpy() * out_grad[0].asnumpy()

    op = Sq()
    x = mx.nd.array([1., 2., 3.])
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd._internal._NDArray(x, info=register_legacy_callback(op))
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2., 4., 6.])


def test_legacy_op_module_fit_converges():
    class Scale3(mx.operator.NumpyOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = in_data[0] * 3

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = out_grad[0] * 3

    mx.random.seed(0)
    data = mx.sym.Variable('data')
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=8,
                                                name='fc1'), act_type='relu')
    h = Scale3()(h)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=2, name='fc2'), name='softmax')
    X = np.random.RandomState(0).randn(256, 4).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=32, label_name='softmax_label')
    mod = mx.mod.Module(net, data_names=['data'],
                        label_names=['softmax_label'])
    mod.fit(it, num_epoch=15, optimizer='sgd',
            optimizer_params={'learning_rate': 0.05})
    score = dict(mod.score(it, 'acc'))
    assert score['accuracy'] > 0.9, score


def test_no_gradient_and_cross_device_copy():
    x = mx.nd.array([1., 2.])
    np.testing.assert_array_equal(
        mx.nd._internal._NoGradient(x).asnumpy(), [1., 2.])
    np.testing.assert_array_equal(
        mx.nd._internal._CrossDeviceCopy(x).asnumpy(), [1., 2.])
    s = mx.nd._internal._broadcast_backward(mx.nd.ones((2, 3)), axis=0)
    np.testing.assert_array_equal(s.asnumpy(), [2., 2., 2.])


def test_custom_symbolic_kwargs_and_traced_backward():
    """mx.sym.Custom with keyword symbol inputs (reference
    example/numpy-ops/custom_softmax.py style) composes in
    list_arguments order and trains through the traced executor."""
    class CESoftmax(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0].asnumpy()
            y = np.exp(x - x.max(axis=1, keepdims=True))
            y /= y.sum(axis=1, keepdims=True)
            self.assign(out_data[0], req[0], mx.nd.array(y))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            lab = in_data[1].asnumpy().ravel().astype(np.int64)
            y = out_data[0].asnumpy().copy()
            y[np.arange(lab.shape[0]), lab] -= 1.0
            self.assign(in_grad[0], req[0], mx.nd.array(y))

    @mx.operator.register('t_ce_softmax')
    class CEProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ['data', 'label']

        def infer_shape(self, in_shape):
            return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return CESoftmax()

    d = mx.sym.Variable('data')
    l = mx.sym.Variable('softmax_label')
    fc = mx.sym.FullyConnected(d, num_hidden=3, name='fc')
    # label= before data= on purpose: order must come from the prop,
    # not keyword insertion
    net = mx.sym.Custom(label=l, data=fc, op_type='t_ce_softmax',
                        name='softmax')
    assert net.list_arguments() == \
        ['data', 'fc_weight', 'fc_bias', 'softmax_label']
    exe = net.simple_bind(mx.cpu(), data=(6, 4), softmax_label=(6,))
    rs = np.random.RandomState(0)
    exe.arg_dict['data'][:] = rs.randn(6, 4)
    exe.arg_dict['softmax_label'][:] = rs.randint(0, 3, 6)
    exe.forward(is_train=True)
    np.testing.assert_allclose(exe.outputs[0].asnumpy().sum(axis=1),
                               np.ones(6), rtol=1e-5)
    exe.backward(exe.outputs)
    # softmax CE gradient wrt fc weights must be nonzero
    assert np.abs(exe.grad_dict['fc_weight'].asnumpy()).sum() > 0


def test_custom_aux_states_symbolic_shape():
    """shape inference slices trailing aux inputs off before calling the
    prop's infer_shape (reference custom.cc input layout)."""
    class MovAvg(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0])

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], out_grad[0])

    @mx.operator.register('t_movavg')
    class MovAvgProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ['data']

        def list_auxiliary_states(self):
            return ['hist']

        def infer_shape(self, in_shape):
            data, = in_shape  # must receive argument shapes only
            return [data], [data], [data]

        def create_operator(self, ctx, shapes, dtypes):
            return MovAvg()

    d = mx.sym.Variable('data')
    h = mx.sym.Variable('hist')
    net = mx.sym.Custom(hist=h, data=d, op_type='t_movavg', name='ma')
    exe = net.simple_bind(mx.cpu(), data=(3, 2), hist=(3, 2))
    exe.arg_dict['data'][:] = np.ones((3, 2))
    exe.forward(is_train=False)
    np.testing.assert_allclose(exe.outputs[0].asnumpy(), np.ones((3, 2)))


def test_custom_symbol_auto_created_inputs():
    """Custom symbols grow a <name>_<arg> Variable for each declared
    input not passed (reference compose semantics; mnist/custom_softmax
    scripts rely on the auto-created softmax_label). Positionals fill
    the leading declared slots only; duplicates and overflow raise."""
    import mxnet_tpu as mx

    class Prop(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)

        def list_arguments(self):
            return ['data', 'label']

        def list_outputs(self):
            return ['output']

        def infer_shape(self, in_shape):
            return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            class Op(mx.operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    self.assign(out_data[0], req[0], in_data[0])

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    self.assign(in_grad[0], req[0], out_data[0])
            return Op()

    mx.operator.register('autoinput_probe')(Prop)
    d = mx.sym.Variable('d')
    s = mx.sym.Custom(data=d, name='soft', op_type='autoinput_probe')
    assert s.list_arguments() == ['d', 'soft_label']
    s2 = mx.sym.Custom(d, name='s2', op_type='autoinput_probe')
    assert s2.list_arguments() == ['d', 's2_label']
    with pytest.raises(ValueError, match='both'):
        mx.sym.Custom(d, data=d, op_type='autoinput_probe')
    with pytest.raises(ValueError, match='extra positional'):
        mx.sym.Custom(d, d, d, op_type='autoinput_probe')
