"""Multi-chip dryrun at width (VERDICT r4 #5): n=16 and n=32 virtual
meshes light up sp/ep in the PRIMARY round-robin mesh (16 → dp2.tp2.pp2.sp2,
32 → all five axes at 2), and every parity assert inside
__graft_entry__.dryrun_multichip must hold — the n-device loss
trajectory equals a 1-device run of the same model/data, so "ok" means
*correct*, not just *ran* (reference analogue: the exact-arithmetic
style of tests/nightly/dist_sync_kvstore.py:28-80).

Each width needs its own process: the virtual device count is fixed at
backend init by --xla_force_host_platform_device_count.
"""
import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), '..', '..'))

# Same root as the five_d xfails: jax 0.4.x's experimental shard_map
# (check_rep=False) mis-specs scalar cotangents through the GPipe
# pipeline gradient, and the wide dryrun meshes (sp/pp lit up) hit it.
# Version-gated and non-strict — on an upgraded jax the dryrun parity
# asserts simply run and pass.
OLD_SHARD_MAP = tuple(int(x) for x in jax.__version__.split('.')[:2]) < (0, 5)

_SCRUB = ['AXON_LOOPBACK_RELAY', 'TPU_SKIP_MDS_QUERY', 'PALLAS_AXON_TPU_GEN',
          'PALLAS_AXON_POOL_IPS', 'PALLAS_AXON_REMOTE_COMPILE',
          'AXON_POOL_SVC_OVERRIDE', 'TPU_WORKER_HOSTNAMES',
          'TPU_LIBRARY_PATH', 'AXON_COMPAT_VERSION', 'PJRT_LIBRARY_PATH',
          'TPU_ACCELERATOR_TYPE', 'TPU_TOPOLOGY', '_AXON_REGISTERED']


@pytest.mark.xfail(
    condition=OLD_SHARD_MAP,
    reason='jax 0.4.x shard_map check_rep=False transpose mis-specs '
           'scalar cotangents through the pipeline stages of the wide '
           'dryrun meshes (needs newer jax; same root as the five_d '
           'pipeline-gradient xfails)',
    strict=False)
@pytest.mark.parametrize('n', [16, 32])
def test_dryrun_multichip_at_width(n):
    env = {k: v for k, v in os.environ.items() if k not in _SCRUB}
    env['XLA_FLAGS'] = '--xla_force_host_platform_device_count=%d' % n
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = os.pathsep.join(
        p for p in [REPO, env.get('PYTHONPATH', '')] if p)
    code = ("import jax; jax.config.update('jax_platforms', 'cpu');"
            "from __graft_entry__ import dryrun_multichip;"
            "dryrun_multichip(%d)" % n)
    proc = subprocess.run([sys.executable, '-c', code], env=env, cwd=REPO,
                          capture_output=True, text=True, timeout=1200)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    # the primary mesh at this width must include the wide axes...
    if n == 16:
        assert "'sp': 2" in out, out[-2000:]
    else:
        assert "'sp': 2" in out and "'ep': 2" in out, out[-2000:]
    # ...and every parity assert must have fired and passed
    assert out.count('parity') >= 1, out[-2000:]
    assert 'OK' in out, out[-2000:]
