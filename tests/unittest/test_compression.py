"""Quantized gradient collectives with error feedback (ISSUE 17).

The contracts (parallel/compression.py on the fused window, the
kvstore wire, and the auto trigger):

- block-wise int8 round-trips within the scale/2 bound across block
  sizes, non-dividing shapes, all-zero blocks, and extreme magnitudes;
  a non-finite input poisons its OWN block (the health sentinel must
  trip) and never launders into a finite value;
- error feedback carries the dropped quantization error so a
  sub-scale gradient component is paid out over steps, not lost;
- with MXTPU_GRAD_COMPRESS unset/off the fused window lowers
  byte-identically to today's program; int8 changes it and carries
  the residual through the scan carry (ZeRO-layout leaves);
- the comm.* gauges are exact wire arithmetic with 'modeled'
  provenance on the SPMD window and 'measured' on the kvstore TCP
  path; the kvstore wire is version-tagged and fails LOUDLY on skew;
- auto mode flips int8 on a communication_bound cluster verdict and
  emits exactly ONE {'type': 'compression'} record with the
  before/after step-time delta;
- PR 9 residue: _update_params re-pins a kvstore-pulled gradient to
  its weight's sharding before the updater runs (SPMD placement
  invariant).
"""
import json
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.config import flags
from mxnet_tpu.parallel import compression as C
from mxnet_tpu.parallel._compat import shard_map

_FLAGS = ('MXTPU_GRAD_COMPRESS', 'MXTPU_GRAD_COMPRESS_BLOCK',
          'MXTPU_SHARDED_UPDATE', 'MXTPU_FUSED_FIT', 'MXTPU_TELEMETRY',
          'MXTPU_TELEMETRY_PATH', 'MXTPU_SCALARS_EVERY')


def _reload():
    for f in _FLAGS:
        flags.reload(f)


@pytest.fixture
def clean_flags(monkeypatch):
    monkeypatch.setenv('MXTPU_FUSED_FIT', '1')
    _reload()
    telemetry._reset_for_tests()
    yield monkeypatch
    telemetry._reset_for_tests()
    for f in _FLAGS:
        monkeypatch.delenv(f, raising=False)
    _reload()


# ---------------------------------------------------------------------------
# codec properties (jnp path)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('block', [8, 64, 256])
@pytest.mark.parametrize('n', [7, 256, 1000])
def test_int8_roundtrip_error_bound(block, n):
    """Round-to-nearest with per-block amax/127 scales: every element
    reconstructs within scale/2 = amax_block/254, for dividing and
    non-dividing lengths alike."""
    rng = np.random.RandomState(block * 1000 + n)
    x = (rng.randn(n) * rng.choice([1e-3, 1.0, 50.0], n)).astype(np.float32)
    payload, scales = C.quantize(jnp.asarray(x), 'int8', block)
    back = np.asarray(C.dequantize(payload, scales, n, jnp.float32,
                                   'int8', block))
    assert back.shape == (n,) and np.isfinite(back).all()
    pad = (-n) % block
    xb = np.concatenate([x, np.zeros(pad, np.float32)]).reshape(-1, block)
    bound = np.abs(xb).max(axis=1, keepdims=True) / 254.0 + 1e-12
    err = np.abs(np.concatenate([back, np.zeros(pad, np.float32)])
                 .reshape(-1, block) - xb)
    assert (err <= bound).all(), float((err - bound).max())


def test_all_zero_blocks_roundtrip_exactly():
    x = jnp.zeros((300,), jnp.float32)
    payload, scales = C.quantize(x, 'int8', 128)
    assert np.asarray(scales).tolist() == [1.0, 1.0, 1.0]
    back = C.dequantize(payload, scales, 300, jnp.float32, 'int8', 128)
    np.testing.assert_array_equal(np.asarray(back), np.zeros(300))


@pytest.mark.parametrize('mag', [1e-30, 1e30])
def test_extreme_scales_stay_finite(mag):
    rng = np.random.RandomState(3)
    x = (rng.randn(256).astype(np.float32) * np.float32(mag))
    payload, scales = C.quantize(jnp.asarray(x), 'int8', 64)
    back = np.asarray(C.dequantize(payload, scales, 256, jnp.float32,
                                   'int8', 64))
    assert np.isfinite(back).all()
    bound = np.abs(x.reshape(-1, 64)).max(axis=1, keepdims=True) / 254.0
    # denormal scales bottom out at float32 resolution — allow an eps
    assert (np.abs(back.reshape(-1, 64) - x.reshape(-1, 64))
            <= bound + np.float32(mag) * 1e-6 + 1e-38).all()


@pytest.mark.parametrize('poison', [np.nan, np.inf, -np.inf])
def test_nonfinite_poisons_own_block_only(poison):
    """A NaN/Inf gradient element must reach the health sentinel: its
    block dequantizes non-finite, neighbors stay exact-quality."""
    x = np.ones((512,), np.float32)
    x[10] = poison
    payload, scales = C.quantize(jnp.asarray(x), 'int8', 256)
    back = np.asarray(C.dequantize(payload, scales, 512, jnp.float32,
                                   'int8', 256))
    assert not np.isfinite(back[:256]).any(), 'poison was laundered'
    assert np.isfinite(back[256:]).all()
    np.testing.assert_allclose(back[256:], 1.0, rtol=1e-2)


def test_ef_roundtrip_sanitizes_residual_not_signal():
    x = np.ones((512,), np.float32)
    x[0] = np.nan
    xq, resid = C.ef_roundtrip(jnp.asarray(x), jnp.zeros((512,)),
                               'int8', 256)
    # the quantized gradient keeps the poison (sentinel trips)...
    assert not np.isfinite(np.asarray(xq)[:256]).any()
    # ...but the carried residual is sanitized: one bad step cannot
    # poison the error-feedback state forever
    assert np.isfinite(np.asarray(resid)).all()


def test_error_feedback_pays_out_subscale_components():
    """A component below scale/2 quantizes to 0 every single step
    without EF; with EF the dropped error accumulates and is paid out —
    the k-step sum tracks k*x within one quantization step."""
    block = 64
    x = np.zeros((block,), np.float32)
    x[0] = 1.0          # pins the block scale at 1/127 ~ 0.0079
    x[1] = 0.001        # sub-scale: rounds to 0 alone
    xj = jnp.asarray(x)
    naive = C.dequantize(*C.quantize(xj, 'int8', block), block,
                         jnp.float32, 'int8', block)
    assert float(naive[1]) == 0.0
    resid = jnp.zeros((block,))
    paid = 0.0
    k = 40
    for _ in range(k):
        xq, resid = C.ef_roundtrip(xj, resid, 'int8', block)
        paid += float(xq[1])
    assert abs(paid - k * 0.001) <= 1.0 / 127.0, paid


def test_bf16_mode_roundtrip():
    rng = np.random.RandomState(5)
    x = rng.randn(100).astype(np.float32) * 30
    payload, scales = C.quantize(jnp.asarray(x), 'bf16')
    assert scales is None and payload.dtype == jnp.bfloat16
    back = np.asarray(C.dequantize(payload, None, 100, jnp.float32, 'bf16'))
    np.testing.assert_allclose(back, x, rtol=2 ** -8)


def test_quantize_rejects_non_wire_modes():
    x = jnp.ones((8,))
    for mode in ('off', 'auto', 'zstd'):
        with pytest.raises(ValueError):
            C.quantize(x, mode, 8)
    with pytest.raises(ValueError):
        C.dequantize(x, x, 8, jnp.float32, 'auto', 8)
    with pytest.raises(ValueError):
        C.wire_bytes(8, 'zstd')


# ---------------------------------------------------------------------------
# the wire-byte model
# ---------------------------------------------------------------------------

def test_wire_bytes_arithmetic():
    assert C.wire_bytes(4096, 'off') == 16384
    assert C.wire_bytes(4096, 'bf16') == 8192
    # int8: payload + one fp32 scale per (ceil) block
    assert C.wire_bytes(4096, 'int8', 256) == 4096 + 16 * 4
    assert C.wire_bytes(100, 'int8', 256) == 100 + 4
    assert C.compression_ratio(0, 'int8') == 1.0
    assert C.compression_ratio(4096, 'bf16') == 2.0
    r = C.compression_ratio(4096, 'int8', 256)
    assert 3.9 < r < 4.0, r


# ---------------------------------------------------------------------------
# kvstore wire codec (numpy) + version discipline
# ---------------------------------------------------------------------------

@pytest.mark.parametrize('n', [10, 256, 1000])
def test_wire_codec_roundtrip(n):
    rng = np.random.RandomState(n)
    x = rng.randn(n).astype(np.float32) * 4
    msg = C.encode_wire(x, 'int8', 256)
    assert msg[0] == C.WIRE_VERSION and msg[1] == 'int8'
    back = C.decode_wire(msg)
    assert back.dtype == np.float32 and back.shape == (n,)
    bound = np.abs(x).max() / 254.0 + 1e-9
    assert np.abs(back - x).max() <= bound
    # measured bytes = payload + scales, genuinely smaller than fp32
    assert C.wire_message_bytes(msg) == n + (-(-n // 256)) * 4
    bf = C.decode_wire(C.encode_wire(x, 'bf16'))
    np.testing.assert_allclose(bf, x, rtol=2 ** -8, atol=1e-6)


def test_wire_codec_never_launders_nonfinite():
    x = np.ones((512,), np.float32)
    x[300] = np.nan
    back = C.decode_wire(C.encode_wire(x, 'int8', 256))
    assert np.isfinite(back[:256]).all()
    assert not np.isfinite(back[256:]).any(), 'wire codec laundered NaN'


def test_wire_version_and_mode_skew_fail_loudly():
    msg = C.encode_wire(np.ones((16,), np.float32), 'int8', 8)
    stale = (C.WIRE_VERSION + 1,) + msg[1:]
    with pytest.raises(RuntimeError, match='version mismatch'):
        C.decode_wire(stale)
    weird = (msg[0], 'zstd') + msg[2:]
    with pytest.raises(RuntimeError, match='unknown mode'):
        C.decode_wire(weird)


def test_kvstore_dist_sync_compressed_push_pull(clean_flags):
    """In-process dist_sync cluster with int8 wire compression: the
    push travels as a push_c message (worker-side EF residual stored),
    the pulled aggregate lands within the int8 bound, and the measured
    comm.* gauges carry genuinely smaller byte counts."""
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    clean_flags.setenv('MXTPU_TELEMETRY', '1')
    clean_flags.setenv('MXTPU_TELEMETRY_PATH', '/dev/null')
    _reload()
    telemetry._reset_for_tests()
    kv = mx.kv.create('dist_sync')
    shape = (25, 20)
    kv.init('cw', mx.nd.zeros(shape))
    g = np.random.RandomState(11).randn(*shape).astype(np.float32)
    kv.push('cw', mx.nd.array(g))
    out = mx.nd.zeros(shape)
    kv.pull('cw', out=out)
    bound = np.abs(g).max() / 254.0 + 1e-9
    assert np.abs(out.asnumpy() - g).max() <= 2 * bound
    # worker-side EF engaged and the wire stats are measured, not modeled
    assert kv._push_ef, 'no worker-side error-feedback residual stored'
    comp, unc = next(iter(kv._wire_stats.values()))
    assert 0 < comp < 0.3 * unc, (comp, unc)
    gauges = telemetry.snapshot()['gauges']
    assert gauges['comm.bytes_src'] == 'measured'
    assert gauges['comm.mode'] == 'int8'
    assert gauges['comm.bytes_on_wire_per_step'] == comp
    kv.barrier()


# ---------------------------------------------------------------------------
# compressed_psum: the honest collective form (shard_map)
# ---------------------------------------------------------------------------

def _dp_mesh():
    devs = np.array(jax.devices()[:8])
    return jax.sharding.Mesh(devs, ('dp',))


@pytest.mark.parametrize('mode', ['off', 'int8', 'bf16'])
def test_compressed_psum_matches_psum(mode):
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = _dp_mesh()
    rng = np.random.RandomState(17)
    x = rng.randn(8, 40).astype(np.float32)

    def body(xs):
        return C.compressed_psum(xs, 'dp', mode=mode, block=16)

    fn = shard_map(body, mesh=mesh, in_specs=P('dp', None),
                   out_specs=P('dp', None), check_rep=False)
    xg = jax.device_put(x, NamedSharding(mesh, P('dp', None)))
    got = np.asarray(jax.jit(fn)(xg))
    want = x.sum(axis=0)
    for row in got:          # every participant holds the full sum
        if mode == 'off':
            np.testing.assert_allclose(row, want, rtol=1e-6)
        else:
            # 8 contributions, each within its own block bound
            tol = 8 * (np.abs(x).max() / (254.0 if mode == 'int8'
                                          else 256.0)) + 1e-5
            np.testing.assert_allclose(row, want, atol=tol)


# ---------------------------------------------------------------------------
# mode resolution + the auto trigger
# ---------------------------------------------------------------------------

def test_resolved_mode_and_auto_flip(clean_flags):
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'off')
    assert C.resolved_mode() == 'off'
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    assert C.resolved_mode() == 'int8'
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'auto')
    assert C.resolved_mode() == 'off' and not C.auto_engaged()
    # only the communication_bound verdict flips
    C.note_round_verdict('compute_bound')
    assert C.resolved_mode() == 'off'
    C.note_round_verdict('communication_bound')
    assert C.auto_engaged() and C.resolved_mode() == 'int8'
    # the flip is latched for the rest of the run
    C.note_round_verdict('compute_bound')
    assert C.resolved_mode() == 'int8'
    # a non-auto flag never engages the trigger state
    telemetry._reset_for_tests()
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    C.note_round_verdict('communication_bound')
    assert not C.auto_engaged()


def test_cluster_round_feeds_the_trigger(clean_flags):
    """telemetry.cluster.sync_now routes its round verdict into
    compression.note_round_verdict on every host — the auto flip needs
    no extra collective."""
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'auto')
    clean_flags.setenv('MXTPU_TELEMETRY', '1')
    clean_flags.setenv('MXTPU_TELEMETRY_SYNC_EVERY', '1')
    clean_flags.setenv('MXTPU_TELEMETRY_PATH', '/dev/null')
    for f in _FLAGS + ('MXTPU_TELEMETRY_SYNC_EVERY',):
        flags.reload(f)
    telemetry._reset_for_tests()
    try:
        from mxnet_tpu.telemetry import cluster
        assert cluster.enabled()
        # a 2-host round whose slowest host spends 90% of its step in
        # collectives (row: step_time_ms, io_wait_pct, steps, t,
        # comm_pct, proc_index) — classify() reads communication_bound
        mat = np.array([[100.0, 0.0, 4.0, 0.0, 90.0, 0.0],
                        [10.0, 0.0, 4.0, 0.0, 5.0, 1.0]])
        assert cluster.round_verdict(mat)[2] == 'communication_bound'
        clean_flags.setattr(cluster, '_allgather', lambda _row: mat)
        assert C.resolved_mode() == 'off'
        cluster.sync_now()
        assert C.auto_engaged() and C.resolved_mode() == 'int8'
    finally:
        telemetry._reset_for_tests()
        flags.reload('MXTPU_TELEMETRY_SYNC_EVERY')


# ---------------------------------------------------------------------------
# fused window: byte-identity off, residual carry + parity on int8
# ---------------------------------------------------------------------------

def _spmd_mod(hidden=10, n=64, batch=16, seed=7):
    mx.random.seed(seed)
    np.random.seed(seed)
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name='fc1')
    act = mx.sym.Activation(fc1, act_type='relu', name='relu1')
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name='fc2')
    out = mx.sym.SoftmaxOutput(fc2, name='softmax')
    X = np.random.RandomState(3).randn(n, 10).astype(np.float32)
    y = (np.random.RandomState(4).rand(n) * 4).astype(int) \
        .astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch, shuffle=False,
                           label_name='softmax_label')
    mod = mx.mod.Module(out, context=[mx.cpu(i) for i in range(8)])
    return mod, it


def _fit(mod, it, num_epoch=2, **kw):
    kw.setdefault('optimizer', 'sgd')
    kw.setdefault('optimizer_params', (('learning_rate', 0.1),
                                       ('momentum', 0.9)))
    kw.setdefault('kvstore', 'device')
    kw.setdefault('eval_metric', 'acc')
    mod.fit(it, num_epoch=num_epoch, **kw)
    return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}


def _loop(mod):
    return mod.__dict__['_fused_fit_cache'][1]


def _window_text(loop):
    """Lowered+compiled HLO of the loop's window program, rebuilt
    deterministically (the test_sharded_update pattern, resid-aware)."""
    fn = loop._build_program(loop._static_attrs(), None)
    jitted = getattr(fn, 'jitted', fn)
    params, states, aux, gaccs = loop._snapshot()
    W = loop.window
    data_stack = (jnp.zeros((W, 16, 10), jnp.float32),)
    label_stack = (jnp.zeros((W, 16), jnp.float32),)
    lr = np.ones((W, len(loop._grad_names)), np.float32)
    args = [params, states, aux, gaccs]
    if loop._cmode() != 'off':
        args.append(loop._ensure_resids())
    args += [data_stack, label_stack, jax.random.PRNGKey(0), lr, lr]
    return jitted.lower(*args).compile().as_text()


def test_off_and_unset_lower_byte_identically(clean_flags):
    """The acceptance bit: MXTPU_GRAD_COMPRESS unset and explicit off
    produce the same lowered window text — the compression machinery
    leaves today's program untouched — and int8 is a REAL program
    change (int8 ops present, extra carry)."""
    clean_flags.setenv('MXTPU_SHARDED_UPDATE', '1')
    _reload()
    texts = {}
    for tag, val in (('unset', None), ('off', 'off')):
        if val is None:
            clean_flags.delenv('MXTPU_GRAD_COMPRESS', raising=False)
        else:
            clean_flags.setenv('MXTPU_GRAD_COMPRESS', val)
        _reload()
        mod, it = _spmd_mod()
        _fit(mod, it, num_epoch=1)
        texts[tag] = _window_text(_loop(mod))
    assert texts['unset'] == texts['off']
    assert 's8[' not in texts['off']

    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    _reload()
    mod, it = _spmd_mod()
    _fit(mod, it, num_epoch=1)
    int8_text = _window_text(_loop(mod))
    assert int8_text != texts['off']
    assert 's8[' in int8_text, 'int8 quantization not in the program'


def test_int8_fit_residual_carry_and_parity(clean_flags):
    """int8+EF training on the 8-device mesh: the residual leaves live
    in the ZeRO layout (flat, padded, one per grad leaf), the window
    count and mode land in the loop's compression state, and the final
    params stay within EF-bounded distance of the uncompressed run."""
    clean_flags.setenv('MXTPU_SHARDED_UPDATE', '1')
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    _reload()
    mod, it = _spmd_mod()
    a1 = _fit(mod, it)
    loop = _loop(mod)
    assert loop._cstate['mode'] == 'int8'
    assert loop._cstate['windows'] == 2
    # one residual per grad leaf, flat zero-padded lengths
    want = {'fc1_weight': 104, 'fc1_bias': 16,
            'fc2_weight': 40, 'fc2_bias': 8}
    got = {n: int(r.shape[0]) for n, r in loop._resid.items()}
    assert got == want, got
    for r in loop._resid.values():
        assert np.isfinite(np.asarray(r)).all()

    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'off')
    _reload()
    mod0, it0 = _spmd_mod()
    a0 = _fit(mod0, it0)
    for k in a1:
        assert np.isfinite(a1[k]).all(), k
        # int8+EF is a different trajectory, but a close one: the
        # quantization error is ~0.4% relative per step and EF keeps
        # it unbiased — parity within a few percent of weight scale
        scale = np.abs(a0[k]).max() + 1e-6
        assert np.abs(a1[k] - a0[k]).max() <= 0.05 * scale, k


def test_modeled_comm_gauges_exact(clean_flags):
    """The SPMD window publishes exact wire arithmetic with 'modeled'
    provenance — 184 bytes/step for this model at block 256 vs 672
    uncompressed."""
    clean_flags.setenv('MXTPU_SHARDED_UPDATE', '1')
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    clean_flags.setenv('MXTPU_TELEMETRY', '1')
    clean_flags.setenv('MXTPU_TELEMETRY_PATH', '/dev/null')
    _reload()
    telemetry._reset_for_tests()
    mod, it = _spmd_mod()
    _fit(mod, it)
    g = telemetry.snapshot()['gauges']
    want = sum(C.wire_bytes(L, 'int8', 256)
               for L in (104, 16, 40, 8))
    assert g['comm.bytes_on_wire_per_step'] == want == 184
    unc = sum(C.wire_bytes(L, 'off') for L in (104, 16, 40, 8))
    assert g['comm.compression_ratio'] == round(unc / want, 3)
    assert g['comm.mode'] == 'int8'
    assert g['comm.bytes_src'] == 'modeled'


def test_auto_flip_rebuilds_and_emits_one_record(clean_flags, tmp_path):
    """MXTPU_GRAD_COMPRESS=auto: the run starts uncompressed; after the
    cluster verdict flips the trigger, the next window dispatch
    rebuilds as int8 and exactly ONE {'type': 'compression'} record
    lands, carrying the before/after step-time delta (taken from the
    steady window AFTER the flip — the flipped window pays compile)."""
    tele = tmp_path / 't.jsonl'
    clean_flags.setenv('MXTPU_SHARDED_UPDATE', '1')
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'auto')
    clean_flags.setenv('MXTPU_TELEMETRY', '1')
    clean_flags.setenv('MXTPU_TELEMETRY_PATH', str(tele))
    _reload()
    telemetry._reset_for_tests()
    mod, it = _spmd_mod()
    _fit(mod, it)                      # 2 windows, auto -> off
    loop = _loop(mod)
    assert loop._cstate['mode'] == 'off'
    assert not loop._cstate['emitted']
    # the cluster round classifies communication_bound on every host
    C.note_round_verdict('communication_bound')
    assert C.resolved_mode() == 'int8'
    _fit(mod, it, num_epoch=4)         # 4 windows, now int8
    assert loop._cstate['mode'] == 'int8'
    assert loop._resid is not None
    telemetry._state.sink.flush()      # the sink batches writes
    recs = [json.loads(ln) for ln in open(tele) if ln.strip()]
    comp = [r for r in recs if r.get('type') == 'compression']
    assert len(comp) == 1, comp
    rec = comp[0]
    assert rec['event'] == 'mode_flip'
    assert rec['mode'] == 'int8' and rec['prev_mode'] == 'off'
    assert rec['auto'] is True
    assert rec['before_step_ms'] > 0 and rec['after_step_ms'] > 0
    assert rec['delta_step_ms'] == pytest.approx(
        rec['after_step_ms'] - rec['before_step_ms'], abs=1e-6)
    g = telemetry.snapshot()['gauges']
    assert g['comm.mode'] == 'int8'


def test_compress_without_sharded_update_warns_and_stays_off(clean_flags,
                                                             caplog):
    """Flag honesty: int8 requested but the ZeRO layout (the flat
    dp-sharded gradient the quantizer needs) is off — the run warns
    once and stays uncompressed rather than silently half-applying."""
    import logging
    from mxnet_tpu.module import fused_fit as ff
    clean_flags.setenv('MXTPU_SHARDED_UPDATE', '0')
    clean_flags.setenv('MXTPU_GRAD_COMPRESS', 'int8')
    _reload()
    ff._compress_off_warned.clear()
    try:
        with caplog.at_level(logging.WARNING):
            mod, it = _spmd_mod()
            _fit(mod, it, num_epoch=1)
        loop = _loop(mod)
        # no ZeRO layout -> the compression plane never engages (the
        # per-window hook is part of the sharded-update path)
        assert loop._cstate['mode'] is None
        assert loop._resid is None
        assert 'MXTPU_GRAD_COMPRESS' in caplog.text
    finally:
        ff._compress_off_warned.clear()


# ---------------------------------------------------------------------------
# PR 9 residue: _update_params SPMD placement invariant
# ---------------------------------------------------------------------------

def test_update_params_repins_kvstore_pulled_grad(clean_flags):
    """The kvstore-but-not-update-on-kvstore branch: pull materializes
    the summed gradient on its own context's device while the weight
    is mesh-sharded — _update_params must restore the gradient to the
    weight's sharding BEFORE the updater mixes them."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu import model
    mesh = _dp_mesh()
    row = NamedSharding(mesh, P('dp', None))
    w = mx.nd.array(np.zeros((8, 4), np.float32))
    w._data = jax.device_put(w._data, row)
    g = mx.nd.array(np.ones((8, 4), np.float32))
    assert w._data.sharding != g._data.sharding
    kv = types.SimpleNamespace(push=lambda *a, **k: None,
                               pull=lambda *a, **k: None)
    seen = []

    def updater(index, grad, weight):
        seen.append((index, grad._data.sharding == weight._data.sharding))
        weight._data = weight._data - 0.1 * grad._data

    model._update_params([[w]], [[g]], updater, num_device=1,
                         kvstore=kv, param_names=['w'])
    assert seen == [(0, True)], seen
    np.testing.assert_allclose(w.asnumpy(), -0.1 * np.ones((8, 4)),
                               rtol=1e-6)
