"""Legacy model API: checkpoints, FeedForward shim, callbacks, monitor,
visualization.

Reference: python/mxnet/model.py:340-370 (save/load_checkpoint),
callback.py, monitor.py, tests/python/unittest/test_viz.py.
"""
import logging
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _mlp():
    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, name='fc1', num_hidden=4)
    net = mx.sym.Activation(net, name='relu1', act_type='relu')
    net = mx.sym.FullyConnected(net, name='fc2', num_hidden=2)
    return mx.sym.SoftmaxOutput(net, name='softmax')


def _params():
    rng = np.random.RandomState(0)
    return (
        {'fc1_weight': nd.array(rng.randn(4, 6).astype(np.float32)),
         'fc1_bias': nd.zeros((4,)),
         'fc2_weight': nd.array(rng.randn(2, 4).astype(np.float32)),
         'fc2_bias': nd.zeros((2,))},
        {},
    )


def test_save_load_checkpoint_roundtrip():
    net = _mlp()
    arg_params, aux_params = _params()
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, 'model')
        mx.model.save_checkpoint(prefix, 3, net, arg_params, aux_params)
        assert os.path.exists(prefix + '-symbol.json')
        assert os.path.exists(prefix + '-0003.params')
        sym2, args2, auxs2 = mx.model.load_checkpoint(prefix, 3)
        assert sym2.tojson() == net.tojson()
        for k, v in arg_params.items():
            np.testing.assert_allclose(args2[k].asnumpy(), v.asnumpy())
        assert auxs2 == {}


def test_module_checkpoint_epoch_callback():
    from mxnet_tpu.module import Module
    from mxnet_tpu.io import NDArrayIter
    rng = np.random.RandomState(1)
    X = rng.randn(16, 6).astype(np.float32)
    y = (rng.rand(16) > 0.5).astype(np.float32)
    mod = Module(_mlp(), data_names=['data'], label_names=['softmax_label'])
    it = NDArrayIter(X, y, batch_size=8, label_name='softmax_label')
    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, 'mod')
        mod.fit(it, num_epoch=2, batch_end_callback=None,
                epoch_end_callback=mx.callback.do_checkpoint(prefix),
                optimizer_params={'learning_rate': 0.1})
        assert os.path.exists(prefix + '-0001.params')
        assert os.path.exists(prefix + '-0002.params')
        sym2, args2, _ = mx.model.load_checkpoint(prefix, 2)
        assert 'fc1_weight' in args2


def test_feedforward_shim():
    rng = np.random.RandomState(2)
    X = rng.randn(32, 6).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    ff = mx.model.FeedForward(_mlp(), num_epoch=3,
                              optimizer='sgd',
                              learning_rate=0.5, numpy_batch_size=16)
    ff.fit(X, y)
    preds = ff.predict(X)
    assert preds.shape == (32, 2)
    assert np.allclose(preds.sum(1), 1.0, atol=1e-4)


def test_speedometer_and_log_metric():
    from mxnet_tpu.callback import Speedometer, log_train_metric
    from mxnet_tpu.metric import create as create_metric

    class P:  # BatchEndParam shim
        def __init__(self, nbatch):
            self.epoch = 0
            self.nbatch = nbatch
            self.eval_metric = create_metric('acc')
            self.locals = None

    s = Speedometer(batch_size=8, frequent=2, auto_reset=False)
    lt = log_train_metric(2)
    for i in range(1, 5):
        p = P(i)
        p.eval_metric.update(
            [nd.array(np.array([0.0], np.float32))],
            [nd.array(np.array([[0.9, 0.1]], np.float32))])
        s(p)
        lt(p)


def test_monitor_collects_op_stats():
    from mxnet_tpu.monitor import Monitor
    net = _mlp()
    arg_params, _ = _params()
    rng = np.random.RandomState(3)
    args = dict(arg_params)
    args['data'] = nd.array(rng.randn(2, 6).astype(np.float32))
    args['softmax_label'] = nd.array(np.array([0, 1], np.float32))
    ex = net.bind(mx.cpu(), args)
    mon = Monitor(1, pattern='.*output.*')
    mon.install(ex)
    mon.tic()
    ex.forward(is_train=True)
    res = mon.toc()
    assert len(res) > 0
    for (batch, name, stat) in res:
        assert 'output' in name


def test_print_summary_runs():
    from mxnet_tpu.visualization import print_summary
    lines = []
    import builtins
    old_print = builtins.print
    builtins.print = lambda *a, **k: lines.append(' '.join(str(x) for x in a))
    try:
        print_summary(_mlp(), shape={'data': (1, 6)})
    finally:
        builtins.print = old_print
    text = '\n'.join(lines)
    assert 'fc1' in text and 'Total params' in text


def test_plot_network_graph_structure():
    from mxnet_tpu.visualization import plot_network
    dot = plot_network(_mlp(), shape={'data': (1, 6)})
    src = getattr(dot, 'source', None) or str(dot)
    assert 'fc1' in src and 'softmax' in src


def test_feedforward_dict_input_batch_size():
    """Regression: dict/list inputs must count samples, not keys."""
    rng = np.random.RandomState(4)
    X = {'data': rng.randn(32, 6).astype(np.float32)}
    y = (X['data'][:, 0] > 0).astype(np.float32)
    ff = mx.model.FeedForward(_mlp(), num_epoch=1, optimizer='sgd',
                              learning_rate=0.1, numpy_batch_size=16)
    ff.fit(X, y)
    assert ff._module._exec_group.batch_size == 16
    preds = ff.predict({'data': X['data']})
    assert preds.shape == (32, 2)
