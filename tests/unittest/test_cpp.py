"""Native C++ unit-test tier (reference tests/cpp) — built from source,
independent of the prebuilt ctypes runtime library.
"""
import os

import pytest


def test_cpp_unit_suite():
    """Build + run the native C++ test binary (reference tests/cpp
    tier: engine/storage/recordio/profiler without python)."""
    import shutil
    import subprocess
    if shutil.which('g++') is None or shutil.which('make') is None:
        pytest.skip('no native toolchain')
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(['make', '-s', '-C',
                           os.path.join(repo, 'tests', 'cpp'), 'test'],
                          capture_output=True, text=True, timeout=300)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-2000:]
    assert 'ALL CPP TESTS PASSED' in out
