"""Roofline attribution plane (mxnet_tpu/telemetry/roofline).

Contracts under test:
- HLO text -> per-layer cost parse (dot/convolution FLOPs from
  contraction dims, bytes from shapes, named-scope layer extraction
  through jvp/transpose wrappers, collective accounting, free ops);
- the trace join: synthetic chrome-trace events keyed by HLO
  instruction names -> measured per-layer times, step-count inference,
  comm/compute overlap;
- deterministic classification goldens against overridden peaks
  (compute-bound / memory-bound / overhead-bound);
- MXTPU_ROOFLINE=0/1 parametrized fit acceptance: =1 puts a ranked
  bottleneck block in the summary where every named layer carries a
  classification and an achieved/peak %, plus roofline.* gauges and a
  JSONL record; =0 leaves no trace anywhere;
- the no-op contract: the lowered step HLO is byte-identical with the
  flag on or off (attribution is host-side parsing, never graph edits);
- unknown-device peaks: warn once, publish roofline.peaks_unknown,
  honor the MXTPU_PEAK_TFLOPS / MXTPU_PEAK_HBM_GBS overrides;
- the offline CLI (tools/roofline_report.py) renders the JSONL record
  byte-identically to the live summary block.
"""
import json
import logging
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.config import flags
from mxnet_tpu.telemetry import roofline
from mxnet_tpu.telemetry import xla as tele_xla

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(REPO, 'tools'))

_FLAGS = ('MXTPU_TELEMETRY', 'MXTPU_TELEMETRY_PATH', 'MXTPU_ROOFLINE',
          'MXTPU_ROOFLINE_TRACE', 'MXTPU_PEAK_TFLOPS',
          'MXTPU_PEAK_HBM_GBS')


def _reload_flags():
    for f in _FLAGS:
        flags.reload(f)


@pytest.fixture
def roof_on(tmp_path, monkeypatch):
    """Telemetry + roofline ON, logging to a tmp JSONL."""
    path = tmp_path / 'roofline.jsonl'
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(path))
    monkeypatch.setenv('MXTPU_ROOFLINE', '1')
    _reload_flags()
    telemetry._reset_for_tests()
    yield path
    telemetry._reset_for_tests()
    for f in _FLAGS:
        monkeypatch.delenv(f, raising=False)
    _reload_flags()


def _records(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


# A synthetic HLO module exercising every parse path: a dot (FLOPs
# from the contracting dim), an elementwise op, a tiny op (the
# overhead-bound golden), an all-reduce (comm accounting) and free ops
# (parameter/copy cost nothing).
_SYNTH_HLO = '''\
HloModule synthetic, entry_computation_layout={()->f32[64,64]{1,0}}
ENTRY %main () -> f32[64,64] {
  %p0 = f32[64,128]{1,0} parameter(0)
  %p1 = f32[64,128]{1,0} parameter(1)
  %dot.1 = f32[64,64]{1,0} dot(f32[64,128]{1,0} %p0, f32[64,128]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={1}, metadata={op_name="jit(main)/fc1/dot_general"}
  %add.2 = f32[64,64]{1,0} add(f32[64,64]{1,0} %dot.1, f32[64,64]{1,0} %dot.1), metadata={op_name="jit(main)/while/body/jvp(relu1)/add"}
  %multiply.5 = f32[4]{0} multiply(f32[4]{0} %p0, f32[4]{0} %p0), metadata={op_name="jit(main)/tiny/mul"}
  %all-reduce.3 = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %add.2), replica_groups={}, metadata={op_name="jit(main)/allreduce"}
  ROOT %copy.4 = f32[64,64]{1,0} copy(f32[64,64]{1,0} %all-reduce.3)
}
'''

_FC1_FLOPS = 2.0 * 64 * 64 * 128          # 2*M*N*K
_FC1_BYTES = 64 * 64 * 4 + 2 * 64 * 128 * 4
_ADD_FLOPS = 64 * 64                       # one per output element
_ADD_BYTES = 3 * 64 * 64 * 4
_AR_BYTES = 64 * 64 * 4


# ---------------------------------------------------------------------------
# HLO parse
# ---------------------------------------------------------------------------

def test_layer_from_op_name_unwraps():
    f = roofline._layer_from_op_name
    assert f('jit(f)/jit(main)/fc1/dot_general') == 'fc1'
    assert f('jit(window_fn)/jit(main)/while/body/jvp(fc1)/dot_general') \
        == 'fc1'
    assert f('jit(f)/while/body/transpose(jvp(fc2))/reduce_sum') == 'fc2'
    assert f('jit(f)/jit(main)/relu1/jit(relu)/max') == 'relu1'
    # scan/update plumbing carries no layer
    assert f('jit(f)/jit(main)/while/body/add') is None
    assert f('/eq') is None
    assert f('params[0]') is None


def test_hlo_layer_costs_golden():
    costs = roofline.hlo_layer_costs(_SYNTH_HLO)
    assert costs['layers']['fc1'] == {'flops': _FC1_FLOPS,
                                      'bytes': _FC1_BYTES}
    assert costs['layers']['relu1'] == {'flops': _ADD_FLOPS,
                                        'bytes': _ADD_BYTES}
    assert costs['layers']['tiny']['flops'] == 4.0
    # free ops (parameter/copy) and the collective cost nothing here
    assert set(costs['layers']) == {'fc1', 'relu1', 'tiny'}
    assert costs['instr_layer'] == {'dot.1': 'fc1', 'add.2': 'relu1',
                                    'multiply.5': 'tiny'}
    assert costs['comm_instrs'] == {'all-reduce.3'}
    assert costs['comm_bytes'] == _AR_BYTES
    assert costs['comm_ops'] == {'all-reduce': float(_AR_BYTES)}
    assert costs['flops_total'] == _FC1_FLOPS + _ADD_FLOPS + 4.0


def test_note_hlo_keeps_largest_variant(roof_on):
    roofline.note_hlo('p', _SYNTH_HLO)
    small = _SYNTH_HLO.replace('f32[64,128]', 'f32[8,128]')
    roofline.note_hlo('p', small)          # tail-batch recompile
    prog = roofline._pick_step_program()
    assert prog['layers']['fc1']['flops'] == _FC1_FLOPS


def test_analysis_calibrates_parsed_split(roof_on):
    """XLA's own cost_analysis totals rescale the parsed per-layer
    split, so layer numbers always sum to what XLA reported."""
    parsed_total = _FC1_FLOPS + _ADD_FLOPS + 4.0
    roofline.note_hlo('p', _SYNTH_HLO,
                      analysis={'flops': 2 * parsed_total})
    d = roofline.analyze(step_time_ms=1.0, events=[])
    assert sum(r['flops'] for r in d['layers']) \
        == pytest.approx(2 * parsed_total, rel=1e-6)


# ---------------------------------------------------------------------------
# trace join + classification goldens
# ---------------------------------------------------------------------------

def _synthetic_events():
    """Two captured steps. Per step: dot.1 1000us, add.2 500us, the
    tiny op 1000us (clear of the collective), all-reduce 500us of
    which 300us overlap add.2 — 60% overall overlap."""
    events = []
    for step in range(2):
        base = step * 10000.0
        events += [
            {'ph': 'X', 'name': 'dot.1', 'ts': base, 'dur': 1000.0},
            {'ph': 'X', 'name': 'add.2', 'ts': base + 1000, 'dur': 500.0},
            {'ph': 'X', 'name': 'multiply.5', 'ts': base + 3000,
             'dur': 1000.0},
            {'ph': 'X', 'name': 'all-reduce.3', 'ts': base + 1200,
             'dur': 500.0},
        ]
    return events


def _set_peaks(monkeypatch, tflops, gbs):
    monkeypatch.setenv('MXTPU_PEAK_TFLOPS', str(tflops))
    monkeypatch.setenv('MXTPU_PEAK_HBM_GBS', str(gbs))
    flags.reload('MXTPU_PEAK_TFLOPS')
    flags.reload('MXTPU_PEAK_HBM_GBS')


def test_trace_join_classification_golden(roof_on, monkeypatch):
    """The deterministic end-to-end golden: synthetic HLO + synthetic
    trace + overridden peaks -> measured per-layer times, the three
    classifications, and the comm/overlap accounting."""
    _set_peaks(monkeypatch, 0.001, 0.1)    # 1e9 FLOP/s, 1e8 B/s
    roofline.note_hlo('p', _SYNTH_HLO)
    d = roofline.analyze(step_time_ms=3.0, events=_synthetic_events())
    assert d['source'] == 'measured'
    assert d['peaks'] == 'override'
    assert d['trace_steps'] == 2
    rows = {r['layer']: r for r in d['layers']}
    # fc1: roofline min = max(1048576/1e9, 81920/1e8)s = 1.049ms over
    # 1.0ms measured -> compute-bound at ~100% of roof
    assert rows['fc1']['class'] == 'compute-bound'
    assert rows['fc1']['time_ms'] == pytest.approx(1.0)
    assert rows['fc1']['roof_pct'] == pytest.approx(100.0)
    assert rows['fc1']['achieved_flops_s'] == pytest.approx(_FC1_FLOPS
                                                            / 1e-3)
    # relu1: bytes term dominates -> memory-bound (0.492ms roof over
    # 0.5ms measured)
    assert rows['relu1']['class'] == 'memory-bound'
    assert rows['relu1']['roof_pct'] == pytest.approx(98.3, abs=0.1)
    # tiny: 1ms measured for a 4-flop op -> far below both ceilings
    assert rows['tiny']['class'] == 'overhead-bound'
    assert rows['tiny']['roof_pct'] < 10.0
    # comm: 500us/step measured, 600/1000 overlapped, 16 KiB on wire
    comm = d['comm']
    assert comm['source'] == 'measured'
    assert comm['bytes'] == _AR_BYTES
    assert comm['time_ms'] == pytest.approx(0.5)
    assert comm['overlap_pct'] == pytest.approx(60.0)
    assert comm['pct_of_step'] == pytest.approx(100.0 * 0.5 / 3.0, abs=0.1)
    assert comm['ops'] == {'all-reduce': float(_AR_BYTES)}


def test_modeled_fallback_without_trace(roof_on, monkeypatch):
    """No capture -> the measured step time distributes across layers
    by roofline-minimum time, labeled 'modeled' (never presented as a
    measurement)."""
    _set_peaks(monkeypatch, 0.001, 0.1)
    roofline.note_hlo('p', _SYNTH_HLO)
    d = roofline.analyze(step_time_ms=10.0, events=[])
    assert d['source'] == 'modeled'
    assert sum(r['time_ms'] for r in d['layers']) == pytest.approx(10.0)
    assert d['comm']['source'] == 'modeled'


def test_comm_pct_grounds_cluster_classifier(roof_on, monkeypatch):
    """The straggler classifier's communication_bound verdict comes
    from the roofline's per-collective numbers, not inference."""
    from mxnet_tpu.telemetry import cluster
    _set_peaks(monkeypatch, 0.001, 0.1)
    roofline.note_hlo('p', _SYNTH_HLO)
    roofline.summarize(step_time_ms=3.0)
    pct = roofline.comm_pct_of_step()
    assert pct is not None and pct > 0
    assert cluster.classify(2.0, comm_pct=45.0) == 'communication_bound'
    assert cluster.classify(55.0, comm_pct=45.0) == 'input_bound'
    assert cluster.classify(2.0, comm_pct=5.0) == 'compute_bound'
    assert cluster.classify(2.0) == 'compute_bound'


# ---------------------------------------------------------------------------
# fit acceptance + no-op contract
# ---------------------------------------------------------------------------

def _mlp_fit():
    np.random.seed(0)
    mx.random.seed(0)
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name='fc1')
    act = mx.sym.Activation(fc1, act_type='relu', name='relu1')
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name='fc2')
    out = mx.sym.SoftmaxOutput(fc2, name='softmax')
    X = np.random.randn(32, 10).astype(np.float32)
    y = (np.random.rand(32) * 4).astype(int).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8,
                           label_name='softmax_label')
    mod = mx.mod.Module(out, context=mx.cpu())
    mod.fit(it, num_epoch=1, optimizer='sgd',
            optimizer_params=(('learning_rate', 0.1),))
    return mod


@pytest.mark.parametrize('roof', ['0', '1'])
def test_fit_acceptance_on_off(roof, tmp_path, monkeypatch):
    """=1: the summary carries a ranked bottleneck block where every
    named layer has a classification and an achieved/peak %, plus
    roofline.* gauges and a JSONL record. =0: no trace anywhere."""
    path = tmp_path / 'onoff.jsonl'
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(path))
    monkeypatch.setenv('MXTPU_ROOFLINE', roof)
    _reload_flags()
    telemetry._reset_for_tests()
    try:
        _mlp_fit()
        table = telemetry.write_summary(log=False)
        recs = _records(path)
        gauges = telemetry.snapshot()['gauges']
        roof_gauges = [n for n in gauges if n.startswith('roofline.')]
        if roof == '0':
            assert not roofline.enabled()
            assert '-- roofline' not in table
            assert roof_gauges == []
            assert not any(r['type'] == 'roofline' for r in recs)
        else:
            assert roofline.enabled()
            assert '-- roofline: fused_fit.window[softmax]' in table
            d = roofline.snapshot_roofline()
            layers = {r['layer']: r for r in d['layers']}
            for name in ('fc1', 'relu1', 'fc2', 'softmax'):
                assert name in layers, (name, sorted(layers))
                row = layers[name]
                assert row['class'] in ('compute-bound', 'memory-bound',
                                        'overhead-bound')
                assert row['roof_pct'] is not None
            assert gauges['roofline.layers'] == len(d['layers'])
            assert gauges['roofline.worst_layer'] == d['layers'][0]['layer']
            rr = [r for r in recs if r['type'] == 'roofline']
            assert rr and rr[-1]['layers'] == json.loads(
                json.dumps(d['layers']))
            summ = [r for r in recs if r['type'] == 'summary'][-1]
            assert summ.get('roofline')
    finally:
        telemetry._reset_for_tests()
        for f in _FLAGS:
            monkeypatch.delenv(f, raising=False)
        _reload_flags()


def test_roofline_off_lowering_byte_identical(tmp_path, monkeypatch):
    """Attribution is host-side HLO parsing — the lowered step program
    is byte-identical with the flag on or off (and with telemetry off
    entirely). The acceptance criterion's no-op contract."""
    import jax.numpy as jnp
    from mxnet_tpu import random as _random

    def _lowered_text(roof_on_):
        telemetry._reset_for_tests()
        monkeypatch.setenv('MXTPU_TELEMETRY', '1')
        monkeypatch.setenv('MXTPU_TELEMETRY_PATH',
                           str(tmp_path / ('r%s.jsonl' % roof_on_)))
        monkeypatch.setenv('MXTPU_ROOFLINE', roof_on_)
        _reload_flags()
        telemetry._reset_for_tests()
        np.random.seed(0)
        mx.random.seed(0)
        data = mx.sym.Variable('data')
        fc1 = mx.sym.FullyConnected(data, num_hidden=16, name='fc1')
        out = mx.sym.SoftmaxOutput(fc1, name='softmax')
        mod = mx.mod.Module(out, context=mx.cpu())
        mod.bind(data_shapes=[('data', (8, 10))],
                 label_shapes=[('softmax_label', (8,))])
        mod.init_params()
        ex = mod._exec_group.execs[0]
        arg_data = tuple(a._data for a in ex.arg_arrays)
        aux_data = tuple(a._data for a in ex.aux_arrays)
        heads = (jnp.ones((8, 16), jnp.float32),)
        return ex._fwd_bwd.lower(arg_data, aux_data, _random.next_key(),
                                 heads).as_text()

    try:
        assert _lowered_text('0') == _lowered_text('1')
    finally:
        telemetry._reset_for_tests()
        for f in _FLAGS:
            monkeypatch.delenv(f, raising=False)
        _reload_flags()


def test_off_no_parse_no_registry(tmp_path, monkeypatch):
    """MXTPU_ROOFLINE unset: the registrar hook is one cached-bool
    check — no HLO text is rendered, nothing lands in the store."""
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(tmp_path / 'x.jsonl'))
    monkeypatch.delenv('MXTPU_ROOFLINE', raising=False)
    _reload_flags()
    telemetry._reset_for_tests()

    class _Boom:
        def as_text(self):
            raise AssertionError('HLO rendered with roofline off')

    try:
        roofline.note_compiled('p', _Boom())
        assert roofline._pick_step_program() is None
        assert roofline.analyze() is None
        assert roofline.summarize() is None
        assert roofline.comm_pct_of_step() is None
    finally:
        telemetry._reset_for_tests()
        for f in _FLAGS:
            monkeypatch.delenv(f, raising=False)
        _reload_flags()


# ---------------------------------------------------------------------------
# peak table: unknown device warn-once + overrides
# ---------------------------------------------------------------------------

class _FakeDev:
    device_kind = 'warp9000'
    platform = 'warp'


def test_unknown_device_warns_once_and_publishes(roof_on, caplog):
    with caplog.at_level(logging.WARNING):
        p1 = tele_xla.device_peaks(_FakeDev())
        p2 = tele_xla.device_peaks(_FakeDev())
    assert p1['source'] == 'unknown' and p1['flops'] == 0.0
    assert p2['source'] == 'unknown'
    warns = [r for r in caplog.records
             if 'no peak table entry' in r.getMessage()]
    assert len(warns) == 1                 # once per process
    assert 'MXTPU_PEAK_TFLOPS' in warns[0].getMessage()
    assert telemetry.get_registry() \
        .gauge('roofline.peaks_unknown').value == 1
    # MFU skips unknown kinds — after the warn, not silently
    peak, kind = tele_xla.device_peak_flops(_FakeDev())
    assert peak == 0.0 and kind == 'warp9000'


def test_peak_overrides_rescue_unknown_device(roof_on, monkeypatch,
                                              caplog):
    _set_peaks(monkeypatch, 123.0, 456.0)
    with caplog.at_level(logging.WARNING):
        p = tele_xla.device_peaks(_FakeDev())
    assert p['source'] == 'override'
    assert p['flops'] == pytest.approx(123e12)
    assert p['hbm_bytes_s'] == pytest.approx(456e9)
    assert not [r for r in caplog.records
                if 'no peak table entry' in r.getMessage()]
    peak, _ = tele_xla.device_peak_flops(_FakeDev())
    assert peak == pytest.approx(123e12)   # MFU honors the override


def test_partial_override_keeps_mfu_contract(roof_on, monkeypatch):
    """A lone MXTPU_PEAK_HBM_GBS (refining roofline bandwidth) must not
    promote a nominal/unknown FLOP/s value to trusted-for-MFU status —
    and a half-unknown device still warns + publishes peaks_unknown."""
    monkeypatch.setenv('MXTPU_PEAK_HBM_GBS', '456.0')
    flags.reload('MXTPU_PEAK_TFLOPS')
    flags.reload('MXTPU_PEAK_HBM_GBS')
    # CPU: hbm overridden, flops still the nominal guess -> no MFU
    p = tele_xla.device_peaks()
    assert p['hbm_source'] == 'override'
    assert p['flops_source'] == 'nominal'
    assert p['hbm_bytes_s'] == pytest.approx(456e9)
    peak, _ = tele_xla.device_peak_flops()
    assert peak == 0.0                     # never MFU against a guess
    # unknown kind: the un-overridden denominator is still missing —
    # the warn-once + peaks_unknown gauge must fire, not be suppressed
    pu = tele_xla.device_peaks(_FakeDev())
    assert pu['flops_source'] == 'unknown' and pu['flops'] == 0.0
    assert pu['hbm_source'] == 'override'
    assert telemetry.get_registry() \
        .gauge('roofline.peaks_unknown').value == 1


def test_cpu_peaks_nominal_but_no_mfu():
    """CPU gets best-effort roofline denominators, but never an MFU
    against a guessed peak."""
    p = tele_xla.device_peaks()            # conftest pins the CPU mesh
    assert p['source'] == 'nominal'
    assert p['flops'] > 0 and p['hbm_bytes_s'] > 0
    peak, _ = tele_xla.device_peak_flops()
    assert peak == 0.0


# ---------------------------------------------------------------------------
# offline CLI round-trip
# ---------------------------------------------------------------------------

def test_roofline_report_matches_live_block(roof_on, monkeypatch,
                                            capsys):
    """JSONL -> tools/roofline_report.py reproduces the live summary
    block byte-for-byte (the acceptance criterion's round-trip)."""
    import roofline_report
    _set_peaks(monkeypatch, 0.001, 0.1)
    roofline.note_hlo('p', _SYNTH_HLO)
    telemetry.gauge('fit.steps')           # touch registry (no-op value)
    table = telemetry.write_summary(log=False)
    telemetry._state.sink.flush()
    lines = table.splitlines()
    i = next(j for j, ln in enumerate(lines)
             if ln.startswith('-- roofline'))
    j = next((k for k in range(i + 1, len(lines))
              if lines[k].startswith('-- ')), len(lines))
    live_block = '\n'.join(lines[i:j])
    assert roofline_report.main([str(roof_on)]) == 0
    out = capsys.readouterr().out
    assert out.rstrip('\n') == live_block
    # --json round-trips the analysis dict itself
    assert roofline_report.main([str(roof_on), '--json']) == 0
    d = json.loads(capsys.readouterr().out)
    assert d['layers'] and d['comm']['bytes'] == _AR_BYTES


def test_roofline_report_no_record(tmp_path, capsys):
    import roofline_report
    p = tmp_path / 'empty.jsonl'
    p.write_text('{"type": "start", "pid": 1}\n')
    assert roofline_report.main([str(p)]) == 1
