"""Gluon data pipeline (reference tests/python/unittest/
test_gluon_data.py): datasets, samplers, DataLoader batching,
transforms, RecordFileDataset.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, recordio


def test_array_dataset_and_len():
    X = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.float32)
    ds = gluon.data.ArrayDataset(nd.array(X), nd.array(y))
    assert len(ds) == 10
    xi, yi = ds[3]
    np.testing.assert_allclose(xi.asnumpy(), X[3])
    # 1-D label arrays index to host scalars (reference dataset.py:63)
    assert float(yi) == 3.0


def test_simple_dataset_transform():
    ds = gluon.data.SimpleDataset(list(range(6)))
    doubled = ds.transform(lambda x: 2 * x)
    assert [doubled[i] for i in range(6)] == [0, 2, 4, 6, 8, 10]
    first = ds.transform_first(lambda x: x + 100)
    assert first[2] == 102


def test_samplers():
    seq = list(gluon.data.SequentialSampler(5))
    assert seq == [0, 1, 2, 3, 4]
    rnd = list(gluon.data.RandomSampler(5))
    assert sorted(rnd) == [0, 1, 2, 3, 4]
    bs = list(gluon.data.BatchSampler(gluon.data.SequentialSampler(5), 2,
                                      last_batch='keep'))
    assert bs == [[0, 1], [2, 3], [4]]
    bd = list(gluon.data.BatchSampler(gluon.data.SequentialSampler(5), 2,
                                      last_batch='discard'))
    assert bd == [[0, 1], [2, 3]]
    br = list(gluon.data.BatchSampler(gluon.data.SequentialSampler(5), 2,
                                      last_batch='rollover'))
    assert br == [[0, 1], [2, 3]]


def test_dataloader_batches():
    X = np.arange(24, dtype=np.float32).reshape(12, 2)
    y = np.arange(12, dtype=np.float32)
    ds = gluon.data.ArrayDataset(nd.array(X), nd.array(y))
    loader = gluon.data.DataLoader(ds, batch_size=4)
    batches = list(loader)
    assert len(batches) == 3
    bx, by = batches[0]
    assert bx.shape == (4, 2) and by.shape == (4,)
    got = np.concatenate([b[0].asnumpy() for b in batches])
    np.testing.assert_allclose(got, X)


def test_dataloader_shuffle_covers_all():
    X = np.arange(16, dtype=np.float32)
    ds = gluon.data.SimpleDataset([nd.array(np.array([v])) for v in X])
    loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=True)
    got = np.sort(np.concatenate([b.asnumpy().ravel() for b in loader]))
    np.testing.assert_allclose(got, X)


def test_record_file_dataset():
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, 'data.rec')
        idx = os.path.join(d, 'data.idx')
        w = recordio.MXIndexedRecordIO(idx, path, 'w')
        payloads = [b'alpha', b'beta', b'gamma']
        for i, p in enumerate(payloads):
            w.write_idx(i, p)
        w.close()
        ds = gluon.data.RecordFileDataset(path)
        assert len(ds) == 3
        assert ds[1] == b'beta' or bytes(ds[1]) == b'beta'


def test_vision_mnist_synthetic():
    """Vision datasets fall back to deterministic synthetic data when
    offline (this image has zero egress)."""
    with tempfile.TemporaryDirectory() as d:
        ds = gluon.data.vision.MNIST(root=d, train=False)
        img, label = ds[0]
        assert tuple(img.shape) == (28, 28, 1)
        assert 0 <= int(label) <= 9
        loader = gluon.data.DataLoader(ds.transform_first(
            lambda x: x.astype('float32') / 255.0), batch_size=16)
        b, l = next(iter(loader))
        assert b.shape == (16, 28, 28, 1)


def test_gluon_utils_download_file_url_and_sha1(tmp_path):
    import hashlib
    from mxnet_tpu.gluon.utils import download, check_sha1
    src = tmp_path / 'payload.bin'
    src.write_bytes(b'mxnet-tpu-data')
    sha = hashlib.sha1(b'mxnet-tpu-data').hexdigest()
    dst = download('file://%s' % src, path=str(tmp_path / 'out.bin'),
                   sha1_hash=sha)
    assert check_sha1(dst, sha)
    # cached: second call with matching hash is a no-op
    assert download('file://%s' % src, path=dst, sha1_hash=sha) == dst
    with pytest.raises(OSError):
        download('file://%s' % src, path=str(tmp_path / 'bad.bin'),
                 sha1_hash='0' * 40)


def test_download_no_partial_file_on_mismatch(tmp_path):
    from mxnet_tpu.gluon.utils import download
    src = tmp_path / 'src.bin'
    src.write_bytes(b'payload')
    dst = tmp_path / 'sub' / 'dir' / 'dst.bin'   # dirs auto-created
    with pytest.raises(OSError):
        download('file://%s' % src, path=str(dst), sha1_hash='0' * 40)
    assert not dst.exists()                      # nothing truncated left
    assert not (tmp_path / 'sub' / 'dir' / 'dst.bin.part').exists()
    ok = download('file://%s' % src, path=str(dst))
    assert open(ok, 'rb').read() == b'payload'
