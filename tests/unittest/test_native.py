"""Native runtime (src/*.cc): engine ordering, storage pool, recordio,
profiler.

Mirrors the reference's C++ test strategy (SURVEY.md §4):
tests/cpp/engine/threaded_engine_test.cc runs randomized dependency
workloads and checks push/wait semantics; storage_test.cc checks
alloc/free reuse. Here the same properties are asserted through the
ctypes bindings.
"""
import os
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import _native, recordio
from mxnet_tpu.engine import Engine

pytestmark = pytest.mark.skipif(not _native.available(),
                                reason='native runtime not built')


def test_engine_serializes_writes():
    eng = Engine(num_workers=4)
    v = eng.new_var()
    out = []
    for i in range(200):
        eng.push(lambda i=i: out.append(i), mutable_vars=[v])
    eng.wait_for_all()
    assert out == list(range(200))


def test_engine_readers_run_between_writes():
    eng = Engine(num_workers=4)
    v = eng.new_var()
    log = []
    eng.push(lambda: log.append('w0'), mutable_vars=[v])
    for i in range(8):
        eng.push(lambda i=i: log.append('r%d' % i), const_vars=[v])
    eng.push(lambda: log.append('w1'), mutable_vars=[v])
    eng.wait_for_all()
    # w0 first, w1 last, all reads in between (any order)
    assert log[0] == 'w0' and log[-1] == 'w1'
    assert sorted(log[1:-1]) == ['r%d' % i for i in range(8)]


def test_engine_independent_ops_run_concurrently():
    eng = Engine(num_workers=4)
    barrier = threading.Barrier(4, timeout=10)

    def task():
        barrier.wait()  # only passes if 4 ops run at once

    for _ in range(4):
        eng.push(task, mutable_vars=[eng.new_var()])
    eng.wait_for_all()  # would deadlock-timeout if serialized


def test_engine_randomized_dependency_workload():
    # the threaded_engine_test.cc analog: random read/write sets over a
    # pool of vars; emulate expected per-var sequential state and compare
    eng = Engine(num_workers=8)
    nvars, nops = 10, 300
    rng = np.random.RandomState(0)
    vars_ = [eng.new_var() for _ in range(nvars)]
    state = [[] for _ in range(nvars)]  # appended to only under write
    lock = threading.Lock()

    expected = [[] for _ in range(nvars)]
    for op in range(nops):
        wset = sorted(rng.choice(nvars, rng.randint(1, 3), replace=False))
        rset = [i for i in sorted(rng.choice(nvars, rng.randint(0, 4),
                                             replace=False))
                if i not in wset]

        def task(op=op, wset=wset):
            for i in wset:
                state[i].append(op)

        eng.push(task, const_vars=[vars_[i] for i in rset],
                 mutable_vars=[vars_[i] for i in wset])
        for i in wset:
            expected[i].append(op)
    eng.wait_for_all()
    # writers to each var ran serialized in push order
    assert state == expected


def test_engine_wait_for_var():
    eng = Engine(num_workers=2)
    v = eng.new_var()
    done = []

    def slow():
        time.sleep(0.1)
        done.append(1)

    eng.push(slow, mutable_vars=[v])
    eng.wait_for_var(v)
    assert done == [1]


def test_engine_priority():
    # one worker: after the running op, highest-priority pending op runs
    # first (reference: grads pushed with priority=-index, kvstore.py:139)
    eng = Engine(num_workers=1)
    gate = threading.Event()
    order = []
    eng.push(lambda: gate.wait(5), mutable_vars=[eng.new_var()])
    for i, prio in enumerate([0, 5, 2]):
        eng.push(lambda i=i: order.append(i), priority=prio,
                 mutable_vars=[eng.new_var()])
    gate.set()
    eng.wait_for_all()
    assert order == [1, 2, 0]


def test_engine_naive_mode(monkeypatch):
    import mxnet_tpu.engine as em
    monkeypatch.setattr(em, '_engine_type', 'NaiveEngine')
    eng = Engine()  # 0 workers -> inline
    out = []
    eng.push(lambda: out.append(threading.get_ident()),
             mutable_vars=[eng.new_var()])
    assert out == [threading.get_ident()]  # ran on this thread, inline


def test_storage_pool_reuse():
    lib = _native.get_lib()
    import ctypes
    lib.MXTStorageReleaseAll()
    before = (ctypes.c_int64 * 4)()
    lib.MXTStorageStats(before)
    p = ctypes.c_void_p()
    _native.check_call(lib.MXTStorageAlloc(5000, ctypes.byref(p)))
    first = p.value
    _native.check_call(lib.MXTStorageFree(p))
    _native.check_call(lib.MXTStorageAlloc(4100, ctypes.byref(p)))
    # same 8192 bucket -> same block handed back
    assert p.value == first
    after = (ctypes.c_int64 * 4)()
    lib.MXTStorageStats(after)
    assert after[3] - before[3] == 1  # exactly one pool hit
    _native.check_call(lib.MXTStorageDirectFree(p))


def test_recordio_native_python_cross_compat(tmp_path):
    # native writer -> python reader and vice versa (byte-identical
    # framing with python/mxnet/recordio.py)
    path = str(tmp_path / 'a.rec')
    recs = [b'hello', b'', b'x' * 1237, b'tail']
    w = recordio.MXRecordIO(path, 'w')
    assert w._nh is not None  # native path active
    for r in recs:
        w.write(r)
    w.close()

    # pure-python read of the native-written file
    import struct
    got = []
    with open(path, 'rb') as f:
        while True:
            head = f.read(8)
            if len(head) < 8:
                break
            magic, lrec = struct.unpack('<II', head)
            assert magic == 0xced7230a
            n = lrec & 0x1fffffff
            got.append(f.read(n))
            f.read((4 - n % 4) % 4)
    assert got == recs

    # native read back
    r = recordio.MXRecordIO(path, 'r')
    assert [r.read() for _ in range(4)] == recs
    assert r.read() is None
    r.close()


def test_recordio_multipart_magic_escape(tmp_path):
    """Payloads containing the magic word at 4-aligned offsets are split
    into kBegin/kMiddle/kEnd chunks (dmlc recordio escape) and reassembled
    on read — native and python implementations interchangeable."""
    import struct
    magic = struct.pack('<I', 0xced7230a)
    recs = [
        magic * 3,                        # all-magic payload
        b'abcd' + magic + b'efgh',        # aligned embedded magic
        b'ab' + magic + b'cdef',          # unaligned — must NOT split
        b'x' * 4 + magic + b'y' * 7 + magic,  # magic at the tail
        b'plain',
    ]
    for use_native in (True, False):
        path = str(tmp_path / ('m%d.rec' % use_native))
        w = recordio.MXRecordIO(path, 'w')
        if not use_native:
            w.close()
            w._nh = None
            w._lib = None
            w.handle = open(path, 'wb')
            w.is_open = True
            w.writable = True
        for r in recs:
            w.write(r)
        w.close() if use_native else w.handle.close()
        for read_native in (True, False):
            r = recordio.MXRecordIO(path, 'r')
            if not read_native:
                if r._nh is not None:
                    r.close()
                r._nh = None
                r._lib = None
                r.handle = open(path, 'rb')
                r.is_open = True
                r.writable = False
            got = [r.read() for _ in range(len(recs))]
            assert got == recs, (use_native, read_native)
            assert r.read() is None
            if read_native:
                r.close()
            else:
                r.handle.close()


def test_indexed_recordio_native(tmp_path):
    path = str(tmp_path / 'b.rec')
    idx = str(tmp_path / 'b.idx')
    w = recordio.MXIndexedRecordIO(idx, path, 'w')
    for i in range(10):
        w.write_idx(i, b'rec%03d' % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx, path, 'r')
    for i in (7, 0, 3, 9):
        assert r.read_idx(i) == b'rec%03d' % i
    r.close()


def test_profiler_dump(tmp_path):
    from mxnet_tpu import profiler
    out = str(tmp_path / 'trace.json')
    profiler.profiler_set_config(mode='all', filename=out)
    profiler.profiler_set_state('run')
    eng = Engine(num_workers=2)
    v = eng.new_var()
    for _ in range(5):
        eng.push(lambda: time.sleep(0.001), mutable_vars=[v],
                 name='profiled_op')
    eng.wait_for_all()
    profiler.profiler_set_state('stop')
    profiler.dump_profile()
    import json
    with open(out) as f:
        trace = json.load(f)
    names = [e.get('name') for e in trace['traceEvents']]
    assert names.count('profiled_op') == 5

