"""Plugin tier: torch bridge (reference plugin/torch as TorchModule/
TorchCriterion ops) and the differentiable eager Custom path it rides.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.autograd as ag
from mxnet_tpu import nd

torch = pytest.importorskip('torch')
from mxnet_tpu.plugin.torch_bridge import TorchModule, TorchCriterion  # noqa: E402


def test_torch_module_forward_matches_torch():
    lin = torch.nn.Linear(4, 2)
    bridge = TorchModule(lin)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    got = bridge(nd.array(x)).asnumpy()
    want = lin(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_torch_module_from_source_string():
    bridge = TorchModule('nn.ReLU()')
    x = np.array([[-1.0, 2.0]], np.float32)
    np.testing.assert_allclose(bridge(nd.array(x)).asnumpy(), [[0.0, 2.0]])


def test_torch_module_backward_into_mx_graph():
    lin = torch.nn.Linear(3, 1, bias=False)
    with torch.no_grad():
        lin.weight[:] = torch.tensor([[1.0, 2.0, 3.0]])
    bridge = TorchModule(lin)
    x = nd.array(np.array([[1.0, 1.0, 1.0], [2.0, 0.0, 1.0]], np.float32))
    x.attach_grad()
    with ag.record():
        y = bridge(x * 2.0)          # mx op before the torch op
        loss = nd.sum(y)
    loss.backward()
    # dloss/dx = 2 * W summed over output rows
    np.testing.assert_allclose(x.grad.asnumpy(),
                               np.tile([[2.0, 4.0, 6.0]], (2, 1)),
                               rtol=1e-5)
    # torch side accumulated its own param grads too
    assert lin.weight.grad is not None


def test_torch_criterion():
    crit = TorchCriterion(torch.nn.MSELoss())
    pred = nd.array(np.array([1.0, 2.0], np.float32))
    target = nd.array(np.array([0.0, 0.0], np.float32))
    pred.attach_grad()
    with ag.record():
        l = crit(pred, target)
    l.backward()
    np.testing.assert_allclose(float(l.asnumpy()), 2.5, rtol=1e-6)
    # d/dpred mean((p-t)^2) = 2(p-t)/n
    np.testing.assert_allclose(pred.grad.asnumpy(), [1.0, 2.0], rtol=1e-6)


def test_custom_op_backward_eager():
    """The upgraded nd.Custom records on the tape (reference custom op
    autograd support)."""
    import mxnet_tpu.operator as op_mod

    class Square(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0] * in_data[0])

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0],
                        out_grad[0] * in_data[0] * 2.0)

    @op_mod.register('square_plugin_test')
    class SquareProp(op_mod.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Square()

    x = nd.array(np.array([1.0, 3.0], np.float32))
    x.attach_grad()
    with ag.record():
        y = nd.Custom(x, op_type='square_plugin_test')
        loss = nd.sum(y)
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), [1.0, 9.0])
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 6.0])


def test_torch_bn_stats_single_update_per_call():
    """Regression: the shape probe must not double-run stateful modules."""
    bn = torch.nn.BatchNorm1d(2)
    bridge = TorchModule(bn)
    x = nd.array(np.random.RandomState(0).randn(8, 2).astype(np.float32))
    with ag.record():
        bridge(x)
    assert int(bn.num_batches_tracked) == 1
    with ag.record():
        bridge(x)
    assert int(bn.num_batches_tracked) == 2
    assert hasattr(mx.plugin, 'torch_bridge')


def test_custom_op_dtype_follows_infer_type():
    import mxnet_tpu.operator as op_mod

    class ArgMax(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0],
                        nd.array(in_data[0].asnumpy().argmax(1)))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0],
                        nd.zeros(in_data[0].shape))

    @op_mod.register('argmax_dtype_test')
    class ArgMaxProp(op_mod.CustomOpProp):
        def infer_shape(self, in_shape):
            return in_shape, [[in_shape[0][0]]], []

        def infer_type(self, in_type):
            # int32: jax without x64 keeps integer arrays at 32 bits
            return in_type, [np.int32], []

        def create_operator(self, ctx, shapes, dtypes):
            return ArgMax()

    x = nd.array(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    out = nd.Custom(x, op_type='argmax_dtype_test')
    assert out.asnumpy().dtype == np.int32
    np.testing.assert_array_equal(out.asnumpy(), [1, 0])


def test_torch_bridge_predict_mode_gradients():
    """Regression: record(train_mode=False) must still backprop, with
    the module in eval mode (running stats untouched)."""
    bn = torch.nn.BatchNorm1d(3)
    seq = torch.nn.Sequential(torch.nn.Linear(3, 3), bn)
    bridge = TorchModule(seq)
    x = nd.array(np.random.RandomState(1).randn(4, 3).astype(np.float32))
    x.attach_grad()
    before = int(bn.num_batches_tracked)
    with ag.record(train_mode=False):
        y = bridge(x)
        s = nd.sum(y * y)
    s.backward()
    assert int(bn.num_batches_tracked) == before   # eval mode: no update
    assert np.abs(x.grad.asnumpy()).sum() > 0


def test_torch_bridge_inference_eval_mode():
    """Plain inference runs the module in eval mode (deterministic
    dropout, running-stat BN)."""
    drop = torch.nn.Dropout(0.9)
    bridge = TorchModule(drop)
    x = nd.array(np.ones((4, 8), np.float32))
    a = bridge(x).asnumpy()
    b = bridge(x).asnumpy()
    np.testing.assert_allclose(a, np.ones((4, 8)))   # identity in eval
    np.testing.assert_allclose(a, b)


def test_torch_bridge_int_output_dtype():
    class ArgMaxMod(torch.nn.Module):
        def forward(self, x):
            return x.argmax(1)

    bridge = TorchModule(ArgMaxMod())
    x = nd.array(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    out = bridge(x)
    assert np.issubdtype(out.asnumpy().dtype, np.integer)
    np.testing.assert_array_equal(out.asnumpy(), [1, 0])


import mxnet_tpu.operator as op_mod


class _Counter(op_mod.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        aux[0][:] = aux[0] + 1.0
        self.assign(out_data[0], req[0], in_data[0])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0])


@op_mod.register('aux_counter_test')
class _CounterProp(op_mod.CustomOpProp):
    def list_auxiliary_states(self):
        return ['count']

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], [[1]]

    def create_operator(self, ctx, shapes, dtypes):
        return _Counter()


def test_custom_op_aux_states():
    x = nd.array(np.array([1.0, 2.0], np.float32))
    out = nd.Custom(x, op_type='aux_counter_test')
    np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])


def test_torch_bridge_integer_input_inference():
    """Regression: integer inputs (Embedding indices) must work at
    inference — requires_grad only applies to recording float tensors."""
    emb = torch.nn.Embedding(10, 4)
    bridge = TorchModule(emb)
    idx = nd.array(np.array([1, 5, 7], np.float32)).astype('int32')
    out = bridge(idx)
    assert out.shape == (3, 4)
    want = emb(torch.tensor([1, 5, 7])).detach().numpy()
    np.testing.assert_allclose(out.asnumpy(), want, rtol=1e-5)
    # and under record(): grads flow to the float path / torch params
    x = nd.array(np.random.RandomState(0).randn(3, 4).astype(np.float32))
    x.attach_grad()
    with ag.record():
        y = bridge(idx) * x
        s = nd.sum(y)
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-5)


def test_custom_symbolic_aux_states():
    """Aux plumbing works in the symbolic executor too."""
    s = mx.sym.Custom(mx.sym.Variable('x'), op_type='aux_counter_test',
                      num_args=1)
    ex = s.bind(mx.cpu(), {'x': nd.array(np.array([3.0], np.float32))})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [3.0])


def test_custom_op_persistent_aux_states():
    """Reference custom.cc input layout: trailing NDArrays are aux —
    caller-owned and persistent across calls."""
    count = nd.zeros((1,))
    x = nd.array(np.array([1.0, 2.0], np.float32))
    for i in range(3):
        out = nd.Custom(x, count, op_type='aux_counter_test')
        np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])
    np.testing.assert_allclose(count.asnumpy(), [3.0])


def test_custom_symbolic_partial_aux_rejected():
    """Trailing inputs map to aux slots by position, so passing a
    partial aux suffix would misbind silently — it must raise."""
    import pytest

    @op_mod.register('two_aux_test')
    class _TwoAuxProp(op_mod.CustomOpProp):
        def list_auxiliary_states(self):
            return ['s1', 's2']

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], [[1], [1]]

        def create_operator(self, ctx, shapes, dtypes):
            return _Counter()

    x = mx.sym.Variable('x')
    with pytest.raises(ValueError, match='all 2 aux states or none'):
        mx.sym.Custom(x, mx.sym.Variable('s1v'),
                      op_type='two_aux_test', num_args=1)
    with pytest.raises(ValueError, match='all 2 aux states or none'):
        mx.sym.Custom(data=x, s1=mx.sym.Variable('s1v'),
                      op_type='two_aux_test')
    # all aux or none both compose fine
    assert mx.sym.Custom(x, op_type='two_aux_test',
                         num_args=1).list_arguments() == ['x']
    both = mx.sym.Custom(x, mx.sym.Variable('s1v'), mx.sym.Variable('s2v'),
                         op_type='two_aux_test', num_args=1)
    assert both.list_arguments() == ['x', 's1v', 's2v']
