"""telemetry/watchdog.py — the hang watchdog.

Contracts under test:
- flags off = NO thread ever, note_progress is one cached-bool no-op,
  and the lowered step program is byte-identical (the telemetry
  off-contract pattern — trivially: nothing is ever traced);
- a stall past MXTPU_WATCHDOG_SECS trips ONE hang incident: the
  counter, the JSONL ``hang`` record with all-thread stacks + the last
  progress mark, and the /healthz flip to a 503 ``hung`` digest;
- progress resuming clears the hang state (healthz back to 200) and
  re-arms for a later stall;
- suspend() (fit's exit path) disarms so post-training idle time can
  never false-trip;
- abort hooks run (bounded) before an action=abort exit — the
  checkpointer's drain path rides this.

The action=abort exit itself (os._exit(85)) is a whole-process
contract: tests/unittest/test_resilience.py drives it under the real
supervisor in the chaos lane.
"""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.config import flags
from mxnet_tpu.telemetry import watchdog, serve

_WD_FLAGS = ('MXTPU_WATCHDOG_SECS', 'MXTPU_WATCHDOG_ACTION',
             'MXTPU_TELEMETRY', 'MXTPU_TELEMETRY_PATH')


def _reload():
    for f in _WD_FLAGS:
        flags.reload(f)


def _wd_threads():
    return [t for t in threading.enumerate()
            if t.name == 'mxtpu-watchdog' and t.is_alive()]


@pytest.fixture
def wd_off(monkeypatch):
    for f in _WD_FLAGS:
        monkeypatch.delenv(f, raising=False)
    _reload()
    telemetry._reset_for_tests()
    yield
    telemetry._reset_for_tests()
    _reload()


@pytest.fixture
def wd_on(tmp_path, monkeypatch):
    """Watchdog armed at 0.25s (warn) with telemetry into a tmp log."""
    monkeypatch.setenv('MXTPU_WATCHDOG_SECS', '0.25')
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(tmp_path / 't.jsonl'))
    _reload()
    telemetry._reset_for_tests()
    yield {'tele_path': tmp_path / 't.jsonl'}
    telemetry._reset_for_tests()
    for f in _WD_FLAGS:
        monkeypatch.delenv(f, raising=False)
    _reload()


def _wait_for(cond, timeout=5.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_flags_off_no_thread_no_op(wd_off):
    assert not watchdog.enabled()
    watchdog.note_progress('fit.step')     # must be a no-op
    assert not _wd_threads()
    assert watchdog.hang_info() is None
    assert watchdog.snapshot_watchdog() is None


def test_armed_but_idle_has_no_thread(wd_on):
    """The monitor thread only starts at the FIRST progress mark."""
    assert watchdog.enabled()
    assert not _wd_threads()


def test_stall_trips_incident_and_healthz_flips(wd_on):
    telemetry.enabled()                     # open the sink
    watchdog.note_progress('fit.step')
    assert _wd_threads()
    assert _wait_for(lambda: watchdog.hang_info() is not None)
    hi = watchdog.hang_info()
    assert hi['last_progress'] == 'fit.step'
    assert hi['stalled_s'] >= 0.25 and hi['threshold_s'] == 0.25
    assert 'MainThread' in hi['stacks']
    assert telemetry.get_registry().counter('watchdog.hangs').value == 1
    ok, body = serve.healthz_payload()
    assert not ok and body['status'] == 'hung'
    assert body['hang']['last_progress'] == 'fit.step'
    # the JSONL record landed (the trip flushes the sink)
    recs = [json.loads(ln) for ln in open(wd_on['tele_path'])
            if ln.strip()]
    hangs = [r for r in recs if r['type'] == 'hang']
    assert len(hangs) == 1
    assert hangs[0]['stacks'] and hangs[0]['action'] == 'warn'
    # progress resumes -> the hang clears and healthz goes green
    watchdog.note_progress('fit.step')
    assert watchdog.hang_info() is None
    ok, body = serve.healthz_payload()
    assert ok and body['status'] == 'ok'
    # ...but the last digest stays available for reports
    assert watchdog.snapshot_watchdog()['stalled_s'] >= 0.25
    # and a LATER stall trips again (re-armed)
    assert _wait_for(lambda: watchdog.hang_info() is not None)
    assert telemetry.get_registry().counter('watchdog.hangs').value == 2


def test_suspend_prevents_false_trip(wd_on):
    watchdog.note_progress('fit.step')
    watchdog.suspend()
    time.sleep(0.7)
    assert watchdog.hang_info() is None
    assert telemetry.get_registry().counter('watchdog.hangs').value == 0
    # the next mark re-arms
    watchdog.note_progress('fit.step')
    assert _wait_for(lambda: watchdog.hang_info() is not None)


def test_suspend_clears_active_hang(wd_on):
    """fit unwinding past a warn-mode hang must not leave /healthz
    stuck at 503 'hung' forever: suspend() clears the active digest."""
    watchdog.note_progress('fit.step')
    assert _wait_for(lambda: watchdog.hang_info() is not None)
    watchdog.suspend()
    assert watchdog.hang_info() is None
    ok, body = serve.healthz_payload()
    assert ok and body['status'] == 'ok'
    # the digest survives for reports, marked inactive
    assert watchdog.snapshot_watchdog()['active'] is False


def test_abort_hooks_run_before_exit_path(wd_on, monkeypatch):
    """The abort path runs registered hooks (bounded) before os._exit;
    patch the exit so the trip is observable in-process."""
    monkeypatch.setenv('MXTPU_WATCHDOG_ACTION', 'abort')
    _reload()
    telemetry._reset_for_tests()
    ran = []
    exited = []
    monkeypatch.setattr(watchdog.os, '_exit',
                        lambda code: (exited.append(code),
                                      watchdog.suspend()))
    watchdog.add_abort_hook(lambda: ran.append('drain'))
    watchdog.note_progress('fit.step')
    assert _wait_for(lambda: exited != [])
    assert exited == [watchdog.HANG_EXIT_CODE] and ran == ['drain']


def test_fit_marks_and_suspends(wd_on):
    """A real fit feeds marks (thread comes up) and suspends at exit —
    no false trip afterwards, no incident during the run."""
    data = mx.sym.Variable('data')
    fc = mx.sym.FullyConnected(data, num_hidden=4, name='fc')
    sym = mx.sym.SoftmaxOutput(fc, name='softmax')
    np.random.seed(0)
    X = np.random.randn(32, 6).astype(np.float32)
    y = (np.random.rand(32) * 4).astype(int).astype(np.float32)
    mod = mx.mod.Module(sym, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=8, label_name='softmax_label')
    mod.fit(it, num_epoch=1, optimizer='sgd',
            optimizer_params=(('learning_rate', 0.1),))
    assert _wd_threads()
    assert telemetry.get_registry().counter('watchdog.hangs').value == 0
    # fit suspended the monitor: idling past the threshold is clean
    time.sleep(0.7)
    assert watchdog.hang_info() is None


def test_score_and_predict_disarm_on_exit(wd_on):
    """Standalone eval after fit must not leave the watchdog armed:
    score()/predict() marks re-arm it, their exit disarms it — long
    post-eval host work cannot false-trip (or be abort-killed)."""
    data = mx.sym.Variable('data')
    fc = mx.sym.FullyConnected(data, num_hidden=4, name='fc')
    sym = mx.sym.SoftmaxOutput(fc, name='softmax')
    np.random.seed(0)
    X = np.random.randn(32, 6).astype(np.float32)
    y = (np.random.rand(32) * 4).astype(int).astype(np.float32)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.fit(mx.io.NDArrayIter(X, y, batch_size=8,
                              label_name='softmax_label'),
            num_epoch=1, optimizer='sgd',
            optimizer_params=(('learning_rate', 0.1),))
    mod.score(mx.io.NDArrayIter(X, y, batch_size=8,
                                label_name='softmax_label'), 'acc')
    time.sleep(0.7)
    assert watchdog.hang_info() is None
    mod.predict(mx.io.NDArrayIter(X, y, batch_size=8,
                                  label_name='softmax_label'))
    time.sleep(0.7)
    assert watchdog.hang_info() is None
    assert telemetry.get_registry().counter('watchdog.hangs').value == 0


def test_lowered_program_byte_identical_with_watchdog(wd_off, monkeypatch):
    """The watchdog is purely host-side: the executor's lowered step
    program is byte-identical with the flag on or off (the same
    off-contract assertion the health sentinels keep)."""
    import jax

    def lower_text():
        telemetry._reset_for_tests()
        data = mx.sym.Variable('data')
        fc = mx.sym.FullyConnected(data, num_hidden=4, name='fc')
        sym = mx.sym.SoftmaxOutput(fc, name='softmax')
        mod = mx.mod.Module(sym, context=mx.cpu())
        mod.bind(data_shapes=[('data', (8, 6))],
                 label_shapes=[('softmax_label', (8,))], for_training=True)
        mod.init_params(initializer=mx.init.Uniform(0.01))
        e = mod._exec_group.execs[0]
        args = tuple(a._data for a in e.arg_dict.values())
        auxs = tuple(a._data for a in e.aux_dict.values())
        key = jax.random.PRNGKey(0)
        return jax.jit(e._run_eager, static_argnums=(3,)).lower(
            args, auxs, key, True).as_text()

    off = lower_text()
    monkeypatch.setenv('MXTPU_WATCHDOG_SECS', '60')
    _reload()
    on = lower_text()
    assert on == off
