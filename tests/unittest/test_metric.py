"""Metric family vs numpy oracles.

Reference: tests/python/unittest/test_metric.py plus the metric
behaviors asserted throughout the reference's training tests
(python/mxnet/metric.py:1132).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric as mtr
from mxnet_tpu import nd


def _m(name, **kw):
    m = mtr.create(name, **kw)
    assert m.name is not None
    return m


def test_create_by_name_and_aliases():
    for name in ['acc', 'accuracy', 'top_k_accuracy', 'f1', 'mae', 'mse',
                 'rmse', 'ce', 'nll_loss', 'pearsonr', 'loss']:
        m = mtr.create(name) if name != 'top_k_accuracy' else \
            mtr.create(name, top_k=2)
        assert isinstance(m, mtr.EvalMetric)


def test_accuracy():
    pred = nd.array(np.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]],
                             np.float32))
    label = nd.array(np.array([1, 0, 0], np.float32))
    m = _m('acc')
    m.update([label], [pred])
    name, val = m.get()
    assert name == 'accuracy'
    assert abs(val - 2.0 / 3.0) < 1e-6
    m.reset()
    assert np.isnan(m.get()[1])


def test_top_k_accuracy():
    pred = nd.array(np.array([[0.1, 0.2, 0.7],
                              [0.5, 0.4, 0.1],
                              [0.35, 0.4, 0.25]], np.float32))
    label = nd.array(np.array([1, 1, 0], np.float32))
    m = mtr.create('top_k_accuracy', top_k=2)
    m.update([label], [pred])
    # top-2 sets: {2,1}, {0,1}, {1,0} -> labels 1,1,0 all hit
    assert abs(m.get()[1] - 1.0) < 1e-6
    # top_k=1 is rejected (reference: "use Accuracy instead")
    with pytest.raises(AssertionError):
        mtr.create('top_k_accuracy', top_k=1)
    assert m.get()[0] == 'top_k_accuracy_2'


def test_f1():
    pred = nd.array(np.array([[0.8, 0.2], [0.3, 0.7], [0.4, 0.6],
                              [0.9, 0.1]], np.float32))
    label = nd.array(np.array([0, 1, 0, 1], np.float32))
    m = _m('f1')
    m.update([label], [pred])
    # predictions: 0,1,1,0 vs labels 0,1,0,1 -> tp=1 fp=1 fn=1
    prec = rec = 0.5
    want = 2 * prec * rec / (prec + rec)
    assert abs(m.get()[1] - want) < 1e-6


def test_perplexity():
    probs = np.array([[0.5, 0.5], [0.9, 0.1]], np.float32)
    label = np.array([0, 0], np.float32)
    m = mtr.create('Perplexity', ignore_label=None)
    m.update([nd.array(label)], [nd.array(probs)])
    want = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(m.get()[1] - want) < 1e-4


def test_perplexity_ignore_label():
    probs = np.array([[0.5, 0.5], [0.9, 0.1]], np.float32)
    label = np.array([0, -1], np.float32)
    m = mtr.create('Perplexity', ignore_label=-1)
    m.update([nd.array(label)], [nd.array(probs)])
    want = np.exp(-np.log(0.5))
    assert abs(m.get()[1] - want) < 1e-4


def test_regression_metrics():
    pred = np.array([[1.0], [2.0], [3.0]], np.float32)
    label = np.array([[1.5], [2.0], [2.0]], np.float32)
    cases = {
        'mae': np.abs(pred - label).mean(),
        'mse': ((pred - label) ** 2).mean(),
        'rmse': np.sqrt(((pred - label) ** 2).mean()),
    }
    for name, want in cases.items():
        m = _m(name)
        m.update([nd.array(label)], [nd.array(pred)])
        assert abs(m.get()[1] - want) < 1e-5, name


def test_cross_entropy():
    probs = np.array([[0.2, 0.8], [0.6, 0.4]], np.float32)
    label = np.array([1, 0], np.float32)
    m = _m('ce')
    m.update([nd.array(label)], [nd.array(probs)])
    want = -(np.log(0.8) + np.log(0.6)) / 2
    assert abs(m.get()[1] - want) < 1e-5


def test_pearson_correlation():
    rng = np.random.RandomState(0)
    pred = rng.randn(20).astype(np.float32)
    label = (2 * pred + 0.1 * rng.randn(20)).astype(np.float32)
    m = _m('pearsonr')
    m.update([nd.array(label)], [nd.array(pred)])
    want = np.corrcoef(pred, label)[0, 1]
    assert abs(m.get()[1] - want) < 1e-3


def test_loss_metric():
    m = _m('loss')
    m.update(None, [nd.array(np.array([1.0, 3.0], np.float32))])
    assert abs(m.get()[1] - 2.0) < 1e-6


def test_composite():
    m = mtr.CompositeEvalMetric([mtr.create('acc'), mtr.create('mse')])
    pred = nd.array(np.array([[0.3, 0.7], [0.9, 0.1]], np.float32))
    label = nd.array(np.array([1, 0], np.float32))
    m.update([label], [pred])
    names, vals = m.get()
    assert 'accuracy' in names[0]
    assert abs(vals[0] - 1.0) < 1e-6


def test_custom_metric_and_np():
    def my_err(label, pred):
        return float(np.abs(label - pred.argmax(1)).mean())

    m = mtr.np(my_err, name='myerr')
    pred = nd.array(np.array([[0.3, 0.7], [0.9, 0.1]], np.float32))
    label = nd.array(np.array([1, 1], np.float32))
    m.update([label], [pred])
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_metric_str_and_multiple_updates():
    m = _m('acc')
    for _ in range(3):
        m.update([nd.array(np.array([0.0], np.float32))],
                 [nd.array(np.array([[0.9, 0.1]], np.float32))])
    assert m.num_inst == 3
    assert abs(m.get()[1] - 1.0) < 1e-6
    assert 'accuracy' in str(m).lower() or 'EvalMetric' in str(m)
