"""KVStore tests: local/device tier invariants + the distributed tier
driven through tools/launch.py as real worker/server/scheduler processes.

Reference: tests/python/unittest/test_kvstore.py (local aggregation over
list-of-NDArrays as pseudo-devices) and tests/nightly/dist_sync_kvstore.py
via tests/nightly/test_all.sh:55 (`launch.py -n 4 python ...`).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.join(os.path.dirname(__file__), '..', '..')

shape = (4, 4)
keys = [5, 7, 9]


def init_kv(kv_type='local'):
    kv = mx.kv.create(kv_type)
    kv.init(3, mx.nd.zeros(shape))
    kv.init(keys, [mx.nd.zeros(shape)] * len(keys))
    return kv


def check_diff_to_scalar(ndarray, number):
    assert np.allclose(ndarray.asnumpy(), number), (
        ndarray.asnumpy(), number)


class TestLocalKVStore:
    def test_single_kv_pair(self):
        kv = init_kv()
        kv.push(3, mx.nd.ones(shape) * 4)
        out = mx.nd.zeros(shape)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 4)

    def test_list_kv_pair(self):
        kv = init_kv()
        kv.push(keys, [mx.nd.ones(shape) * 4] * len(keys))
        out = [mx.nd.zeros(shape)] * len(keys)
        kv.pull(keys, out=out)
        for o in out:
            check_diff_to_scalar(o, 4)

    def test_aggregator(self):
        """List-of-NDArrays as pseudo-devices (reference test_kvstore.py)."""
        kv = init_kv()
        num_devs = 4
        vals = [mx.nd.ones(shape)] * num_devs
        kv.push(3, vals)
        out = [mx.nd.zeros(shape) for _ in range(num_devs)]
        kv.pull(3, out=out)
        for o in out:
            check_diff_to_scalar(o, num_devs)
        # multiple keys
        vv = [[mx.nd.ones(shape) * 2] * num_devs] * len(keys)
        kv.push(keys, vv)
        outs = [[mx.nd.zeros(shape) for _ in range(num_devs)]
                for _ in keys]
        kv.pull(keys, out=outs)
        for olist in outs:
            for o in olist:
                check_diff_to_scalar(o, 2 * num_devs)

    def test_updater(self):
        kv = init_kv()
        kv.set_updater(lambda key, recv, stored: stored.__iadd__(recv))
        kv.push(3, mx.nd.ones(shape))
        kv.push(3, mx.nd.ones(shape))
        out = mx.nd.zeros(shape)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 2)

    def test_optimizer_updates(self):
        kv = init_kv()
        kv.set_optimizer(mx.optimizer.create('test', rescale_grad=3.0))
        kv.push(3, mx.nd.ones(shape))
        out = mx.nd.zeros(shape)
        kv.pull(3, out=out)
        check_diff_to_scalar(out, 3)

    def test_get_type(self):
        assert mx.kv.create('device').type == 'device'


class TestDistKVStore:
    def test_standalone_dist_sync(self):
        """create('dist_sync') with no launcher: in-process 1-worker
        cluster (the round-1 dangling import, now real)."""
        kv = mx.kv.create('dist_sync')
        assert kv.rank == 0 and kv.num_workers == 1
        kv.init('w', mx.nd.zeros(shape))
        kv.push('w', mx.nd.ones(shape) * 2)
        out = mx.nd.zeros(shape)
        kv.pull('w', out=out)
        check_diff_to_scalar(out, 2)
        kv.barrier()

    def test_standalone_dist_async(self):
        """dist_async: pushes apply immediately without the sync barrier
        (reference kvstore_dist_server.h:389-401 async path) and a
        server-side optimizer accumulates each push as it lands."""
        kv = mx.kv.create('dist_async')
        assert kv.type == 'dist_async'
        kv.set_optimizer(mx.optimizer.Test(rescale_grad=1.0))
        kv.init('a', mx.nd.zeros(shape))
        out = mx.nd.zeros(shape)
        for i in range(3):
            kv.push('a', mx.nd.ones(shape))
            kv.pull('a', out=out)
        # Test optimizer: weight += grad each push; async → applied by
        # the time the same worker's pull returns
        check_diff_to_scalar(out, 3)

    def test_dead_node_query_local_is_zero(self):
        kv = mx.kv.create('local')
        assert kv.num_dead_node(node_id=6) == 0
        kvd = mx.kv.create('dist_sync')
        # single live in-process cluster: nothing dead at a sane timeout
        assert kvd.num_dead_node(node_id=6, timeout=60) == 0

    @pytest.mark.slow
    def test_launch_4_workers(self):
        """Real multi-process cluster: 4 workers, 2 servers, 1 scheduler
        (reference test_all.sh:55)."""
        env = dict(os.environ)
        env.pop('DMLC_ROLE', None)
        env['JAX_PLATFORMS'] = 'cpu'
        env.pop('XLA_FLAGS', None)  # workers don't need the 8-dev mesh
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'launch.py'),
             '-n', '4', '-s', '2', sys.executable,
             os.path.join(REPO, 'tests', 'dist', 'dist_sync_kvstore.py')],
            env=env, capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, (r.stdout[-4000:], r.stderr[-4000:])
        assert r.stdout.count('all dist_sync invariants passed') == 4, \
            r.stdout
