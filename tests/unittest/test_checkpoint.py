"""parallel/checkpoint.py — the sharded (orbax) checkpoint tier.

Contracts under test (the tier had zero tests before the resilience
arc made it the substrate of module/checkpointing.py):
- save/restore round-trip on a SHARDED state tree: every leaf comes
  back value-identical, on the same NamedSharding, without the full
  state materializing on one device;
- ``latest_step`` / ``all_steps`` ordering;
- ``max_to_keep`` pruning deletes the oldest committed steps;
- restore-into-template fidelity: dtype and sharding come from the
  TEMPLATE arrays (bf16 stays bf16, replicated stays replicated);
- the ``meta`` JSON sidecar rides the same atomic commit
  (save(meta=...) / restore_with_meta);
- ``delete_step`` removes a step from the catalog.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mxnet_tpu.parallel import checkpoint as ckpt


def _mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ('dp',))


def _state(mesh, scale=1.0):
    """A small train-state-shaped tree: dp-sharded params, replicated
    scalar-ish state, an integer step counter."""
    sharded = NamedSharding(mesh, P('dp'))
    repl = NamedSharding(mesh, P())
    return {
        'params': {
            'w': jax.device_put(
                jnp.arange(16 * 4, dtype=jnp.float32).reshape(16, 4)
                * scale, sharded),
            'b': jax.device_put(jnp.ones((4,), jnp.float32) * scale,
                                repl),
        },
        'opt': {'mom': jax.device_put(jnp.full((16, 4), 0.5 * scale,
                                               jnp.float32), sharded)},
        'step': jnp.asarray(7, jnp.int32),
    }


def test_save_restore_round_trip_sharded(tmp_path):
    mesh = _mesh()
    mngr = ckpt.manager(tmp_path, max_to_keep=3)
    state = _state(mesh)
    ckpt.save(mngr, 10, state, wait=True)
    assert ckpt.latest_step(mngr) == 10

    restored = ckpt.restore(mngr, template=state, step=10)
    flat_a, tree_a = jax.tree_util.tree_flatten(state)
    flat_b, tree_b = jax.tree_util.tree_flatten(restored)
    assert tree_a == tree_b
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype
        # restore-into-template: the shard layout comes back too
        assert b.sharding.is_equivalent_to(a.sharding, a.ndim)


def test_latest_and_all_steps(tmp_path):
    mesh = _mesh()
    mngr = ckpt.manager(tmp_path, max_to_keep=5)
    state = _state(mesh)
    for s in (1, 3, 8):
        ckpt.save(mngr, s, state, wait=True)
    assert ckpt.all_steps(mngr) == [1, 3, 8]
    assert ckpt.latest_step(mngr) == 8
    # a stale (non-monotonic) step is refused by the manager, not
    # silently committed over the newer state
    assert not ckpt.save(mngr, 2, state, wait=True)
    assert ckpt.all_steps(mngr) == [1, 3, 8]


def test_max_to_keep_prunes_oldest(tmp_path):
    mesh = _mesh()
    mngr = ckpt.manager(tmp_path, max_to_keep=2)
    state = _state(mesh)
    for s in (1, 2, 3, 4):
        ckpt.save(mngr, s, state, wait=True)
    assert ckpt.all_steps(mngr) == [3, 4]
    # the pruned steps are gone from disk, not just the catalog
    kept = {p.name for p in tmp_path.iterdir() if p.is_dir()}
    assert '1' not in kept and '2' not in kept


def test_restore_into_template_dtype_and_sharding(tmp_path):
    """The template's dtype/sharding win: a bf16 dp-sharded template
    restores the saved values as bf16 on the dp sharding, regardless
    of how the catalog stored them."""
    mesh = _mesh()
    mngr = ckpt.manager(tmp_path, max_to_keep=3)
    state = _state(mesh)
    ckpt.save(mngr, 1, state, wait=True)

    sharded = NamedSharding(mesh, P('dp'))
    template = jax.tree_util.tree_map(lambda x: x, state)
    template['params']['w'] = jax.device_put(
        jnp.zeros((16, 4), jnp.bfloat16), sharded)
    restored = ckpt.restore(mngr, template=template, step=1)
    w = restored['params']['w']
    assert w.dtype == jnp.bfloat16
    assert w.sharding.is_equivalent_to(sharded, 2)
    np.testing.assert_array_equal(
        np.asarray(w, np.float32),
        np.asarray(state['params']['w'], np.float32))


def test_meta_sidecar_round_trip(tmp_path):
    mesh = _mesh()
    mngr = ckpt.manager(tmp_path, max_to_keep=3)
    state = _state(mesh)
    meta = {'epoch': 2, 'step_in_epoch': 5,
            'rng_host': {'key_values': [1, 2], 'key_dtype': 'uint32'},
            'metric': [['Accuracy', 0.75, 32]]}
    ckpt.save(mngr, 4, state, wait=True, meta=meta)

    restored, meta_back = ckpt.restore_with_meta(mngr, state, 4)
    assert meta_back == meta
    np.testing.assert_array_equal(
        np.asarray(restored['params']['w']),
        np.asarray(state['params']['w']))
    assert restored['params']['w'].sharding.is_equivalent_to(
        state['params']['w'].sharding, 2)


def test_delete_step(tmp_path):
    mesh = _mesh()
    mngr = ckpt.manager(tmp_path, max_to_keep=5)
    state = _state(mesh)
    for s in (1, 2):
        ckpt.save(mngr, s, state, wait=True)
    ckpt.delete_step(mngr, 1)
    assert ckpt.all_steps(mngr) == [2]
    with pytest.raises(Exception):
        ckpt.restore(mngr, template=state, step=1)


def test_restore_without_checkpoint_raises(tmp_path):
    mngr = ckpt.manager(tmp_path)
    with pytest.raises(FileNotFoundError):
        ckpt.restore(mngr, template={'x': jnp.zeros(2)})


# ---------------------------------------------------------------------------
# reshard-on-restore: N devices -> M devices, both directions
# ---------------------------------------------------------------------------

def _mesh_n(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ('dp',))


@pytest.mark.parametrize('n_save,n_restore', [(8, 4), (4, 8)])
def test_reshard_restore_across_mesh_sizes(tmp_path, n_save, n_restore):
    """A checkpoint saved on an N-device mesh restores onto an M-device
    template: GLOBAL shapes (recorded in the meta sidecar) validate,
    values round-trip exactly, and every array lands on the NEW mesh's
    sharding — the property that makes host loss 'relaunch smaller'
    instead of 'wait for the dead host'."""
    saved = _state(_mesh_n(n_save), scale=3.0)
    mngr = ckpt.manager(tmp_path, max_to_keep=3)
    ckpt.save(mngr, 5, saved, wait=True,
              meta={'shapes': ckpt.template_shapes(saved)})

    target_mesh = _mesh_n(n_restore)
    template = _state(target_mesh, scale=0.0)
    meta = ckpt.read_meta(mngr, 5)
    ckpt.validate_shapes(meta['shapes'], template)   # global: must pass
    restored, _ = ckpt.restore_with_meta(mngr, template, 5)
    flat_a, tree_a = jax.tree_util.tree_flatten(saved)
    flat_b, tree_b = jax.tree_util.tree_flatten(restored)
    assert tree_a == tree_b
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    w = restored['params']['w']
    assert w.sharding.is_equivalent_to(
        NamedSharding(target_mesh, P('dp')), w.ndim)


def test_validate_shapes_names_offending_leaf(tmp_path):
    """A GENUINE shape change (not a mesh change) raises BEFORE any
    array restore, naming the exact leaf and both global shapes."""
    mesh = _mesh_n(8)
    saved = _state(mesh)
    shapes = ckpt.template_shapes(saved)
    assert shapes['params/w'] == [16, 4]
    bad = _state(mesh)
    bad['params']['w'] = jax.device_put(
        jnp.zeros((8, 4), jnp.float32), NamedSharding(mesh, P('dp')))
    with pytest.raises(ValueError) as ei:
        ckpt.validate_shapes(shapes, bad)
    msg = str(ei.value)
    assert 'params/w' in msg and '(16, 4)' in msg and '(8, 4)' in msg
    # an added / removed leaf is named too
    missing = _state(mesh)
    del missing['opt']['mom']
    with pytest.raises(ValueError, match='opt/mom'):
        ckpt.validate_shapes(shapes, missing)


def test_read_meta_without_state_restore(tmp_path):
    mesh = _mesh_n(8)
    mngr = ckpt.manager(tmp_path, max_to_keep=3)
    state = _state(mesh)
    ckpt.save(mngr, 2, state, wait=True, meta={'mesh': {'devices': 8},
                                               'epoch': 1})
    meta = ckpt.read_meta(mngr, 2)
    assert meta == {'mesh': {'devices': 8}, 'epoch': 1}


def test_restore_state_without_meta_round_trip(tmp_path):
    """restore_state: the array half of a save-with-meta step, without
    re-reading the JSON sidecar (the resume path pairs it with
    read_meta — one restore round-trip each)."""
    mesh = _mesh_n(8)
    mngr = ckpt.manager(tmp_path, max_to_keep=3)
    state = _state(mesh, scale=2.0)
    ckpt.save(mngr, 3, state, wait=True, meta={'epoch': 0})
    restored = ckpt.restore_state(mngr, _state(mesh, scale=0.0), 3)
    np.testing.assert_array_equal(np.asarray(restored['params']['w']),
                                  np.asarray(state['params']['w']))
