"""Streaming ImageRecordIter (io/image_record.py).

Reference behaviors under test (src/io/iter_image_recordio_2.cc +
image_aug_default.cc + iter_prefetcher.h): per-image rand_crop /
rand_mirror (not per-batch), honored preprocess_threads, bounded
prefetch (dataset never resident), shuffle-is-permutation, round_batch
padding, num_parts sharding, and reproducibility under mx.random.seed.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio
from mxnet_tpu.recordio import MXRecordIO, IRHeader, pack_img
from mxnet_tpu.io.image_record import scan_record_offsets


def _write_rec(path, n, hw=12, seed=0, encode='.raw', labeler=None):
    rng = np.random.RandomState(seed)
    rec = MXRecordIO(path, 'w')
    imgs = []
    for i in range(n):
        img = (rng.rand(hw, hw, 3) * 255).astype(np.uint8)
        imgs.append(img)
        lab = float(labeler(i) if labeler else i % 7)
        rec.write(pack_img(IRHeader(0, lab, i, 0), img, img_fmt=encode))
    rec.close()
    return imgs


def test_offset_scan_counts_records(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 17)
    assert len(scan_record_offsets(p)) == 17


def test_sequential_batches_and_values(tmp_path):
    """No shuffle/augment: batches reproduce the packed pixels exactly
    (scale/mean/std applied)."""
    p = str(tmp_path / 'a.rec')
    imgs = _write_rec(p, 8, hw=6)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                             batch_size=4, scale=1.0 / 255)
    batches = list(it)
    assert len(batches) == 2
    got = batches[0].data[0].asnumpy()
    want = np.stack([im.transpose(2, 0, 1) for im in imgs[:4]]) / 255.0
    np.testing.assert_allclose(got, want, atol=1e-6)
    np.testing.assert_allclose(batches[0].label[0].asnumpy(),
                               [0, 1, 2, 3], atol=0)


def test_round_batch_pad_wraps(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 10, hw=6)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                             batch_size=4, round_batch=True)
    batches = list(it)
    assert [b.pad for b in batches] == [0, 0, 2]
    # padded tail wraps to the head records
    np.testing.assert_allclose(batches[2].label[0].asnumpy()[-2:], [0, 1])
    it2 = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                              batch_size=4, round_batch=False)
    assert len(list(it2)) == 2


def test_rand_mirror_is_per_image(tmp_path):
    """The round-3 gap: one coin per BATCH is wrong; each image flips
    independently (image_aug_default.cc). With 32 images the chance of
    a uniform batch is 2^-31."""
    p = str(tmp_path / 'a.rec')
    imgs = _write_rec(p, 32, hw=6)
    mx.random.seed(5)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                             batch_size=32, rand_mirror=True)
    got = next(iter(it)).data[0].asnumpy()
    flipped = []
    for i, im in enumerate(imgs):
        chw = im.transpose(2, 0, 1).astype(np.float32)
        if np.allclose(got[i], chw):
            flipped.append(False)
        elif np.allclose(got[i], chw[:, :, ::-1]):
            flipped.append(True)
        else:
            raise AssertionError('image %d is neither original nor '
                                 'mirrored' % i)
    assert any(flipped) and not all(flipped)


def test_rand_crop_is_per_image(tmp_path):
    """Each image draws its own crop offset: crops of a coordinate ramp
    differ across the batch."""
    p = str(tmp_path / 'a.rec')
    rec = MXRecordIO(p, 'w')
    ramp = np.tile(np.arange(16, dtype=np.uint8)[None, :, None] * 10,
                   (16, 1, 3))
    for i in range(16):
        rec.write(pack_img(IRHeader(0, float(i), i, 0), ramp,
                           img_fmt='.raw'))
    rec.close()
    mx.random.seed(11)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 8, 8),
                             batch_size=16, rand_crop=True)
    got = next(iter(it)).data[0].asnumpy()
    # the x-offset of each crop is its first column value / 10
    offs = {int(round(got[i, 0, 0, 0] / 10)) for i in range(16)}
    assert len(offs) > 1, 'all crops identical — per-batch, not per-image'
    # without rand_crop: center crop for every image
    it2 = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 8, 8),
                              batch_size=16)
    got2 = next(iter(it2)).data[0].asnumpy()
    assert {int(round(got2[i, 0, 0, 0] / 10)) for i in range(16)} == {4}


def test_shuffle_is_seeded_permutation(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 24, hw=6)
    mx.random.seed(3)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                             batch_size=8, shuffle=True)
    labs = np.concatenate([b.label[0].asnumpy() for b in it])
    full = np.arange(24) % 7
    assert sorted(labs.tolist()) == sorted(full.tolist())
    assert not np.array_equal(labs, full)   # actually shuffled
    mx.random.seed(3)
    it2 = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                              batch_size=8, shuffle=True)
    labs2 = np.concatenate([b.label[0].asnumpy() for b in it2])
    np.testing.assert_allclose(labs, labs2)   # seed-reproducible


def test_num_parts_sharding(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 12, hw=6, labeler=lambda i: i)
    seen = []
    for part in range(3):
        it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                                 batch_size=4, num_parts=3,
                                 part_index=part)
        seen.append(np.concatenate([b.label[0].asnumpy() for b in it]))
    allsee = sorted(np.concatenate(seen).tolist())
    assert allsee == list(range(12))
    assert seen[0].tolist() == [0, 3, 6, 9]


def test_reset_mid_epoch_and_reuse(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 16, hw=6, labeler=lambda i: i)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                             batch_size=4)
    next(it)
    it.reset()   # abandon a running producer mid-epoch
    labs = np.concatenate([b.label[0].asnumpy() for b in it])
    np.testing.assert_allclose(labs, np.arange(16))
    it.reset()
    assert len(list(it)) == 4


def test_preprocess_threads_honored_and_equal(tmp_path):
    """Thread count changes execution, not results."""
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 20, hw=6)
    outs = []
    for t in (1, 4):
        it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                                 batch_size=5, preprocess_threads=t)
        outs.append(np.concatenate([b.data[0].asnumpy() for b in it]))
    np.testing.assert_allclose(outs[0], outs[1])


def test_pad_and_fill_value(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 4, hw=6)
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 10, 10),
                             batch_size=4, pad=2, fill_value=9)
    got = next(iter(it)).data[0].asnumpy()
    assert got.shape == (4, 3, 10, 10)
    np.testing.assert_allclose(got[:, :, 0, 0], 9.0)   # padded corner


def test_unsupported_augmenter_warns_once(tmp_path):
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 4, hw=6)
    with pytest.warns(UserWarning, match='max_rotate_angle'):
        mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                            batch_size=4, max_rotate_angle=10)


def test_jpeg_stream(tmp_path):
    pytest.importorskip('PIL')
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 6, hw=8, encode='.jpg')
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 8, 8),
                             batch_size=3)
    bs = list(it)
    assert len(bs) == 2 and bs[0].data[0].shape == (3, 3, 8, 8)


def test_decode_error_surfaces_to_consumer(tmp_path):
    """A corrupt record raises in the consumer thread, not silently in
    the producer."""
    p = str(tmp_path / 'a.rec')
    rec = MXRecordIO(p, 'w')
    rec.write(b'not-an-image-record')
    rec.close()
    it = mio.ImageRecordIter(path_imgrec=p, data_shape=(3, 6, 6),
                             batch_size=1)
    with pytest.raises(Exception):
        next(it)


# ---- device-augment mode (round 5: feed the chip) -------------------------

def _iter_kw(hw, batch, **kw):
    base = dict(data_shape=(3, hw, hw), batch_size=batch,
                preprocess_threads=2, prefetch_buffer=2)
    base.update(kw)
    return base


def test_device_augment_matches_host_path_deterministic(tmp_path):
    """With randomness off, the device path (uint8 ship + on-device
    center crop / normalize) must produce the host path's exact
    values — same math, different execution site."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'a.rec')
    _write_rec(p, 8, hw=10)
    kw = dict(mean_r=11, mean_g=17, mean_b=23, std_r=2, std_g=3, std_b=4,
              scale=0.7, resize=8, label_name='l')
    host = mx.io.ImageRecordIter(
        p, **_iter_kw(6, 4, **kw), device_augment=0)
    dev = mx.io.ImageRecordIter(
        p, **_iter_kw(6, 4, **kw), device_augment=1)
    host.reset(); dev.reset()
    for _ in range(2):
        bh, bd = host.next(), dev.next()
        np.testing.assert_allclose(bd.data[0].asnumpy(),
                                   bh.data[0].asnumpy(),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(bd.label[0].asnumpy(),
                                      bh.label[0].asnumpy())
        assert bd.data[0].shape == (4, 3, 6, 6)
        assert str(bd.data[0].dtype) == 'float32'


def test_device_augment_rand_crop_mirror_properties(tmp_path):
    """Random crop/mirror on device: per-image variation, values drawn
    from the source image set, deterministic under mx.random.seed."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'b.rec')
    _write_rec(p, 16, hw=12)
    kw = _iter_kw(8, 8, rand_crop=1, rand_mirror=1, resize=12,
                  label_name='l')

    def run():
        mx.random.seed(5)
        it = mx.io.ImageRecordIter(p, **kw, device_augment=1)
        it.reset()
        return it.next().data[0].asnumpy()

    a, b = run(), run()
    np.testing.assert_array_equal(a, b)   # seeded determinism
    # different crops across a batch of distinct random images: the 8
    # outputs must not all be identical slices of one another
    assert a.shape == (8, 3, 8, 8)
    assert len({arr.tobytes() for arr in a}) > 1


def test_device_augment_raw_fixed_records_no_resize(tmp_path):
    """RAW0 fixed-size records need no host resize: uniform sizes pass
    straight through; a non-uniform file errors with guidance."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'c.rec')
    _write_rec(p, 8, hw=9)
    it = mx.io.ImageRecordIter(p, **_iter_kw(7, 4, label_name='l'),
                               device_augment=1)
    it.reset()
    b = it.next()
    assert b.data[0].shape == (4, 3, 7, 7)


def test_device_augment_feeds_module_fit(tmp_path):
    """End-to-end: ImageRecordIter(device_augment=1) drives Module.fit
    (the fused window when eligible) and the loss is finite."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'd.rec')
    _write_rec(p, 32, hw=10, labeler=lambda i: i % 4)
    it = mx.io.ImageRecordIter(
        p, **_iter_kw(8, 8, rand_crop=1, rand_mirror=1, resize=10,
                      label_name='softmax_label'), device_augment=1)
    data = mx.sym.Variable('data')
    net = mx.sym.Convolution(data, num_filter=4, kernel=(3, 3), name='c')
    net = mx.sym.Activation(net, act_type='relu')
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=4, name='fc')
    net = mx.sym.SoftmaxOutput(net, name='softmax')
    mod = mx.mod.Module(net, context=mx.cpu())
    accs = []
    mod.fit(it, num_epoch=2, optimizer='sgd',
            optimizer_params=(('learning_rate', 0.05),),
            eval_metric='acc',
            batch_end_callback=lambda prm: accs.append(
                prm.eval_metric.get_name_value()[0][1]))
    assert accs and all(np.isfinite(v) for v in accs)


def test_device_augment_nonsquare_and_undersized(tmp_path):
    """Non-square uniform records crop over each axis independently;
    undersized records are padded up to the crop like the host path."""
    import mxnet_tpu as mx
    from mxnet_tpu.recordio import MXRecordIO, IRHeader, pack_img
    # non-square 8x12 records, crop 7x7: x offsets must reach col 5
    p = str(tmp_path / 'ns.rec')
    rng = np.random.RandomState(0)
    rec = MXRecordIO(p, 'w')
    for i in range(16):
        img = (rng.rand(8, 12, 3) * 255).astype(np.uint8)
        rec.write(pack_img(IRHeader(0, float(i), i, 0), img,
                           img_fmt='.raw'))
    rec.close()
    mx.random.seed(3)
    it = mx.io.ImageRecordIter(p, **_iter_kw(7, 8, rand_crop=1,
                                             label_name='l'),
                               device_augment=1)
    it.reset()
    assert it.next().data[0].shape == (8, 3, 7, 7)

    # undersized 5x5 records, crop 7x7: padded with fill_value like the
    # host path (not an opaque dynamic_slice failure)
    q = str(tmp_path / 'small.rec')
    rec = MXRecordIO(q, 'w')
    for i in range(8):
        img = (rng.rand(5, 5, 3) * 255).astype(np.uint8)
        rec.write(pack_img(IRHeader(0, float(i), i, 0), img,
                           img_fmt='.raw'))
    rec.close()
    host = mx.io.ImageRecordIter(q, **_iter_kw(7, 4, label_name='l'),
                                 device_augment=0)
    dev = mx.io.ImageRecordIter(q, **_iter_kw(7, 4, label_name='l'),
                                device_augment=1)
    host.reset(); dev.reset()
    np.testing.assert_allclose(dev.next().data[0].asnumpy(),
                               host.next().data[0].asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_device_augment_grayscale_and_odd_parity_center_crop(tmp_path):
    """C=1 targets use only the first channel's mean/std (no 3-channel
    broadcast), and the composed host-square + device-center crop lands
    on the host path's exact pixels even at odd parities."""
    import mxnet_tpu as mx
    from mxnet_tpu.recordio import MXRecordIO, IRHeader, pack_img
    rng = np.random.RandomState(1)
    # odd-parity geometry: source 13x21 resized-short handled via
    # resize=13 -> S=13, crop 10: (21-13)//2=4 vs (21-10)//2 - (13-10)//2
    # = 5-1 = 4... pick sizes where naive differs: source h=13,w=20,
    # resize... use raw fixed-size path with resize set
    p = str(tmp_path / 'odd.rec')
    rec = MXRecordIO(p, 'w')
    for i in range(8):
        img = (rng.rand(15, 21, 3) * 255).astype(np.uint8)
        rec.write(pack_img(IRHeader(0, float(i), i, 0), img,
                           img_fmt='.raw'))
    rec.close()
    kw = dict(data_shape=(3, 10, 10), batch_size=4, preprocess_threads=2,
              prefetch_buffer=2, resize=13, mean_r=3, std_r=2,
              label_name='l')
    host = mx.io.ImageRecordIter(p, **kw, device_augment=0)
    dev = mx.io.ImageRecordIter(p, **kw, device_augment=1)
    host.reset(); dev.reset()
    np.testing.assert_allclose(dev.next().data[0].asnumpy(),
                               host.next().data[0].asnumpy(),
                               rtol=1e-5, atol=1e-5)

    # grayscale target: output must be (B, 1, H, W), matching host
    q = str(tmp_path / 'gray.rec')
    rec = MXRecordIO(q, 'w')
    for i in range(8):
        img = (rng.rand(9, 9, 3) * 255).astype(np.uint8)
        rec.write(pack_img(IRHeader(0, float(i), i, 0), img,
                           img_fmt='.raw'))
    rec.close()
    kw = dict(data_shape=(1, 8, 8), batch_size=4, preprocess_threads=2,
              prefetch_buffer=2, mean_r=7, std_r=3, label_name='l')
    host = mx.io.ImageRecordIter(q, **kw, device_augment=0)
    dev = mx.io.ImageRecordIter(q, **kw, device_augment=1)
    host.reset(); dev.reset()
    bh, bd = host.next(), dev.next()
    assert bd.data[0].shape == (4, 1, 8, 8)
    np.testing.assert_allclose(bd.data[0].asnumpy(), bh.data[0].asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_device_augment_spmd_fused_fit(tmp_path):
    """device_augment batches (device-resident f32) must stack and
    dp-shard correctly into the fused Module.fit window on a multi-
    device SPMD group, matching the host-augment path's training
    trajectory with randomness off."""
    import os
    import mxnet_tpu as mx
    from mxnet_tpu.module.executor_group import SPMDExecutorGroup
    from mxnet_tpu.module.fused_fit import FusedFitLoop

    p = str(tmp_path / 'spmd.rec')
    _write_rec(p, 64, hw=8, labeler=lambda i: i % 4)

    def run(device_augment):
        mx.random.seed(9)
        np.random.seed(9)
        it = mx.io.ImageRecordIter(
            p, **_iter_kw(8, 16, label_name='softmax_label'),
            device_augment=device_augment)
        data = mx.sym.Variable('data')
        net = mx.sym.Flatten(data)
        net = mx.sym.FullyConnected(net, num_hidden=4, name='fc')
        net = mx.sym.SoftmaxOutput(net, name='softmax')
        mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(8)])
        os.environ['MXTPU_FUSED_FIT'] = '1'
        try:
            mod.fit(it, num_epoch=2, optimizer='sgd',
                    optimizer_params=(('learning_rate', 0.1),),
                    kvstore='device', eval_metric='acc')
            # the behaviors under test must actually have engaged — a
            # silent eligibility fallback would test the reference loop
            assert isinstance(mod._exec_group, SPMDExecutorGroup)
            assert FusedFitLoop.build(
                mod, mx.metric.create('acc')) is not None
        finally:
            os.environ.pop('MXTPU_FUSED_FIT', None)
        return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    a_dev = run(1)
    a_host = run(0)
    for k in a_dev:
        np.testing.assert_allclose(a_dev[k], a_host[k], rtol=1e-5,
                                   atol=1e-5, err_msg=k)


def test_device_augment_deferred_into_fused_window(tmp_path):
    """When the fused fit loop drives a device-augment iterator, the
    augmentation is traced INSIDE the window program (defer mode: raw
    uint8 batches, zero per-batch aug dispatches — each eager dispatch
    costs ~65-85 ms of tunnel latency, docs/perf.md round-5). With
    randomness off the trajectory equals the unfused eager path
    exactly; tail batches (< window) materialize eagerly; the
    iterator's defer switch is always restored."""
    import os
    import mxnet_tpu as mx
    from mxnet_tpu.module.fused_fit import FusedFitLoop
    import mxnet_tpu.module.fused_fit as ff

    p = str(tmp_path / 'defer.rec')
    # 40 imgs / batch 4 = 10 batches: W=4 on cpu -> 2 windows + 2 tail
    _write_rec(p, 40, hw=8, labeler=lambda i: i % 4)

    def run(fused):
        mx.random.seed(11)
        np.random.seed(11)
        it = mx.io.ImageRecordIter(
            p, **_iter_kw(8, 4, label_name='softmax_label'),
            device_augment=1)
        data = mx.sym.Variable('data')
        net = mx.sym.Flatten(data)
        net = mx.sym.FullyConnected(net, num_hidden=4, name='fc')
        net = mx.sym.SoftmaxOutput(net, name='softmax')
        mod = mx.mod.Module(net, context=mx.cpu())
        os.environ['MXTPU_FUSED_FIT'] = '1' if fused else '0'
        try:
            mod.fit(it, num_epoch=2, optimizer='sgd',
                    optimizer_params=(('learning_rate', 0.1),),
                    kvstore='local', eval_metric='acc')
        finally:
            os.environ.pop('MXTPU_FUSED_FIT', None)
        return mod, it

    mod_f, it_f = run(True)
    # defer engaged: the cached loop compiled a defer-mode program
    _, loop = mod_f.__dict__['_fused_fit_cache']
    assert any(k[2] for k in loop._programs), list(loop._programs)
    # ...exactly one program across both epochs (reuse, no retrace)
    assert len(loop._programs) == 1
    # switch restored for other consumers of the iterator
    assert it_f._defer_aug is False
    # eager batches augment again after the fit (f32 CHW, not uint8)
    it_f.reset()
    b = next(iter(it_f))
    assert str(b.data[0].dtype) == 'float32'
    assert b.data[0].shape[1:] == (3, 8, 8)

    mod_u, _ = run(False)
    a_f = {k: v.asnumpy() for k, v in mod_f.get_params()[0].items()}
    a_u = {k: v.asnumpy() for k, v in mod_u.get_params()[0].items()}
    assert a_f.keys() == a_u.keys()
    for k in a_f:
        np.testing.assert_allclose(a_f[k], a_u[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_defer_program_keyed_by_aug_config(tmp_path):
    """Two device-augment iterators with EQUAL batch shapes but
    different normalization must not share a compiled defer window:
    the aug math is baked into the program, so the program key carries
    device_aug_signature()."""
    import os
    import mxnet_tpu as mx

    p = str(tmp_path / 'sig.rec')
    _write_rec(p, 32, hw=8, labeler=lambda i: i % 4)

    def build_mod():
        data = mx.sym.Variable('data')
        net = mx.sym.Flatten(data)
        net = mx.sym.FullyConnected(net, num_hidden=4, name='fc')
        net = mx.sym.SoftmaxOutput(net, name='softmax')
        return mx.mod.Module(net, context=mx.cpu())

    os.environ['MXTPU_FUSED_FIT'] = '1'
    try:
        mod = build_mod()
        kw = dict(_iter_kw(8, 8, label_name='softmax_label'),
                  device_augment=1)
        it_a = mx.io.ImageRecordIter(p, **kw)
        it_b = mx.io.ImageRecordIter(p, mean_r=100., std_r=7., **kw)
        assert it_a.device_aug_signature() != it_b.device_aug_signature()
        fit_kw = dict(optimizer='sgd',
                      optimizer_params=(('learning_rate', 0.1),),
                      kvstore='local', eval_metric='acc')
        mod.fit(it_a, num_epoch=1, **fit_kw)
        _, loop = mod.__dict__['_fused_fit_cache']
        assert len(loop._programs) == 1
        mod.fit(it_b, num_epoch=2, begin_epoch=1, **fit_kw)
        _, loop2 = mod.__dict__['_fused_fit_cache']
        assert loop2 is loop            # loop reused (module unchanged)
        assert len(loop._programs) == 2  # ...but a FRESH aug program
        keys = list(loop._programs)
        assert keys[0][2] != keys[1][2]
    finally:
        os.environ.pop('MXTPU_FUSED_FIT', None)


def test_host_crop_matches_device_crop_deterministic(tmp_path):
    """host_crop=1 (workers crop to HxW before handover — 23% fewer
    upload bytes for 224^2-from-256^2) must produce the device-crop
    path's exact values with randomness off: the center-crop formulas
    are shared, only the execution site moves."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'hc.rec')
    _write_rec(p, 8, hw=10)
    kw = dict(mean_r=3, mean_g=5, mean_b=7, std_r=2, std_g=3, std_b=4,
              scale=0.5, label_name='l')
    a = mx.io.ImageRecordIter(p, **_iter_kw(6, 4, **kw),
                              device_augment=1, host_crop=1)
    b = mx.io.ImageRecordIter(p, **_iter_kw(6, 4, **kw),
                              device_augment=1, host_crop=0)
    a.reset(); b.reset()
    for _ in range(2):
        ba, bb = a.next(), b.next()
        np.testing.assert_allclose(ba.data[0].asnumpy(),
                                   bb.data[0].asnumpy(),
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_array_equal(ba.label[0].asnumpy(),
                                      bb.label[0].asnumpy())


def test_host_crop_defer_ships_cropped_uint8(tmp_path):
    """In fused-fit defer mode a host-crop iterator hands over
    (B, H, W, C) uint8 — the crop already applied — and its
    device_aug_signature differs from the device-crop one, so the two
    modes never share a compiled window."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'hcd.rec')
    _write_rec(p, 16, hw=10)
    it = mx.io.ImageRecordIter(p, **_iter_kw(6, 4, label_name='l'),
                               device_augment=1, host_crop=1)
    it2 = mx.io.ImageRecordIter(p, **_iter_kw(6, 4, label_name='l'),
                                device_augment=1, host_crop=0)
    assert it.device_aug_signature() != it2.device_aug_signature()
    assert it.defer_device_aug(True)
    try:
        b = next(iter(it))
        d = b.data[0]
        assert d.shape == (4, 6, 6, 3), d.shape      # pre-cropped HWC
        assert str(d.dtype) == 'uint8'
        # the pure fn consumes the pre-cropped batch directly
        import jax
        out = jax.jit(it.device_aug_pure())(
            d.asnumpy(), jax.random.PRNGKey(0))
        assert out.shape == (4, 3, 6, 6)
    finally:
        it.defer_device_aug(False)


def test_host_crop_rand_crop_varies_and_is_seeded(tmp_path):
    """Random host crops: per-image variation within a batch,
    deterministic under mx.random.seed (offsets ride the producer's
    per-batch RandomState, like the host-augment path)."""
    import mxnet_tpu as mx
    p = str(tmp_path / 'hcr.rec')
    _write_rec(p, 16, hw=12)
    kw = _iter_kw(8, 8, rand_crop=1, rand_mirror=1, label_name='l')

    def run():
        mx.random.seed(5)
        it = mx.io.ImageRecordIter(p, **kw, device_augment=1,
                                   host_crop=1)
        it.reset()
        return it.next().data[0].asnumpy()

    a, b = run(), run()
    np.testing.assert_array_equal(a, b)
    assert a.shape == (8, 3, 8, 8)
    assert len({arr.tobytes() for arr in a}) > 1
