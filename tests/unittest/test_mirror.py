"""Gradient-memory tradeoff (remat) — MXTPU_BACKWARD_DO_MIRROR.

Reference: MXNET_BACKWARD_DO_MIRROR (graph_executor.cc:273-287) and the
memory/speed tradeoff documented in BASELINE.md. The XLA form is
jax.checkpoint over the traced forward; this asserts the semantics are
unchanged: loss and gradients bit-for-tol identical with mirroring on.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _bound_exec():
    data = mx.sym.Variable('data')
    w1 = mx.sym.Variable('w1')
    w2 = mx.sym.Variable('w2')
    h = mx.sym.Activation(mx.sym.dot(data, w1), act_type='tanh')
    out = mx.sym.sum(mx.sym.dot(h, w2) ** 2)
    rng = np.random.RandomState(0)
    args = {'data': mx.nd.array(rng.standard_normal((8, 16))),
            'w1': mx.nd.array(rng.standard_normal((16, 32)) * 0.1),
            'w2': mx.nd.array(rng.standard_normal((32, 4)) * 0.1)}
    grads = {n: mx.nd.zeros(a.shape) for n, a in args.items()}
    return out.bind(mx.cpu(), args=args, args_grad=grads, grad_req='write')


@pytest.mark.parametrize('mode', ['1', 'dots'])
def test_mirror_matches_plain(mode, monkeypatch):
    monkeypatch.delenv('MXTPU_BACKWARD_DO_MIRROR', raising=False)
    e0 = _bound_exec()
    e0.forward(is_train=True)
    e0.backward()
    out0 = e0.outputs[0].asnumpy()
    g0 = {n: g.asnumpy().copy() for n, g in e0.grad_dict.items()}

    monkeypatch.setenv('MXTPU_BACKWARD_DO_MIRROR', mode)
    e1 = _bound_exec()
    e1.forward(is_train=True)
    e1.backward()
    np.testing.assert_allclose(e1.outputs[0].asnumpy(), out0,
                               rtol=1e-6, atol=1e-6)
    for n, g in e1.grad_dict.items():
        np.testing.assert_allclose(g.asnumpy(), g0[n],
                                   rtol=1e-6, atol=1e-6, err_msg=n)


def test_mirror_gluon_hybrid(monkeypatch):
    from mxnet_tpu import gluon

    def run():
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize(mx.init.One())
        net.hybridize()
        x = mx.nd.array(np.arange(16, dtype='float32').reshape(2, 8))
        with mx.autograd.record():
            y = net(x)
            L = (y * y).sum()
        L.backward()
        # key by param-name suffix: the global name counter differs
        # between the two net instances (dense0_ vs dense1_)
        return (L.asnumpy(),
                {k.split('_', 1)[-1]: v.grad().asnumpy().copy()
                 for k, v in net.collect_params().items()})

    monkeypatch.delenv('MXTPU_BACKWARD_DO_MIRROR', raising=False)
    l0, g0 = run()
    monkeypatch.setenv('MXTPU_BACKWARD_DO_MIRROR', '1')
    l1, g1 = run()
    np.testing.assert_allclose(l1, l0, rtol=1e-6)
    for k in g0:
        np.testing.assert_allclose(g1[k], g0[k], rtol=1e-6, err_msg=k)
