"""Training-health sentinels (mxnet_tpu/telemetry/health).

Contracts under test:
- gating: MXTPU_HEALTH needs MXTPU_TELEMETRY; either off = true no-op
  (zero telemetry I/O, empty registry, byte-identical compiled
  programs — no is_finite in the lowered fwd+bwd);
- in-graph sentinels: an injected NaN is detected on BOTH the
  per-batch executor path and a mid-window fused-fit step, the latter
  with the exact window step index, and the first-bad-layer bisect
  names the offending symbol;
- MXTPU_HEALTH_ACTION: 'record' keeps training, 'raise' raises
  TrainingHealthError with the diagnostic attached;
- anomaly detectors: rolling median/MAD spike detection over loss /
  step-time streams, JSONL anomaly records, summary integration;
- satellites: Monitor.nan_watch preset + single-fetch stat_helper,
  the derived fit.input_bound_pct gauge, the "Run health" block.
"""
import json
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.config import flags
from mxnet_tpu.telemetry import export as tele_export
from mxnet_tpu.telemetry import health
from mxnet_tpu.telemetry.health import SpikeDetector, TrainingHealthError

_HEALTH_FLAGS = ('MXTPU_TELEMETRY', 'MXTPU_TELEMETRY_PATH', 'MXTPU_HEALTH',
                 'MXTPU_HEALTH_ACTION', 'MXTPU_HEALTH_K',
                 'MXTPU_HEALTH_WINDOW')


def _reload_flags():
    for f in _HEALTH_FLAGS:
        flags.reload(f)


@pytest.fixture
def health_path(tmp_path, monkeypatch):
    """Telemetry + health ON (action=record so injected NaNs don't
    raise), logging to a tmp JSONL; fully restored afterwards."""
    path = tmp_path / 'telemetry.jsonl'
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(path))
    monkeypatch.setenv('MXTPU_HEALTH', '1')
    monkeypatch.setenv('MXTPU_HEALTH_ACTION', 'record')
    _reload_flags()
    telemetry._reset_for_tests()
    yield path
    telemetry._reset_for_tests()
    for f in _HEALTH_FLAGS:
        monkeypatch.delenv(f, raising=False)
    _reload_flags()


@pytest.fixture
def all_off(monkeypatch):
    """Telemetry AND health decisively off."""
    for f in _HEALTH_FLAGS:
        monkeypatch.delenv(f, raising=False)
    _reload_flags()
    telemetry._reset_for_tests()
    yield
    telemetry._reset_for_tests()
    _reload_flags()


def _records(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _mlp_sym():
    data = mx.sym.Variable('data')
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name='fc1')
    act = mx.sym.Activation(fc1, act_type='relu')
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name='fc2')
    return mx.sym.SoftmaxOutput(fc2, name='softmax')


def _fit(X=None, y=None, arg_params=None, num_epoch=1, batch=8, n=32):
    np.random.seed(0)
    mx.random.seed(0)
    if X is None:
        X = np.random.randn(n, 10).astype(np.float32)
    if y is None:
        y = (np.random.rand(n) * 4).astype(int).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch,
                           label_name='softmax_label')
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.fit(it, num_epoch=num_epoch, optimizer='sgd',
            arg_params=arg_params, allow_missing=arg_params is not None,
            optimizer_params=(('learning_rate', 0.1),))
    return mod


def _nan_weight():
    np.random.seed(1)
    w = (np.random.randn(16, 10) * 0.1).astype(np.float32)
    w[0, 0] = np.nan
    return {'fc1_weight': mx.nd.array(w)}


# ---------------------------------------------------------------------------
# gating / zero-overhead no-op
# ---------------------------------------------------------------------------

def test_true_noop_without_telemetry(all_off, monkeypatch):
    """MXTPU_HEALTH=1 with telemetry OFF is a true no-op: no I/O, no
    registry writes, sentinels off."""
    monkeypatch.setenv('MXTPU_HEALTH', '1')
    _reload_flags()
    telemetry._reset_for_tests()
    io_before = tele_export._io_calls
    mod = _fit()
    assert not health.enabled()
    assert tele_export._io_calls == io_before
    assert telemetry.get_registry().names() == []
    assert mod._exec_group.execs[0]._health_on is False


def test_health_off_leaves_programs_byte_identical(tmp_path, monkeypatch):
    """With telemetry ON but MXTPU_HEALTH=0 the executor's fused
    fwd+bwd lowers WITHOUT any finite-check (the no-op contract is in
    the traced program, not just skipped host work); =1 adds it."""
    import jax.numpy as jnp
    from mxnet_tpu import random as _random

    def _lowered_text(health_on):
        telemetry._reset_for_tests()
        monkeypatch.setenv('MXTPU_TELEMETRY', '1')
        monkeypatch.setenv('MXTPU_TELEMETRY_PATH',
                           str(tmp_path / ('h%d.jsonl' % health_on)))
        monkeypatch.setenv('MXTPU_HEALTH', '1' if health_on else '0')
        _reload_flags()
        telemetry._reset_for_tests()
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.bind(data_shapes=[('data', (8, 10))],
                 label_shapes=[('softmax_label', (8,))])
        mod.init_params()
        ex = mod._exec_group.execs[0]
        assert ex._health_on is bool(health_on)
        arg_data = tuple(a._data for a in ex.arg_arrays)
        aux_data = tuple(a._data for a in ex.aux_arrays)
        heads = (jnp.ones((8, 4), jnp.float32),)
        return ex._fwd_bwd.lower(arg_data, aux_data, _random.next_key(),
                                 heads).as_text()

    try:
        assert 'is_finite' not in _lowered_text(False)
        assert 'is_finite' in _lowered_text(True)
    finally:
        telemetry._reset_for_tests()
        for f in _HEALTH_FLAGS:
            monkeypatch.delenv(f, raising=False)
        _reload_flags()


@pytest.mark.parametrize('health_on', ['0', '1'])
def test_fit_acceptance_on_off(health_on, tmp_path, monkeypatch):
    """Parametrized fit acceptance: =0 leaves no health trace anywhere;
    =1 counts every step through the sentinels and lands the Run
    health block in the summary."""
    path = tmp_path / 'onoff.jsonl'
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(path))
    monkeypatch.setenv('MXTPU_HEALTH', health_on)
    _reload_flags()
    telemetry._reset_for_tests()
    try:
        _fit()
        snap = telemetry.snapshot()
        health_names = [n for n in telemetry.get_registry().names()
                        if n.startswith('health.')]
        if health_on == '0':
            assert health_names == []
            assert health.snapshot_health() is None
            table = telemetry.write_summary(log=False)
            assert '-- run health --' not in table
        else:
            assert snap['counters']['health.steps'] == 4
            assert snap['counters'].get('health.nonfinite_steps', 0) == 0
            table = telemetry.write_summary(log=False)
            assert '-- run health --' in table
            assert 'status            ok' in table
            telemetry.shutdown()
            summ = [r for r in _records(path) if r['type'] == 'summary'][-1]
            assert summ['health']['nonfinite_steps'] == 0
    finally:
        telemetry._reset_for_tests()
        for f in _HEALTH_FLAGS:
            monkeypatch.delenv(f, raising=False)
        _reload_flags()


# ---------------------------------------------------------------------------
# injected-NaN detection + first-bad-layer bisect
# ---------------------------------------------------------------------------

def test_nan_detected_per_batch_executor_path(health_path, monkeypatch):
    """Reference per-batch loop: a poisoned weight trips the in-graph
    sentinel on the first step and the bisect names the weight."""
    monkeypatch.setenv('MXTPU_FUSED_FIT', '0')
    _fit(arg_params=_nan_weight())
    reg = telemetry.get_registry()
    assert reg.counter('health.nonfinite_steps').value >= 1
    hs = health.snapshot_health()
    inc = hs['incidents'][0]
    assert inc['source'] == 'executor'
    assert inc['first_bad_layer'] == 'fc1_weight'
    assert inc['outputs_nonfinite'] == [0]
    telemetry.shutdown()
    recs = _records(health_path)
    assert any(r['type'] == 'health' and r.get('event') == 'nonfinite'
               for r in recs)


def test_nan_detected_mid_window_fused_fit(health_path):
    """A NaN batch in the middle of a fused-fit window is attributed to
    its exact window step through the window's single fetch, and the
    bisect (replaying the snapshotted batch) names the bad input."""
    np.random.seed(0)
    X = np.random.randn(32, 10).astype(np.float32)
    X[16:24] = np.nan        # batch index 2 of the W=4 window
    _fit(X=X)
    reg = telemetry.get_registry()
    assert reg.counter('fused_fit.windows').value >= 1   # fused path ran
    # steps 2 AND 3 are bad (params carry the NaN forward): the counter
    # is per STEP — same semantics as the per-batch path — while the
    # window reports ONE incident
    assert reg.counter('health.nonfinite_steps').value == 2
    hs = health.snapshot_health()
    inc = hs['incidents'][0]
    assert inc['source'] == 'fused_fit'
    assert inc['window_step'] == 2
    assert inc['step'] == 2
    assert inc['first_bad_layer'] == 'data'
    # steps 2 and 3 are both poisoned (params carry the NaN forward);
    # ONE incident, counting the window's bad steps
    assert inc['nonfinite_steps_in_window'] == 2
    telemetry.shutdown()
    recs = _records(health_path)
    hrec = next(r for r in recs if r['type'] == 'health')
    assert hrec['window_step'] == 2


def test_nan_detected_fused_eval(health_path):
    """The fused eval window carries per-step finite flags too."""
    np.random.seed(0)
    X = np.random.randn(32, 10).astype(np.float32)
    X[9] = np.inf            # batch index 1
    y = np.zeros((32,), np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8, label_name='softmax_label')
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.score(it, 'acc')
    hs = health.snapshot_health()
    inc = hs['incidents'][0]
    assert inc['source'] == 'fused_eval'
    assert inc['window_step'] == 1
    assert inc['first_bad_layer'] == 'data'


def test_eval_window_does_not_feed_grad_detector(health_path):
    """A fused eval pass (forward only: the norm slots are
    structurally zero) must not flush the TRAINING grad-norm baseline
    or zero the norm gauges."""
    mod = _fit()                     # trains: gauges set, detector fed
    reg = telemetry.get_registry()
    g = reg.gauge('health.grad_norm').value
    assert g > 0
    n_vals = len(health.detector('grad_norm')._vals)
    assert n_vals > 0
    np.random.seed(0)
    X = np.random.randn(32, 10).astype(np.float32)
    y = np.zeros((32,), np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8, label_name='softmax_label')
    mod.score(it, 'acc')
    assert reg.counter('fused_eval.windows').value >= 1   # fused path ran
    assert reg.gauge('health.grad_norm').value == g
    assert len(health.detector('grad_norm')._vals) == n_vals


def test_raise_action_attaches_diagnostic(health_path, monkeypatch):
    """MXTPU_HEALTH_ACTION=raise fails fast with the structured
    diagnostic attached to the exception."""
    monkeypatch.setenv('MXTPU_HEALTH_ACTION', 'raise')
    monkeypatch.setenv('MXTPU_FUSED_FIT', '0')
    flags.reload('MXTPU_HEALTH_ACTION')
    telemetry._reset_for_tests()
    with pytest.raises(TrainingHealthError) as ei:
        _fit(arg_params=_nan_weight())
    d = ei.value.diagnostic
    assert d['source'] == 'executor'
    assert d['first_bad_layer'] == 'fc1_weight'
    assert 'fc1_weight' in str(ei.value)


def test_first_nonfinite_node_clean_graph(health_path):
    """The bisect returns None on a healthy graph and respects
    overrides (a NaN override is attributed to its variable)."""
    import jax.numpy as jnp
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[('data', (8, 10))],
             label_shapes=[('softmax_label', (8,))])
    mod.init_params()
    ex = mod._exec_group.execs[0]
    assert ex.first_nonfinite_node() is None
    bad = jnp.full((8, 10), jnp.nan, jnp.float32)
    assert ex.first_nonfinite_node({'data': bad}) == ('data', 0)


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------

def test_spike_detector_flags_spike():
    d = SpikeDetector('t', window=16, k=5.0, min_count=8)
    rng = np.random.RandomState(0)
    for _ in range(12):
        assert d.observe(100.0 + rng.randn()) is None
    a = d.observe(500.0)
    assert a is not None
    assert a['detector'] == 't'
    assert a['value'] == 500.0
    assert 95 < a['baseline'] < 105
    assert a['k'] == 5.0


def test_spike_detector_constant_baseline_needs_real_spike():
    """A near-constant stream (MAD ~ 0) must not alarm on noise — the
    MAD floor (1% of the median) absorbs it."""
    d = SpikeDetector('t', window=16, k=5.0, min_count=8)
    for _ in range(12):
        d.observe(100.0)
    assert d.observe(100.5) is None          # within the floor
    assert d.observe(200.0) is not None      # a real spike


def test_spike_detector_level_shift_becomes_new_baseline():
    d = SpikeDetector('t', window=8, k=5.0, min_count=4)
    for _ in range(8):
        d.observe(10.0)
    assert d.observe(100.0) is not None      # the shift alarms once
    for _ in range(8):
        d.observe(100.0)                     # ...then becomes normal
    assert d.observe(101.0) is None


def test_spike_detector_ignores_nonfinite():
    d = SpikeDetector('t', window=8, k=5.0, min_count=4)
    for _ in range(6):
        d.observe(10.0)
    assert d.observe(float('nan')) is None
    assert d.observe(float('inf')) is None


def test_loss_and_step_time_detectors_emit_anomalies(health_path, caplog):
    """note_loss / note_step_time feed the registry detectors; a spike
    lands a JSONL anomaly record, counters, and the last-anomaly slot."""
    assert health.enabled()
    for _ in range(12):
        health.note_loss(2.0)
        health.note_step_time(0.1)
    with caplog.at_level(logging.WARNING):
        health.note_loss(2.0)            # steady: no anomaly
        health.note_loss(50.0)           # spike
        health.note_step_time(5.0)       # spike (5000 ms vs 100 ms)
    reg = telemetry.get_registry()
    assert reg.counter('health.anomalies').value == 2
    assert reg.counter('health.anomalies.loss').value == 1
    assert reg.counter('health.anomalies.step_time').value == 1
    hs = health.snapshot_health()
    assert hs['anomaly_counts'] == {'loss': 1, 'step_time': 1}
    assert hs['last_anomaly']['detector'] == 'step_time'
    telemetry.shutdown()
    recs = _records(health_path)
    anomalies = [r for r in recs if r['type'] == 'anomaly']
    assert {a['detector'] for a in anomalies} == {'loss', 'step_time'}
    # record action (the fixture's): spikes stay out of the warnings
    assert not [r for r in caplog.records if 'spike' in r.getMessage()]


def test_grad_norm_gauges_and_detector_fed_from_fit(health_path):
    """A clean fit publishes the sentinel gauges."""
    _fit()
    snap = telemetry.snapshot()
    assert snap['gauges']['health.grad_norm'] > 0
    assert snap['gauges']['health.param_norm'] > 0
    assert snap['gauges']['health.update_ratio'] > 0
    assert snap['gauges']['health.step_time_ms'] > 0


# ---------------------------------------------------------------------------
# input-bound classifier + summary integration
# ---------------------------------------------------------------------------

def test_input_bound_pct_gauge_and_classifier(health_path, caplog):
    reg = telemetry.get_registry()
    for _ in range(4):
        reg.histogram('io.prefetch_wait').observe(50.0)
        reg.histogram('fit.batch').observe(100.0)
    with caplog.at_level(logging.WARNING):
        hs = health.summarize()
    assert reg.gauge('fit.input_bound_pct').value == 50.0
    assert hs['input_bound_pct'] == 50.0
    assert [r for r in caplog.records
            if 'input-bound' in r.getMessage()]
    telemetry.shutdown()
    recs = _records(health_path)
    assert any(r['type'] == 'health' and r.get('event') == 'input_bound'
               for r in recs)


def test_input_bound_pct_without_health(tmp_path, monkeypatch):
    """The derived gauge is telemetry-tier: published even when
    MXTPU_HEALTH is off (no classifier record then)."""
    monkeypatch.setenv('MXTPU_TELEMETRY', '1')
    monkeypatch.setenv('MXTPU_TELEMETRY_PATH', str(tmp_path / 'o.jsonl'))
    _reload_flags()
    telemetry._reset_for_tests()
    try:
        reg = telemetry.get_registry()
        assert telemetry.enabled()
        reg.histogram('io.prefetch_wait').observe(10.0)
        reg.histogram('fit.batch').observe(100.0)
        assert health.summarize() is None     # health off: no snapshot
        assert reg.gauge('fit.input_bound_pct').value == 10.0
    finally:
        telemetry._reset_for_tests()
        for f in _HEALTH_FLAGS:
            monkeypatch.delenv(f, raising=False)
        _reload_flags()


def test_run_health_block_renders_incident(health_path):
    np.random.seed(0)
    X = np.random.randn(32, 10).astype(np.float32)
    X[16:24] = np.nan
    _fit(X=X)
    table = telemetry.write_summary(log=False)
    assert '-- run health --' in table
    assert 'DEGRADED (2 non-finite steps)' in table
    assert 'first non-finite symbol data' in table
    assert 'window step 2' in table


# ---------------------------------------------------------------------------
# Monitor satellites
# ---------------------------------------------------------------------------

def test_monitor_nan_watch_flags_bad_tensor(all_off):
    """The nan_watch preset (staged executor path) reports per-op
    finite status built on the same host finite check."""
    mon = mx.mon.Monitor.nan_watch(interval=1)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=[('data', (4, 10))],
             label_shapes=[('softmax_label', (4,))])
    mod.init_params()
    mod.install_monitor(mon)
    X = np.ones((4, 10), np.float32)
    X[0, 0] = np.nan
    batch = mx.io.DataBatch(data=[mx.nd.array(X)],
                            label=[mx.nd.zeros((4,))])
    mon.tic()
    mod.forward(batch, is_train=False)
    rows = mon.toc()
    stats = {name: stat for _, name, stat in rows}
    assert stats['fc1_output'].startswith('nan=')
    assert any(v.startswith('ok') for v in stats.values())


def test_monitor_single_fetch_shared_across_stat_funcs(all_off):
    """stat_helper fetches each matched array once; every stat func
    reads the same host-resident copy."""
    seen = []

    def f1(x):
        seen.append(x)
        return 'a'

    def f2(x):
        seen.append(x)
        return 'b'

    mon = mx.mon.Monitor(1, stat_func=[f1, f2])
    mon.activated = True
    mon.stat_helper('x_output', mx.nd.ones((2, 2)))
    assert len(seen) == 2
    assert seen[0] is seen[1]                 # one fetch, shared
    assert [r.stat for r in mon.queue] == ['a', 'b']
    # the shared copy is host-resident but keeps the NDArray API
    assert float(seen[0].norm().asscalar()) == pytest.approx(2.0)


def test_monitor_legacy_single_stat_func_unchanged(all_off):
    mon = mx.mon.Monitor(1)
    mon.activated = True
    mon.stat_helper('w_output', mx.nd.ones((2, 2)))
    assert len(mon.queue) == 1
    assert float(mon.queue[0].stat) == pytest.approx(1.0)


def test_finite_report_strings():
    from mxnet_tpu.telemetry.health import finite_report, has_nonfinite
    assert finite_report(np.ones((4,))) == 'ok'
    assert finite_report(np.zeros((0,))) == 'ok'
    assert finite_report(np.arange(5)) == 'ok'         # ints always ok
    a = np.ones((8,), np.float32)
    a[1] = np.nan
    a[2] = np.inf
    assert finite_report(a) == 'nan=1 inf=1 of 8'
    assert has_nonfinite(a)
    assert not has_nonfinite(np.ones((3,)))
